# Pre-commit gate (round-1 post-mortem: HEAD shipped with a SyntaxError
# because nothing ran before the final commit). `make check` MUST pass
# before every commit.

PY ?= python
ASAN_RT := $(shell g++ -print-file-name=libasan.so 2>/dev/null)

.PHONY: check ci import-check lint lock-order test bench-smoke bench-check native native-asan chaos loadcheck

check: import-check lint test native-asan bench-smoke
	@echo "CHECK OK"

# pre-merge gate (docs/static-analysis.md): gofrlint + shardcheck over the
# tree, the analyzer's own fixture suites, the fixed-seed chaos tier
# (docs/robustness.md), then the full tier-1 pytest run. The fixture suites
# DO run again inside tier-1; the explicit first pass is a deliberate
# fail-fast — a broken analyzer surfaces in ~30 s, not after the ~15 min
# full suite.
ci: lint bench-check
	$(PY) -m gofr_tpu.analysis --chaos-coverage
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_analysis.py tests/test_shardcheck.py tests/test_lockcheck.py tests/test_leakcheck.py tests/test_deadlinecheck.py tests/test_deadlinetrace.py tests/test_kernelcheck.py tests/test_kerneltrace.py -q -m 'not slow' \
	  --deselect tests/test_lockcheck.py::test_runtime_graph_is_subgraph_of_static \
	  --deselect tests/test_leakcheck.py::test_runtime_pairs_covered_by_static_table \
	  --deselect tests/test_deadlinetrace.py::test_runtime_crossings_covered_by_static_table \
	  --deselect tests/test_deadlinetrace.py::test_lora_acquire_timeout_clamped_to_request_deadline \
	  --deselect tests/test_kerneltrace.py::test_observer_live_engine_matches_contract_table
	$(MAKE) chaos
	$(MAKE) loadcheck
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
	@echo "CI OK"

# chaos tier (docs/robustness.md): the serving/engine suites under
# FIXED-SEED fault schedules at every registered injection point, asserting
# the request-lifecycle invariant — every submitted request reaches exactly
# one terminal state with its slot + KV pages reclaimed, and the engine
# thread exits cleanly — plus the engine-supervision invariant (an injected
# engine.step hang/crash or device.loss poisoning is detected by the
# watchdog, warm-restarted under budget, queued requests survive the
# restart, a budget-exhausted engine parks WEDGED instead of flapping;
# tests/test_supervisor.py), plus the pubsub delivery invariant (every
# published message handled-and-committed or dead-lettered with history;
# never lost, never looping) over the memory + kafka-wire drivers.
# Deterministic: a red run reproduces with the same seed every time (seeds
# live in tests/test_chaos.py::CHAOS_SEEDS,
# tests/test_supervisor.py::CHAOS_SEEDS,
# tests/test_pubsub_chaos.py::CHAOS_SEEDS,
# tests/test_router_chaos.py::CHAOS_SEEDS and
# tests/test_disagg.py::CHAOS_SEEDS), plus the router-plane replica
# tier (kill / wedge / heartbeat-partition over ≥2 in-process replicas,
# asserting exactly-one-terminal-state-on-exactly-one-replica) and the
# disaggregation plane (handoff-interrupted seeds: source death,
# destination death, kv.handoff transport faults; autoscaler scale-down
# drains under scale.decision faults), and the goodput-under-load tier
# (docs/robustness.md "Goodput under production load"): the full stack
# replays a seeded production trace while a wall-clock FaultSchedule
# fires a mid-run replica kill + tenant storm + heartbeat partition,
# asserting zero lost requests, exactly-one terminal per request, and
# interactive-class goodput strictly above batch inside the fault
# window (seeds in tests/test_loadlab.py::CHAOS_SEEDS), and the HA
# plane (docs/robustness.md "The HA plane"): router death mid-stream
# with a keyed Last-Event-ID re-attach on the survivor router
# (token-identical suffix), duplicate keyed submits across a two-router
# split brain (exactly one admission tier-wide), and stale-epoch
# fencing at the engine wire, under router.claim / stream.resume fault
# schedules (seeds in tests/test_ha.py::CHAOS_SEEDS).
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py tests/test_supervisor.py tests/test_pubsub_chaos.py tests/test_router_chaos.py tests/test_disagg.py tests/test_loadlab.py tests/test_reclaim.py tests/test_ha.py -q -m chaos

# goodput ratchet gate (docs/robustness.md, docs/performance.md#bench-ratchet):
# one deterministic chaos-under-load trace (seed 101) through the full
# stack via bench.py --loadlab, then the floor check — goodput under
# chaos (direction max) plus TTFT/e2e p99 ceilings must stay inside
# analysis/bench_floors.json.
loadcheck:
	JAX_PLATFORMS=cpu $(PY) bench.py --loadlab
	$(PY) bench.py --check

# gofrlint (docs/static-analysis.md): the unified front door — the
# framework-invariant AST lints, the shardcheck SPMD family, the
# lockcheck concurrency families, the leakcheck resource-lifecycle
# families, the extern-C vs ctypes FFI signature cross-check, AND the
# stale-suppression audit, in ONE shared SourceFile walk with one
# baseline load (`--format sarif` emits SARIF 2.1.0 for CI annotation).
# Exits non-zero on any unsuppressed finding — or when the unified pass
# blows its wall-clock budget: the lint gate is the pre-commit fast
# path, and an analyzer that quietly grows past $(LINT_BUDGET_S)s stops
# being one (a new whole-program family must pay for itself in the
# shared walk, not with a second tree scan).
LINT_BUDGET_S ?= 30
lint:
	@start=$$(date +%s); \
	$(PY) -m gofr_tpu.analysis --all || exit $$?; \
	end=$$(date +%s); elapsed=$$((end - start)); \
	if [ $$elapsed -gt $(LINT_BUDGET_S) ]; then \
	  echo "lint: unified pass took $${elapsed}s, over the $(LINT_BUDGET_S)s budget" >&2; \
	  exit 1; \
	fi; \
	echo "lint: unified pass in $${elapsed}s (budget $(LINT_BUDGET_S)s)"

# lock-order tier: run the concurrency tests with every Python lock
# instrumented; any cyclic acquisition order (potential deadlock) fails.
# The observed acquisition graph is exported for the static cross-check
# (docs/static-analysis.md "Static ↔ runtime cross-check"): every
# runtime edge must already be in `python -m gofr_tpu.analysis
# --lock-graph`'s static graph.
lock-order:
	GOFR_LOCK_ORDER=1 GOFR_LOCK_ORDER_EXPORT=$(CURDIR)/.gofr_lock_graph.json \
	JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_native_concurrency.py tests/test_engine_recovery.py -q -x
	$(PY) -m gofr_tpu.analysis --check-lock-graph $(CURDIR)/.gofr_lock_graph.json

import-check:
	$(PY) -c "import compileall,sys; sys.exit(0 if compileall.compile_dir('gofr_tpu', quiet=2) else 1)"
	$(PY) -c "import gofr_tpu; import __graft_entry__; print('import ok')"

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -x

bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py

# ratcheted perf gate (docs/performance.md#bench-ratchet): committed
# bench records must stay above the floors in analysis/bench_floors.json.
# Pure JSONL comparison — no jax import, no TPU; a real TPU bench run
# appends evidence to BENCH_LOCAL.jsonl and `bench.py --update-floors`
# ratchets the floors up.
bench-check:
	$(PY) bench.py --check

native:
	$(MAKE) -C native

# sanitizer tier for the C++ layer (SURVEY §5.2, VERDICT r2 item 8): the
# same native tests run against ASan+UBSan builds of gofr_runtime.cc /
# pjrt_dl.cc / stub_plugin.cc. The loader rebuilds with the extra flags
# into distinct cache entries; libasan must be preloaded before python.
native-asan:
	GOFR_NATIVE_EXTRA_CXXFLAGS="-fsanitize=address,undefined -fno-sanitize-recover=all -g -O1" \
	GOFR_PJRT_INCLUDE_DIRS="$$($(PY) -c 'from gofr_tpu.native import pjrt_include_dirs; print(":".join(pjrt_include_dirs()))')" \
	LD_PRELOAD=$(ASAN_RT) \
	ASAN_OPTIONS="detect_leaks=0 suppressions=native/asan.supp" \
	UBSAN_OPTIONS="print_stacktrace=1" \
	JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_native_runtime.py tests/test_native_pjrt.py -q -x

# regenerate the committed descriptor sets for the built-in services
protos:
	cd gofr_tpu/grpcx/protos && \
	protoc -I. --descriptor_set_out=reflection.binpb reflection.proto && \
	protoc -I. --descriptor_set_out=health.binpb health.proto
	cd gofr_tpu/datasource/pubsub/protos && \
	protoc -I. --descriptor_set_out=pubsub_v1.binpb pubsub_v1.proto
	python -m gofr_tpu.grpcx.codegen gofr_tpu/distributed/coordination.proto \
	  -o gofr_tpu/distributed/

# thread-sanitizer tier (SURVEY §5.2, VERDICT r4 item 9): the allocator/
# scheduler concurrency stress AND the PJRT binding (pjrt_dl.cc +
# stub_plugin.cc, rebuilt with -fsanitize=thread through the loader's
# GOFR_NATIVE_EXTRA_CXXFLAGS hook) run against TSan builds — any data race
# in the C++ layer becomes a hard failure. GOFR_PJRT_INCLUDE_DIRS skips
# the tensorflow import (same reason as native-asan).
TSAN_RT := $(shell g++ -print-file-name=libtsan.so 2>/dev/null)

.PHONY: native-tsan
native-tsan:
	GOFR_NATIVE_EXTRA_CXXFLAGS="-fsanitize=thread -g -O1" \
	GOFR_PJRT_INCLUDE_DIRS="$$($(PY) -c 'from gofr_tpu.native import pjrt_include_dirs; print(":".join(pjrt_include_dirs()))')" \
	LD_PRELOAD=$(TSAN_RT) \
	TSAN_OPTIONS="halt_on_error=1 suppressions=native/tsan.supp" \
	JAX_PLATFORMS=cpu \
	$(PY) -m pytest tests/test_native_concurrency.py tests/test_native_runtime.py tests/test_native_pjrt.py -q -x

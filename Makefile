# Pre-commit gate (round-1 post-mortem: HEAD shipped with a SyntaxError
# because nothing ran before the final commit). `make check` MUST pass
# before every commit.

PY ?= python

.PHONY: check import-check test bench-smoke native

check: import-check test bench-smoke
	@echo "CHECK OK"

import-check:
	$(PY) -c "import compileall,sys; sys.exit(0 if compileall.compile_dir('gofr_tpu', quiet=2) else 1)"
	$(PY) -c "import gofr_tpu; import __graft_entry__; print('import ok')"

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -x

bench-smoke:
	JAX_PLATFORMS=cpu $(PY) bench.py

native:
	$(MAKE) -C native

# regenerate the committed descriptor sets for the built-in services
protos:
	cd gofr_tpu/grpcx/protos && \
	protoc -I. --descriptor_set_out=reflection.binpb reflection.proto && \
	protoc -I. --descriptor_set_out=health.binpb health.proto

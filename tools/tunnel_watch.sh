#!/bin/bash
LOG=/root/repo/TUNNEL_WATCH.log
prev=unknown
while true; do
  if timeout 90 python -c "import jax; assert jax.devices()[0].platform=='tpu'" >/dev/null 2>&1; then
    cur=up
  else
    cur=down
  fi
  if [ "$cur" != "$prev" ]; then
    echo "$(date -u +%FT%TZ) tunnel=$cur" >> "$LOG"
    prev=$cur
  fi
  sleep 300
done

"""Loader for the native C++ runtime (``native/`` at the repo root).

The native layer plays the role SURVEY.md §2.9 assigns to native code in a
TPU stack: host-side serving bookkeeping (paged KV block allocator,
admission scheduler — ``native/runtime/gofr_runtime.cc``) and the PJRT
C-API binding (``native/pjrt/pjrt_dl.cc``). Python talks to it over a
plain C ABI via ctypes (no pybind11 in the image).

Build model: shared objects are compiled on first use with ``g++`` into
``native/_build/`` and re-used while their source hash matches (the
"compile-or-load executable cache" idea of SURVEY §5.4 applied to our own
native code). When no compiler is available the callers fall back to the
pure-Python implementations in :mod:`gofr_tpu.native.fallback`.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_BUILD_DIR = os.path.join(_NATIVE_DIR, "_build")

_lock = threading.Lock()
_cache: dict[str, ctypes.CDLL | None] = {}

GOFR_OK = 0
GOFR_E_BADHANDLE = -1
GOFR_E_NOMEM = -2
GOFR_E_NOTFOUND = -3
GOFR_E_EXISTS = -4
GOFR_E_QUEUEFULL = -5
GOFR_E_ARG = -6
GOFR_E_CAP = -7

ERROR_NAMES = {
    GOFR_E_BADHANDLE: "bad handle",
    GOFR_E_NOMEM: "out of KV blocks",
    GOFR_E_NOTFOUND: "not found",
    GOFR_E_EXISTS: "already exists",
    GOFR_E_QUEUEFULL: "queue full",
    GOFR_E_ARG: "bad argument",
    GOFR_E_CAP: "buffer too small",
}


class NativeError(RuntimeError):
    def __init__(self, code: int, what: str = "") -> None:
        self.code = code
        super().__init__(f"{what}: {ERROR_NAMES.get(code, code)}" if what else str(code))


def _source_hash(*paths: str) -> str:
    h = hashlib.sha256()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def pjrt_include_dirs() -> list[str]:
    """Locate the PJRT C API headers (shipped in the image's tensorflow).
    ``GOFR_PJRT_INCLUDE_DIRS`` (colon-separated) short-circuits the
    tensorflow import — required under the ASan tier, where importing
    TF's pybind11 dependency chain trips the sanitizer's exception
    interceptor before our code even runs."""
    env = os.environ.get("GOFR_PJRT_INCLUDE_DIRS")
    if env:
        return [d for d in env.split(":") if d]
    dirs = []
    try:
        import tensorflow  # noqa: F401  (cpu wheel, only used for headers)

        tf_inc = os.path.join(os.path.dirname(tensorflow.__file__), "include")
        if os.path.exists(os.path.join(tf_inc, "xla/pjrt/c/pjrt_c_api.h")):
            dirs.append(tf_inc)
    except Exception:
        pass
    return dirs


def build_library(name: str, sources: list[str], extra_flags: list[str] | None = None,
                  libs: list[str] | None = None) -> str | None:
    """Compile `sources` (relative to native/) into _build/<name>-<hash>.so.

    Returns the path, or None if the toolchain is unavailable or the
    compile fails (callers fall back to Python implementations).
    """
    srcs = [os.path.join(_NATIVE_DIR, s) for s in sources]
    if not all(os.path.exists(s) for s in srcs):
        return None
    # sanitizer tier (SURVEY §5.2): GOFR_NATIVE_EXTRA_CXXFLAGS joins the
    # build AND the cache tag, so asan and release artifacts never collide
    env_flags = os.environ.get("GOFR_NATIVE_EXTRA_CXXFLAGS", "").split()
    tag = _source_hash(*srcs)
    if env_flags:
        tag += "-" + hashlib.sha256(" ".join(env_flags).encode()).hexdigest()[:8]
    out = os.path.join(_BUILD_DIR, f"{name}-{tag}.so")
    if os.path.exists(out):
        return out
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        os.environ.get("CXX", "g++"),
        "-O2", "-fPIC", "-std=c++17", "-shared", "-fvisibility=hidden",
        *(extra_flags or []),
        *env_flags,
        "-o", out + ".tmp", *srcs, *(libs or []),
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(out + ".tmp", out)
    except Exception:
        return None
    return out


def _load(name: str, sources: list[str], extra_flags: list[str] | None = None,
          libs: list[str] | None = None) -> ctypes.CDLL | None:
    with _lock:
        if name in _cache:
            return _cache[name]
        path = build_library(name, sources, extra_flags, libs)
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
            except OSError:
                lib = None
        _cache[name] = lib
        return lib


def load_runtime() -> ctypes.CDLL | None:
    """The serving runtime: block allocator + scheduler. None if unbuildable."""
    lib = _load("libgofr_runtime", ["runtime/gofr_runtime.cc"])
    if lib is not None and not getattr(lib, "_gofr_typed", False):
        _declare_runtime(lib)
        lib._gofr_typed = True
    return lib


def load_pjrt() -> ctypes.CDLL | None:
    """The PJRT C-API binding. None if headers/toolchain unavailable."""
    incs = pjrt_include_dirs()
    if not incs:
        return None
    flags = [f"-I{d}" for d in incs]
    lib = _load("libgofr_pjrt", ["pjrt/pjrt_dl.cc"], flags, ["-ldl"])
    if lib is not None and not getattr(lib, "_gofr_typed", False):
        _declare_pjrt(lib)
        lib._gofr_typed = True
    return lib


def build_stub_plugin() -> str | None:
    """Build the test-only stub PJRT plugin (SURVEY §4: fake PJRT rig)."""
    incs = pjrt_include_dirs()
    if not incs:
        return None
    return build_library(
        "libgofr_pjrt_stub", ["pjrt/stub_plugin.cc"], [f"-I{d}" for d in incs]
    )


def _declare_runtime(lib: ctypes.CDLL) -> None:
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    p_i32, p_i64 = ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int64)
    sig = {
        "gofr_ba_create": (i64, [i32, i32]),
        "gofr_ba_destroy": (i32, [i64]),
        "gofr_ba_alloc": (i32, [i64, i64, i64]),
        "gofr_ba_extend": (i32, [i64, i64, i64, p_i32, p_i32]),
        "gofr_ba_fork": (i64, [i64, i64, i64, i64]),
        "gofr_ba_free": (i32, [i64, i64]),
        "gofr_ba_block_table": (i32, [i64, i64, p_i32, i32]),
        "gofr_ba_seq_length": (i64, [i64, i64]),
        "gofr_ba_stats": (i32, [i64, p_i64]),
        "gofr_sched_create": (i64, [i32, i32, i32]),
        "gofr_sched_destroy": (i32, [i64]),
        "gofr_sched_submit": (i32, [i64, i64, i32, i32, i32]),
        "gofr_sched_submit_front": (i32, [i64, i64, i32, i32, i32]),
        "gofr_sched_cancel": (i32, [i64, i64]),
        "gofr_sched_admit": (i32, [i64, p_i64, p_i32, i32, p_i64, i32, p_i32]),
        "gofr_sched_release": (i32, [i64, i32]),
        "gofr_sched_stats": (i32, [i64, p_i64]),
        "gofr_runtime_version": (ctypes.c_char_p, []),
    }
    for fname, (res, args) in sig.items():
        fn = getattr(lib, fname)
        fn.restype = res
        fn.argtypes = args


def _declare_pjrt(lib: ctypes.CDLL) -> None:
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    cp = ctypes.c_char_p
    p_i64 = ctypes.POINTER(ctypes.c_int64)
    vp = ctypes.c_void_p
    sig = {
        "gofr_pjrt_load": (i64, [cp]),
        "gofr_pjrt_api_version": (i32, [i64, ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32)]),
        "gofr_pjrt_client_create": (i64, [i64]),
        "gofr_pjrt_client_destroy": (i32, [i64]),
        "gofr_pjrt_platform_name": (i32, [i64, cp, i32]),
        "gofr_pjrt_device_count": (i32, [i64]),
        "gofr_pjrt_addressable_device_count": (i32, [i64]),
        "gofr_pjrt_device_ids": (i32, [i64, p_i64, i32]),
        "gofr_pjrt_compile": (i64, [i64, vp, i64, cp, vp, i64]),
        "gofr_pjrt_executable_destroy": (i32, [i64]),
        "gofr_pjrt_unload": (i32, [i64]),
        "gofr_pjrt_execute_f32": (
            i32,
            [i64, i64, ctypes.POINTER(ctypes.c_float), i64,
             ctypes.POINTER(ctypes.c_float), i64, p_i64],
        ),
        "gofr_pjrt_last_error": (cp, []),
    }
    for fname, (res, args) in sig.items():
        fn = getattr(lib, fname)
        fn.restype = res
        fn.argtypes = args


def native_available() -> bool:
    return load_runtime() is not None

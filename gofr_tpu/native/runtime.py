"""High-level wrappers over the native runtime (block allocator +
scheduler), dispatching to the C++ library when buildable and the
pure-Python fallback otherwise. The interface is identical either way;
``BlockAllocator(...).backend`` reports which one is live."""

from __future__ import annotations

import ctypes
import threading

from gofr_tpu import chaos
from gofr_tpu.native import (
    GOFR_E_EXISTS,
    GOFR_E_NOMEM,
    GOFR_E_NOTFOUND,
    GOFR_E_QUEUEFULL,
    NativeError,
    load_runtime,
)
from gofr_tpu.native.fallback import (
    OutOfBlocks,
    PyBlockAllocator,
    PyScheduler,
    QueueFull,
)

__all__ = ["BlockAllocator", "Scheduler", "OutOfBlocks", "QueueFull"]


def _check(code: int, what: str) -> int:
    if code >= 0:
        return code
    if code == GOFR_E_NOMEM:
        raise OutOfBlocks(what)
    if code == GOFR_E_QUEUEFULL:
        raise QueueFull(what)
    if code in (GOFR_E_NOTFOUND, GOFR_E_EXISTS):
        raise KeyError(f"{what}: {code}")
    raise NativeError(code, what)


class BlockAllocator:
    """Paged KV block allocator. See native/runtime/gofr_runtime.cc."""

    def __init__(self, num_blocks: int, block_size: int, *, force_python: bool = False):
        self._lib = None if force_python else load_runtime()
        if self._lib is None:
            self._py = PyBlockAllocator(num_blocks, block_size)
            self.backend = "python"
        else:
            h = self._lib.gofr_ba_create(num_blocks, block_size)
            _check(int(h), "ba_create")
            self._h = h
            self.backend = "native"
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._closed = False
        self._mu = threading.Lock()

    def alloc(self, seq_id: int, tokens: int) -> None:
        if self._lib is None:
            return self._py.alloc(seq_id, tokens)
        _check(self._lib.gofr_ba_alloc(self._h, seq_id, tokens), f"alloc seq {seq_id}")

    def extend(self, seq_id: int, new_length: int) -> tuple[int, int]:
        if self._lib is None:
            return self._py.extend(seq_id, new_length)
        src = ctypes.c_int32(-1)
        dst = ctypes.c_int32(-1)
        _check(
            self._lib.gofr_ba_extend(
                self._h, seq_id, new_length, ctypes.byref(src), ctypes.byref(dst)
            ),
            f"extend seq {seq_id}",
        )
        return int(src.value), int(dst.value)

    def fork(self, src_id: int, dst_id: int, shared_tokens: int) -> int:
        if self._lib is None:
            return self._py.fork(src_id, dst_id, shared_tokens)
        return _check(
            int(self._lib.gofr_ba_fork(self._h, src_id, dst_id, shared_tokens)),
            f"fork {src_id}->{dst_id}",
        )

    def free(self, seq_id: int) -> None:
        if self._lib is None:
            return self._py.free(seq_id)
        _check(self._lib.gofr_ba_free(self._h, seq_id), f"free seq {seq_id}")

    def block_table(self, seq_id: int) -> list[int]:
        if self._lib is None:
            return self._py.block_table(seq_id)
        cap = self.num_blocks
        buf = (ctypes.c_int32 * cap)()
        n = _check(
            self._lib.gofr_ba_block_table(self._h, seq_id, buf, cap),
            f"block_table seq {seq_id}",
        )
        return list(buf[:n])

    def seq_length(self, seq_id: int) -> int:
        if self._lib is None:
            return self._py.seq_length(seq_id)
        return _check(int(self._lib.gofr_ba_seq_length(self._h, seq_id)), "seq_length")

    def _stats_unlocked(self) -> dict[str, int]:
        if self._lib is None:
            return self._py.stats()
        out = (ctypes.c_int64 * 4)()
        _check(self._lib.gofr_ba_stats(self._h, out), "ba_stats")
        return {
            "free_blocks": out[0],
            "total_blocks": out[1],
            "sequences": out[2],
            "alloc_failures": out[3],
        }

    def stats(self) -> dict[str, int]:
        # the whole read happens under _mu so a racing close() cannot
        # destroy the handle between the _closed check and the native call
        with self._mu:
            if self._closed:
                return dict(self._last_stats)
            return self._stats_unlocked()

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            try:
                self._last_stats = self._stats_unlocked()
            except Exception:
                self._last_stats = {
                    "free_blocks": 0, "total_blocks": self.num_blocks,
                    "sequences": 0, "alloc_failures": 0,
                }
            # flag first: a destroy failure must not leave the object
            # half-open for __del__ to re-destroy the same native handle
            self._closed = True
            if self._lib is not None:
                _check(self._lib.gofr_ba_destroy(self._h), "ba_destroy")

    def leak(self) -> None:  # leakcheck: transfer(quarantine)
        """Quarantine-leak: mark the allocator closed WITHOUT destroying
        the native handle. Used by the engine's warm restart when its loop
        thread failed to join — a hung thread may still be inside a native
        call on this handle, and destroying memory under it would trade a
        hang for a use-after-free. The handle is deliberately abandoned;
        __del__ will not re-destroy it."""
        with self._mu:
            if self._closed:
                return
            self._last_stats = {
                "free_blocks": 0, "total_blocks": self.num_blocks,
                "sequences": 0, "alloc_failures": 0,
            }
            self._closed = True

    def __del__(self) -> None:  # best-effort; explicit close preferred
        try:
            self.close()
        except Exception:
            pass


class Scheduler:
    """Continuous-batching admission scheduler (priority + FIFO + budget)."""

    def __init__(self, max_slots: int, max_queue: int, prefill_token_budget: int,
                 *, force_python: bool = False):
        self._lib = None if force_python else load_runtime()
        if self._lib is None:
            self._py = PyScheduler(max_slots, max_queue, prefill_token_budget)
            self.backend = "python"
        else:
            h = self._lib.gofr_sched_create(max_slots, max_queue, prefill_token_budget)
            _check(int(h), "sched_create")
            self._h = h
            self.backend = "native"
        self.max_slots = max_slots
        self._closed = False
        self._mu = threading.Lock()

    def _ensure_open(self) -> None:
        if self._closed:
            raise RuntimeError("scheduler closed")

    def submit(self, req_id: int, prompt_len: int, max_new_tokens: int,
               priority: int = 0, front: bool = False) -> None:
        """Queue a request; ``front=True`` re-inserts at the head of its
        priority class (requeue after a transient admission failure)."""
        self._ensure_open()
        chaos.maybe_fail("sched.submit")
        if self._lib is None:
            return self._py.submit(req_id, prompt_len, max_new_tokens, priority, front)
        fn = self._lib.gofr_sched_submit_front if front else self._lib.gofr_sched_submit
        _check(
            fn(self._h, req_id, prompt_len, max_new_tokens, priority),
            f"submit req {req_id}",
        )

    def cancel(self, req_id: int) -> None:
        if self._lib is None:
            return self._py.cancel(req_id)
        _check(self._lib.gofr_sched_cancel(self._h, req_id), f"cancel req {req_id}")

    def admit(self, cap: int) -> tuple[list[tuple[int, int]], list[int]]:
        chaos.maybe_fail("sched.admit")
        if self._lib is None:
            return self._py.admit(cap)
        ids = (ctypes.c_int64 * cap)()
        slots = (ctypes.c_int32 * cap)()
        canceled = (ctypes.c_int64 * 64)()
        n_canceled = ctypes.c_int32(0)
        n = _check(
            self._lib.gofr_sched_admit(
                self._h, ids, slots, cap, canceled, 64, ctypes.byref(n_canceled)
            ),
            "admit",
        )
        return (
            [(int(ids[i]), int(slots[i])) for i in range(n)],
            [int(canceled[i]) for i in range(n_canceled.value)],
        )

    def release(self, slot: int) -> None:
        if self._lib is None:
            return self._py.release(slot)
        _check(self._lib.gofr_sched_release(self._h, slot), f"release slot {slot}")

    def _stats_unlocked(self) -> dict[str, int]:
        if self._lib is None:
            return self._py.stats()
        out = (ctypes.c_int64 * 5)()
        _check(self._lib.gofr_sched_stats(self._h, out), "sched_stats")
        return {
            "queue_depth": out[0],
            "busy_slots": out[1],
            "max_slots": out[2],
            "total_admitted": out[3],
            "total_canceled": out[4],
        }

    def pending(self) -> int:
        """Queue depth only — the engine loop's admit-cadence fast path.
        Canceled-but-undelivered requests still count (they sit in the
        queue until an admit() delivers them), so a zero here means a full
        admit round trip has nothing to do. Same close-race discipline as
        stats(); a closed scheduler reports its last snapshot."""
        with self._mu:
            if self._closed:
                return int(self._last_stats["queue_depth"])
            if self._lib is None:
                return int(self._py.stats()["queue_depth"])
            out = (ctypes.c_int64 * 5)()
            _check(self._lib.gofr_sched_stats(self._h, out), "sched_stats")
            return int(out[0])

    def stats(self) -> dict[str, int]:
        with self._mu:  # see BlockAllocator.stats — same close race
            if self._closed:
                return dict(self._last_stats)
            return self._stats_unlocked()

    def close(self) -> None:
        with self._mu:
            if self._closed:
                return
            try:
                self._last_stats = self._stats_unlocked()
            except Exception:
                self._last_stats = {
                    "queue_depth": 0, "busy_slots": 0, "max_slots": self.max_slots,
                    "total_admitted": 0, "total_canceled": 0,
                }
            self._closed = True  # see BlockAllocator.close — no re-destroy
            if self._lib is not None:
                _check(self._lib.gofr_sched_destroy(self._h), "sched_destroy")

    def leak(self) -> None:  # leakcheck: transfer(quarantine)
        """Quarantine-leak the scheduler handle (see BlockAllocator.leak):
        closed-without-destroy for the warm-restart path where the engine
        thread may still be inside a scheduler call."""
        with self._mu:
            if self._closed:
                return
            self._last_stats = {
                "queue_depth": 0, "busy_slots": 0, "max_slots": self.max_slots,
                "total_admitted": 0, "total_canceled": 0,
            }
            self._closed = True

    def __del__(self) -> None:
        try:
            self.close()
        except Exception:
            pass

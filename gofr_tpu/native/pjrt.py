"""Python face of the native PJRT C-API binding (native/pjrt/pjrt_dl.cc).

``PjrtPlugin.load(path)`` dlopens a PJRT plugin — ``libtpu.so`` on TPU
hosts, the built-in test stub otherwise — and exposes clients, device
topology, AOT compile, and a single-device f32 execute used to validate
the full buffer lifecycle. This is the native integration layer SURVEY.md
§2.9 requires; the JAX path stays primary for compute, while this binding
lets the runtime own executables without Python in the loop.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Any

from gofr_tpu.native import (
    ERROR_NAMES,
    NativeError,
    build_stub_plugin,
    load_pjrt,
)


class PjrtError(RuntimeError):
    pass


_cache_lock = threading.Lock()
_plugin_cache: dict[str, "PjrtPlugin"] = {}
_load_failures: dict[str, str] = {}  # path -> first failure, memoized


def _lib() -> Any:
    lib = load_pjrt()
    if lib is None:
        raise PjrtError("native PJRT binding unavailable (no toolchain/headers)")
    return lib


_PJRT_CODES = (-8, -9)  # GOFR_E_PJRT / GOFR_E_DLOPEN carry a detail string


def _check(lib: Any, code: int, what: str) -> int:
    if code >= 0:
        return code
    if code in _PJRT_CODES:  # other codes would read a stale thread-local
        detail = lib.gofr_pjrt_last_error().decode() or str(code)
    else:
        detail = ERROR_NAMES.get(code, str(code))
    raise PjrtError(f"{what}: {detail}")


def probe_plugin_path() -> str | None:
    """Resolve a REAL PJRT plugin .so only: $TPU_PJRT_PLUGIN, then libtpu.
    Never falls back to the test stub — production health must not report
    a stub as a validated binding, and building the stub costs a compile."""
    env = os.environ.get("TPU_PJRT_PLUGIN")
    if env and os.path.exists(env):
        return env
    try:
        import libtpu

        path = os.path.join(os.path.dirname(libtpu.__file__), "libtpu.so")
        if os.path.exists(path):
            return path
    except ImportError:
        pass
    return None


def default_plugin_path() -> str | None:
    """Like :func:`probe_plugin_path` but falls back to building the test
    stub (dev/test convenience; CI's fake-PJRT tier)."""
    return probe_plugin_path() or build_stub_plugin()


class PjrtExecutable:
    def __init__(self, client: "PjrtClient", handle: int) -> None:
        self._client = client
        self._h = handle
        self._destroyed = False

    def execute_f32(self, values: list[float], out_cap: int = 1 << 16) -> list[float]:
        lib = self._client._lib
        arr = (ctypes.c_float * len(values))(*values)
        out = (ctypes.c_float * out_cap)()
        n_out = ctypes.c_int64(0)
        _check(
            lib,
            lib.gofr_pjrt_execute_f32(
                self._client._h, self._h, arr, len(values), out, out_cap,
                ctypes.byref(n_out),
            ),
            "execute",
        )
        return list(out[: n_out.value])

    def destroy(self) -> None:
        if not self._destroyed:
            self._destroyed = True
            lib = self._client._lib
            _check(lib, lib.gofr_pjrt_executable_destroy(self._h),
                   "executable destroy")


class PjrtClient:
    def __init__(self, lib: Any, handle: int) -> None:
        self._lib = lib
        self._h = handle
        self._closed = False

    @property
    def platform_name(self) -> str:
        buf = ctypes.create_string_buffer(256)
        _check(self._lib, self._lib.gofr_pjrt_platform_name(self._h, buf, 256),
               "platform name")
        return buf.value.decode()

    @property
    def device_count(self) -> int:
        return _check(self._lib, self._lib.gofr_pjrt_device_count(self._h),
                      "device count")

    @property
    def addressable_device_count(self) -> int:
        return _check(
            self._lib, self._lib.gofr_pjrt_addressable_device_count(self._h),
            "addressable device count",
        )

    def device_ids(self) -> list[int]:
        cap = max(self.device_count, 1)
        buf = (ctypes.c_int64 * cap)()
        n = _check(self._lib, self._lib.gofr_pjrt_device_ids(self._h, buf, cap),
                   "device ids")
        return list(buf[:n])

    def compile(self, code: bytes, fmt: str = "mlir",
                compile_options: bytes = b"") -> PjrtExecutable:
        h = self._lib.gofr_pjrt_compile(
            self._h, code, len(code), fmt.encode(),
            compile_options or None, len(compile_options),
        )
        _check(self._lib, int(h), "compile")
        return PjrtExecutable(self, int(h))

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            _check(self._lib, self._lib.gofr_pjrt_client_destroy(self._h),
                   "client destroy")


class PjrtPlugin:
    """A loaded PJRT plugin (shared object exporting GetPjrtApi)."""

    def __init__(self, lib: Any, handle: int, path: str) -> None:
        self._lib = lib
        self._h = handle
        self.path = path

    @classmethod
    def load(cls, path: str | None = None) -> "PjrtPlugin":
        """Load (or return the cached) plugin at ``path``. Loads are
        memoized per path — FAILURES included: a plugin that failed to
        initialize (e.g. the real libtpu probing for absent hardware,
        which can burn ~47 s in retries) fails once per process, not once
        per reconnect. The health probe on every ``TPUClient.connect``
        (and the sick-chip suite's per-test fixtures) ride this; a plugin
        stays resident for the process either way (dlopen handles are not
        refcount-churned by reconnects)."""
        lib = _lib()
        resolved = path or default_plugin_path()
        if resolved is None:
            raise PjrtError("no PJRT plugin found (set TPU_PJRT_PLUGIN)")
        with _cache_lock:
            cached = _plugin_cache.get(resolved)
            if cached is not None:
                return cached
            prior = _load_failures.get(resolved)
            if prior is not None:
                raise PjrtError(f"{prior} (memoized failure)")
            try:
                h = lib.gofr_pjrt_load(resolved.encode())
                _check(lib, int(h), f"load plugin {resolved}")
            except PjrtError as exc:
                _load_failures[resolved] = str(exc)
                raise
            plugin = cls(lib, int(h), resolved)
            _plugin_cache[resolved] = plugin
            return plugin

    @property
    def api_version(self) -> tuple[int, int]:
        major = ctypes.c_int32(0)
        minor = ctypes.c_int32(0)
        _check(
            self._lib,
            self._lib.gofr_pjrt_api_version(
                self._h, ctypes.byref(major), ctypes.byref(minor)
            ),
            "api version",
        )
        return major.value, minor.value

    def create_client(self) -> PjrtClient:
        h = self._lib.gofr_pjrt_client_create(self._h)
        _check(self._lib, int(h), "client create")
        return PjrtClient(self._lib, int(h))


__all__ = [
    "NativeError",
    "PjrtClient",
    "PjrtError",
    "PjrtExecutable",
    "PjrtPlugin",
    "default_plugin_path",
    "probe_plugin_path",
]

"""Pure-Python fallbacks for the native runtime (same semantics as
``native/runtime/gofr_runtime.cc``), used when no C++ toolchain is
available. The test suite runs both implementations against the same
scenarios so the contract stays pinned."""

from __future__ import annotations

import threading
from collections import OrderedDict, deque


class OutOfBlocks(RuntimeError):
    pass


class PyBlockAllocator:
    """Ref-counted paged KV block allocator with copy-on-write forks."""

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError("num_blocks and block_size must be positive")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._refcount = [0] * num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))
        self._seqs: dict[int, tuple[list[int], int]] = {}  # id -> (blocks, length)
        self._alloc_failures = 0
        self._mu = threading.Lock()

    def _needed(self, tokens: int) -> int:
        return (tokens + self.block_size - 1) // self.block_size

    def _take(self) -> int:
        b = self._free.pop()
        self._refcount[b] = 1
        return b

    def _drop(self, b: int) -> None:
        self._refcount[b] -= 1
        if self._refcount[b] == 0:
            self._free.append(b)

    def alloc(self, seq_id: int, tokens: int) -> None:
        with self._mu:
            if seq_id in self._seqs:
                raise KeyError(f"sequence {seq_id} exists")
            need = self._needed(tokens)
            if len(self._free) < need:
                self._alloc_failures += 1
                raise OutOfBlocks(f"need {need} blocks, {len(self._free)} free")
            self._seqs[seq_id] = ([self._take() for _ in range(need)], tokens)

    def extend(self, seq_id: int, new_length: int) -> tuple[int, int]:
        """Grow to new_length; returns (cow_src, cow_dst) block ids or (-1,-1)."""
        with self._mu:
            blocks, length = self._seqs[seq_id]
            if new_length < length:
                raise ValueError("cannot shrink")
            cow = (-1, -1)
            if (blocks and length % self.block_size != 0
                    and self._refcount[blocks[-1]] > 1 and new_length > length):
                if not self._free:
                    self._alloc_failures += 1
                    raise OutOfBlocks("no block for copy-on-write")
                fresh = self._take()
                self._drop(blocks[-1])
                cow = (blocks[-1], fresh)
                blocks[-1] = fresh
            need = self._needed(new_length)
            if need > len(blocks):
                if len(self._free) < need - len(blocks):
                    self._alloc_failures += 1
                    raise OutOfBlocks("extend")
                blocks.extend(self._take() for _ in range(need - len(blocks)))
            self._seqs[seq_id] = (blocks, new_length)
            return cow

    def fork(self, src_id: int, dst_id: int, shared_tokens: int) -> int:
        with self._mu:
            blocks, length = self._seqs[src_id]
            if dst_id in self._seqs:
                raise KeyError(f"sequence {dst_id} exists")
            full = min(min(shared_tokens, length) // self.block_size, len(blocks))
            shared = blocks[:full]
            for b in shared:
                self._refcount[b] += 1
            self._seqs[dst_id] = (list(shared), full * self.block_size)
            return full * self.block_size

    def free(self, seq_id: int) -> None:
        with self._mu:
            blocks, _ = self._seqs.pop(seq_id)
            for b in blocks:
                self._drop(b)

    def block_table(self, seq_id: int) -> list[int]:
        with self._mu:
            return list(self._seqs[seq_id][0])

    def seq_length(self, seq_id: int) -> int:
        with self._mu:
            return self._seqs[seq_id][1]

    def stats(self) -> dict[str, int]:
        with self._mu:
            return {
                "free_blocks": len(self._free),
                "total_blocks": self.num_blocks,
                "sequences": len(self._seqs),
                "alloc_failures": self._alloc_failures,
            }

    def close(self) -> None:
        pass


class QueueFull(RuntimeError):
    pass


class PyScheduler:
    """Priority + FIFO admission scheduler with a prefill token budget."""

    def __init__(self, max_slots: int, max_queue: int, prefill_token_budget: int) -> None:
        if max_slots <= 0 or max_queue <= 0 or prefill_token_budget <= 0:
            raise ValueError("all scheduler sizes must be positive")
        self.max_slots = max_slots
        self.max_queue = max_queue
        self.prefill_token_budget = prefill_token_budget
        self._slots: list[int | None] = [None] * max_slots
        self._queues: OrderedDict[int, deque] = OrderedDict()
        self._meta: dict[int, dict] = {}
        self._total_admitted = 0
        self._total_canceled = 0
        self._mu = threading.Lock()

    def submit(self, req_id: int, prompt_len: int, max_new_tokens: int,
               priority: int = 0, front: bool = False) -> None:
        with self._mu:
            if req_id in self._meta:
                raise KeyError(f"request {req_id} exists")
            if sum(len(q) for q in self._queues.values()) >= self.max_queue:
                raise QueueFull()
            meta = {"prompt_len": prompt_len, "max_new": max_new_tokens,
                    "priority": priority, "canceled": False}
            self._meta[req_id] = meta
            q = self._queues.setdefault(priority, deque())
            q.appendleft(req_id) if front else q.append(req_id)
            # keep priorities sorted (lower first) like the C++ std::map
            self._queues = OrderedDict(sorted(self._queues.items()))

    def cancel(self, req_id: int) -> None:
        with self._mu:
            self._meta[req_id]["canceled"] = True
            self._total_canceled += 1

    def admit(self, cap: int) -> tuple[list[tuple[int, int]], list[int]]:
        """Returns ([(req_id, slot)...], [canceled_req_ids...])."""
        with self._mu:
            admitted: list[tuple[int, int]] = []
            canceled: list[int] = []
            budget = self.prefill_token_budget
            for priority in list(self._queues):
                q = self._queues[priority]
                while q and len(admitted) < cap:
                    rid = q[0]
                    meta = self._meta[rid]
                    if meta["canceled"]:
                        canceled.append(rid)
                        del self._meta[rid]
                        q.popleft()
                        continue
                    if admitted and meta["prompt_len"] > budget:
                        break  # next priority may hold shorter prompts
                    try:
                        slot = self._slots.index(None)
                    except ValueError:
                        return admitted, canceled
                    self._slots[slot] = rid
                    admitted.append((rid, slot))
                    budget -= meta["prompt_len"]
                    self._total_admitted += 1
                    del self._meta[rid]
                    q.popleft()
                    if budget <= 0:
                        return admitted, canceled
                if len(admitted) >= cap:
                    break
            return admitted, canceled

    def release(self, slot: int) -> None:
        with self._mu:
            if self._slots[slot] is None:
                raise KeyError(f"slot {slot} already free")
            self._slots[slot] = None

    def stats(self) -> dict[str, int]:
        with self._mu:
            return {
                "queue_depth": sum(len(q) for q in self._queues.values()),
                "busy_slots": sum(1 for s in self._slots if s is not None),
                "max_slots": self.max_slots,
                "total_admitted": self._total_admitted,
                "total_canceled": self._total_canceled,
            }

    def close(self) -> None:
        pass

"""gRPC serving (reference: pkg/gofr/grpc.go + pkg/gofr/grpc/log.go).

grpc.aio server with chained recovery + observability interceptors, a
built-in standard health service (grpc.health.v1 wire format, hand-framed —
the image carries no grpc_health package), container injection into
servicers, and the Inference service: unary Generate/Embed/Echo plus
server-streaming GenerateStream — the token-by-token decode path of the
north star (SURVEY §3.3: "this is where token-by-token decode streaming
slots in").
"""

from gofr_tpu.grpcx.server import GRPCServer
from gofr_tpu.grpcx.inference import InferenceService, InferenceClient
from gofr_tpu.grpcx.runtime import GofrGrpcService, GofrStream, ProtoRequest

__all__ = [
    "GRPCServer",
    "InferenceService",
    "InferenceClient",
    "GofrGrpcService",
    "GofrStream",
    "ProtoRequest",
]

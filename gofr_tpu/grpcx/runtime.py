"""Runtime support for gofr-tpu generated gRPC services.

The typed codegen path (grpcx/codegen.py) mirrors the reference's
gofr-cli protoc plugin output (`*_gofr.go`,
examples/grpc/grpc-streaming-server/server/chatservice_gofr.go:29-120):
generated modules are thin — message classes materialized from an
embedded ``FileDescriptorSet`` and a servicer base class per service —
while everything behavioral lives here:

- :func:`load_messages` — descriptor pool + message factory, no protoc
  python plugin needed at runtime;
- :class:`ProtoRequest` — adapts a proto message to the framework's
  ``Request`` contract so ``ctx.bind`` works inside gRPC handlers
  (reference ``RequestWrapper``, request_gofr.go:15-53);
- :class:`GofrStream` — typed, instrumented stream endpoint: every
  ``send``/``recv`` is logged at DEBUG with the method and message type
  and counted on ``app_grpc_message_total`` (chatservice_gofr.go:43-120
  per-Send/Recv spans+logs);
- :class:`GofrGrpcService` — turns the generated ``METHODS`` table into
  real grpc.aio method handlers, building a ``Context`` first so user
  methods keep the Context-first gofr signature.
"""

from __future__ import annotations

import dataclasses
from typing import Any, AsyncIterator, Callable

import grpc

from google.protobuf import descriptor_pb2, descriptor_pool, json_format, message_factory


def load_descriptor_set(data: bytes) -> descriptor_pb2.FileDescriptorSet:
    return descriptor_pb2.FileDescriptorSet.FromString(data)


def load_messages(fds_bytes: bytes) -> dict[str, Any]:
    """Materialize message classes for every type in a serialized
    FileDescriptorSet. Each call uses a private pool, so generated
    modules never collide with each other or with installed _pb2s."""
    fds = load_descriptor_set(fds_bytes)
    pool = descriptor_pool.DescriptorPool()
    for f in fds.file:
        pool.Add(f)
    classes: dict[str, Any] = {}

    def walk(prefix: str, msgs: Any) -> None:
        for m in msgs:
            full = f"{prefix}.{m.name}" if prefix else m.name
            classes[full] = message_factory.GetMessageClass(
                pool.FindMessageTypeByName(full)
            )
            walk(full, m.nested_type)

    for f in fds.file:
        walk(f.package, f.message_type)
    return classes


class ProtoRequest:
    """``Request`` implementation over a proto message + gRPC metadata."""

    def __init__(self, message: Any, context: Any = None) -> None:
        self.message = message
        self._context = context

    def param(self, key: str) -> str:
        try:
            return str(getattr(self.message, key))
        except AttributeError:
            return ""

    def params(self, key: str) -> list[str]:
        try:
            value = getattr(self.message, key)
        except AttributeError:
            return []
        if isinstance(value, (list, tuple)) or hasattr(value, "append"):
            return [str(v) for v in value]
        return [str(value)] if str(value) else []

    def path_param(self, key: str) -> str:
        return self.param(key)

    def header(self, key: str) -> str:
        if self._context is None:
            return ""
        for mk, mv in self._context.invocation_metadata() or ():
            if mk.lower() == key.lower():
                return mv
        return ""

    def host_name(self) -> str:
        return self._context.peer() if self._context is not None else ""

    def bind(self, target: Any) -> Any:
        """Bind the proto message into ``target``: the message itself,
        a dict, or a dataclass with matching field names."""
        if target is None or target is type(self.message) or isinstance(target, type(self.message)):
            return self.message
        as_dict = json_format.MessageToDict(self.message, preserving_proto_field_name=True)
        if target is dict:
            return as_dict
        cls = target if isinstance(target, type) else type(target)
        if dataclasses.is_dataclass(cls):
            names = {f.name for f in dataclasses.fields(cls)}
            return cls(**{k: v for k, v in as_dict.items() if k in names})
        obj = target if not isinstance(target, type) else cls()
        for k, v in as_dict.items():
            setattr(obj, k, v)
        return obj


class GofrStream:
    """Typed stream endpoint handed to user handlers of streaming RPCs.

    ``recv()`` pulls the next client message (``None`` at end of stream);
    ``send()`` pushes a response frame; ``async for`` iterates requests.
    Every message movement is instrumented (per-Send/Recv DEBUG log +
    counter) like the reference's generated stream wrappers.
    """

    def __init__(
        self,
        method: str,
        container: Any,
        request_iterator: AsyncIterator[Any] | None,
        response_cls: type | None,
    ) -> None:
        import asyncio

        self.method = method
        self._container = container
        self._requests = request_iterator
        self._response_cls = response_cls
        # frames queued by send(), drained concurrently by the behavior so
        # push-style handlers stream incrementally (no buffering to the end)
        self._out: "asyncio.Queue[Any]" = asyncio.Queue()
        self.sent = 0
        self.received = 0

    def _observe(self, direction: str, msg: Any) -> None:
        c = self._container
        if c is None:
            return
        c.logger.debug(
            f"gRPC {self.method} {direction} {type(msg).__name__}"
        )
        m = getattr(c, "metrics_manager", None)
        if m is not None and m.get("app_grpc_message_total") is not None:
            m.increment_counter(
                "app_grpc_message_total", method=self.method, direction=direction
            )

    async def recv(self) -> Any:
        if self._requests is None:
            raise RuntimeError(f"{self.method} has no client stream to recv from")
        try:
            msg = await self._requests.__anext__()
        except StopAsyncIteration:
            return None
        self.received += 1
        self._observe("recv", msg)
        return msg

    def __aiter__(self) -> "GofrStream":
        return self

    async def __anext__(self) -> Any:
        msg = await self.recv()
        if msg is None:
            raise StopAsyncIteration
        return msg

    def send(self, msg: Any) -> None:
        if self._response_cls is not None and not isinstance(msg, self._response_cls):
            raise TypeError(
                f"{self.method} must send {self._response_cls.__name__}, "
                f"got {type(msg).__name__}"
            )
        self.sent += 1
        self._observe("send", msg)
        self._out.put_nowait(msg)


class GofrGrpcService:
    """Base class for generated servicers.

    Subclass contract (written by codegen): ``SERVICE_NAME``,
    ``FILE_DESCRIPTOR_SET`` (serialized bytes), ``MESSAGES`` (full-name →
    class) and ``METHODS`` (name → (kind, in_type, out_type)); one async
    method per RPC with the Context-first signature:

    - unary_unary:   ``async def M(self, ctx, request) -> Response``
    - unary_stream:  ``async def M(self, ctx, request, stream)`` —
      push frames with ``stream.send``; or an async generator
      ``async def M(self, ctx, request)`` yielding responses
    - stream_unary:  ``async def M(self, ctx, stream) -> Response``
    - stream_stream: ``async def M(self, ctx, stream)`` — ``recv`` and
      ``send`` freely; or an async generator over ``stream``
    """

    SERVICE_NAME: str = ""
    FILE_DESCRIPTOR_SET: bytes = b""
    MESSAGES: dict[str, Any] = {}
    METHODS: dict[str, tuple[str, str, str]] = {}

    def __init__(self) -> None:
        self.container: Any = None  # injected by GRPCServer.register

    # -- gofr generic-service contract ------------------------------------
    def gofr_service_name(self) -> str:
        return self.SERVICE_NAME

    def gofr_file_descriptor_set(self) -> bytes:
        return self.FILE_DESCRIPTOR_SET

    def _context(self, request: Any, grpc_context: Any) -> Any:
        from gofr_tpu.context import Context

        return Context(ProtoRequest(request, grpc_context), self.container)

    def gofr_method_handlers(self) -> dict[str, Any]:
        handlers: dict[str, Any] = {}
        for name, (kind, in_type, out_type) in self.METHODS.items():
            in_cls = self.MESSAGES[in_type]
            out_cls = self.MESSAGES[out_type]
            user = getattr(self, name)
            behavior = getattr(self, f"_behavior_{kind}")(name, user, out_cls)
            factory = getattr(grpc, f"{kind}_rpc_method_handler")
            handlers[name] = factory(
                behavior,
                request_deserializer=in_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        return handlers

    # -- behaviors ---------------------------------------------------------
    def _check_response(self, name: str, out_cls: type, msg: Any) -> Any:
        if not isinstance(msg, out_cls):
            raise TypeError(
                f"{self.SERVICE_NAME}/{name} returned {type(msg).__name__}, "
                f"expected {out_cls.__name__}"
            )
        return msg

    def _behavior_unary_unary(self, name: str, user: Callable, out_cls: type) -> Callable:
        async def behavior(request: Any, context: Any) -> Any:
            ctx = self._context(request, context)
            return self._check_response(name, out_cls, await user(ctx, request))

        return behavior

    async def _pump(self, coro: Any, stream: GofrStream):
        """Run a push-style handler concurrently with draining its send
        queue, so frames reach the wire as they are sent."""
        import asyncio

        sentinel = object()
        task = asyncio.ensure_future(coro)
        task.add_done_callback(lambda _t: stream._out.put_nowait(sentinel))
        try:
            while True:
                frame = await stream._out.get()
                if frame is sentinel:
                    break
                yield frame
            await task  # surface handler exceptions after the queue drains
            while not stream._out.empty():  # frames sent during teardown
                frame = stream._out.get_nowait()
                if frame is not sentinel:
                    yield frame
        finally:
            task.cancel()

    def _behavior_unary_stream(self, name: str, user: Callable, out_cls: type) -> Callable:
        import inspect

        method = f"/{self.SERVICE_NAME}/{name}"
        is_gen = inspect.isasyncgenfunction(user)

        async def behavior(request: Any, context: Any):
            ctx = self._context(request, context)
            stream = GofrStream(method, self.container, None, out_cls)
            if is_gen:
                async for msg in user(ctx, request):
                    stream.send(msg)  # instrument + type-check each frame
                    yield stream._out.get_nowait()
            else:
                async for frame in self._pump(user(ctx, request, stream), stream):
                    yield frame

        return behavior

    def _behavior_stream_unary(self, name: str, user: Callable, out_cls: type) -> Callable:
        method = f"/{self.SERVICE_NAME}/{name}"

        async def behavior(request_iterator: Any, context: Any) -> Any:
            ctx = self._context(None, context)
            stream = GofrStream(method, self.container, request_iterator, out_cls)
            return self._check_response(name, out_cls, await user(ctx, stream))

        return behavior

    def _behavior_stream_stream(self, name: str, user: Callable, out_cls: type) -> Callable:
        import inspect

        method = f"/{self.SERVICE_NAME}/{name}"
        is_gen = inspect.isasyncgenfunction(user)

        async def behavior(request_iterator: Any, context: Any):
            ctx = self._context(None, context)
            stream = GofrStream(method, self.container, request_iterator, out_cls)
            if is_gen:
                async for msg in user(ctx, stream):
                    stream.send(msg)
                    yield stream._out.get_nowait()
                while not stream._out.empty():  # frames pushed via send()
                    yield stream._out.get_nowait()
            else:
                async for frame in self._pump(user(ctx, stream), stream):
                    yield frame

        return behavior

"""The Inference gRPC service + client.

The north-star serving surface over gRPC (SURVEY §3.3): unary Echo (the
framework-overhead bench, BASELINE.json configs[0]), unary Generate, unary
Embed, and server-streaming GenerateStream for token-by-token decode
(configs[2]). Wire format: JSON bytes with identity serializers — the
service is defined with generic method handlers, so no protoc step is
needed; any gRPC client sends `application/grpc` frames of UTF-8 JSON.

Servicers follow the reference's DI convention (grpc.go:222-269): a
``container`` attribute is injected at registration.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any

import grpc
import grpc.aio

from gofr_tpu.http.errors import (
    ErrorDeadlineExceeded,
    ErrorRequestEntityTooLarge,
    ErrorServiceUnavailable,
    ErrorTooManyRequests,
)
from gofr_tpu.tracing.trace import current_span, format_traceparent

# the engine's typed lifecycle errors every generation RPC converts to a
# gRPC status instead of letting them surface as INTERNAL
LIFECYCLE_ERRORS = (
    ErrorTooManyRequests,
    ErrorServiceUnavailable,
    ErrorDeadlineExceeded,
    ErrorRequestEntityTooLarge,
)

SERVICE_NAME = "gofr.v1.Inference"


def _deadline_of(context: Any) -> float | None:
    """The gRPC-native deadline: clients set it on the call; the remaining
    budget propagates into the engine so queued work that cannot make it
    is dropped instead of decoded into the void."""
    try:
        remaining = context.time_remaining()
    except Exception:
        return None
    if remaining is None or remaining <= 0:
        return None
    return float(remaining)


async def _abort_lifecycle(context: Any, exc: Exception) -> None:
    """Map the engine's typed lifecycle errors onto gRPC status codes:
    shed → RESOURCE_EXHAUSTED (+ retry-delay detail), drain →
    UNAVAILABLE, expired → DEADLINE_EXCEEDED, can-never-fit →
    FAILED_PRECONDITION (permanent: retrying the same request is
    pointless, unlike every other status here)."""
    if isinstance(exc, ErrorRequestEntityTooLarge):
        await context.abort(grpc.StatusCode.FAILED_PRECONDITION, exc.message)
    if isinstance(exc, ErrorTooManyRequests):
        retry_after = exc.retry_after if exc.retry_after is not None else 1.0
        context.set_trailing_metadata((
            ("retry-delay-s", f"{retry_after:.3f}"),
        ))
        await context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, exc.message)
    if isinstance(exc, ErrorServiceUnavailable):
        await context.abort(grpc.StatusCode.UNAVAILABLE, exc.message)
    if isinstance(exc, ErrorDeadlineExceeded):
        await context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, exc.message)
    raise exc

_identity = lambda b: b  # noqa: E731


def _trace_metadata() -> tuple | None:
    """Outbound W3C propagation: the caller's active span rides gRPC
    metadata as ``traceparent``, mirroring the HTTP header path."""
    span = current_span()
    if span is None:
        return None
    return (("traceparent", format_traceparent(span)),)


def _json_bytes(obj: Any) -> bytes:
    return json.dumps(obj).encode("utf-8")


async def _parse(request: bytes, context: Any) -> dict:
    """Malformed bodies are client errors: INVALID_ARGUMENT, not a handler
    panic/INTERNAL."""
    if not request:
        return {}
    try:
        data = json.loads(request.decode("utf-8"))
    except (json.JSONDecodeError, UnicodeDecodeError):
        await context.abort(
            grpc.StatusCode.INVALID_ARGUMENT, "request body must be UTF-8 JSON"
        )
    return data if isinstance(data, dict) else {"value": data}


class InferenceService:
    """Register with ``app.register_grpc_service(InferenceService(engine))``
    or attach embedder params for /Embed."""

    def __init__(self, engine: Any = None, embedder: Any = None) -> None:
        self.container: Any = None  # injected by GRPCServer.register
        self.engine = engine
        self.embedder = embedder  # (bert_cfg, bert_params, tokenizer)

    # -- gofr generic-service contract ----------------------------------------
    def gofr_service_name(self) -> str:
        return SERVICE_NAME

    def gofr_method_handlers(self) -> dict[str, Any]:
        return {
            "Echo": grpc.unary_unary_rpc_method_handler(
                self.echo, request_deserializer=_identity, response_serializer=_identity
            ),
            "Generate": grpc.unary_unary_rpc_method_handler(
                self.generate, request_deserializer=_identity, response_serializer=_identity
            ),
            "GenerateStream": grpc.unary_stream_rpc_method_handler(
                self.generate_stream, request_deserializer=_identity, response_serializer=_identity
            ),
            "Embed": grpc.unary_unary_rpc_method_handler(
                self.embed, request_deserializer=_identity, response_serializer=_identity
            ),
        }

    # -- methods ---------------------------------------------------------------
    async def echo(self, request: bytes, context: Any) -> bytes:
        """configs[0]: framework-overhead calibration."""
        return request or b"{}"

    def _gen_kwargs(self, body: dict, context: Any = None) -> dict:
        kw = dict(
            max_new_tokens=int(body.get("max_tokens") or 0) or None,
            temperature=float(body.get("temperature", 0.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
        )
        # multi-tenant plane (docs/serving.md "Multi-tenancy"): adapter
        # and tenant ride only when set, mirroring the HTTP handlers; an
        # x-tenant-id metadata entry (the gateway's stamp) outranks the
        # body field
        tenant = str(body.get("tenant") or "")
        if context is not None:
            try:
                for key, value in context.invocation_metadata() or ():
                    if key == "x-tenant-id" and value:
                        tenant = value
            except Exception:
                pass
        if body.get("adapter_id"):
            kw["adapter_id"] = str(body["adapter_id"])
        if tenant:
            kw["tenant"] = tenant
        return kw

    async def generate(self, request: bytes, context: Any) -> bytes:
        if self.engine is None:
            await context.abort(grpc.StatusCode.UNIMPLEMENTED, "no engine attached")
        body = await _parse(request, context)
        prompt = body.get("prompt")
        if not prompt:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "prompt required")
        try:
            # the interceptor's server span (which continued any inbound
            # traceparent metadata) is active here: hang the engine's
            # lifecycle spans off it
            result = await self.engine.generate(
                prompt, deadline=_deadline_of(context),
                trace_ctx=current_span(), **self._gen_kwargs(body, context)
            )
        except LIFECYCLE_ERRORS as exc:
            await _abort_lifecycle(context, exc)
        return _json_bytes(
            {
                "id": result.request_id,
                "text": result.text,
                "finish_reason": result.finish_reason,
                "usage": {
                    "prompt_tokens": result.prompt_tokens,
                    "completion_tokens": result.completion_tokens,
                    "ttft_ms": round(result.ttft_s * 1000, 2),
                },
            }
        )

    async def generate_stream(self, request: bytes, context: Any):
        """Server-streaming decode: one JSON frame per token."""
        if self.engine is None:
            await context.abort(grpc.StatusCode.UNIMPLEMENTED, "no engine attached")
        body = await _parse(request, context)
        prompt = body.get("prompt")
        if not prompt:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "prompt required")
        final: dict = {}
        try:
            async for token_id, piece in self.engine.stream(
                prompt, deadline=_deadline_of(context),
                on_result=lambda r: final.setdefault("result", r),
                trace_ctx=current_span(),
                **self._gen_kwargs(body, context),
            ):
                yield _json_bytes({"token": token_id, "text": piece})
        except LIFECYCLE_ERRORS as exc:
            await _abort_lifecycle(context, exc)
        result = final.get("result")
        done: dict[str, Any] = {"done": True}
        if result is not None:
            # deadline_exceeded mid-stream surfaces as the terminal frame's
            # finish_reason — the stream itself completed normally
            done["finish_reason"] = result.finish_reason
        yield _json_bytes(done)

    async def embed(self, request: bytes, context: Any) -> bytes:
        if self.embedder is None:
            await context.abort(grpc.StatusCode.UNIMPLEMENTED, "no embedder attached")
        import jax.numpy as jnp
        import numpy as np

        from gofr_tpu.models import bert as bert_model

        body = await _parse(request, context)
        texts = body.get("input") or body.get("texts") or []
        if isinstance(texts, str):
            texts = [texts]
        if not texts:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT, "input required")
        bert_cfg, bert_params, tokenizer = self.embedder
        from gofr_tpu.serving.tokenizer import pad_batch

        arr, lens = pad_batch(tokenizer, texts, bert_cfg.max_seq_len)
        loop = asyncio.get_running_loop()
        emb = await loop.run_in_executor(
            None,
            lambda: np.asarray(
                bert_model.embed(
                    bert_cfg, bert_params, jnp.asarray(arr), jnp.asarray(lens, jnp.int32)
                )
            ),
        )
        return _json_bytes({"embeddings": emb.tolist(), "dim": int(emb.shape[1])})


class InferenceClient:
    """Minimal client for the Inference service (tests, benches, and the
    DCN cross-host coordination path reuse this)."""

    def __init__(self, target: str) -> None:
        self.target = target
        self._channel = grpc.aio.insecure_channel(target)
        self._callables: dict[str, Any] = {}

    def _unary(self, method: str):
        # multicallables are stateless and reusable; building one per call
        # was a measurable share of client-side per-RPC cost
        mc = self._callables.get(method)
        if mc is None:
            mc = self._channel.unary_unary(
                f"/{SERVICE_NAME}/{method}",
                request_serializer=_identity,
                response_deserializer=_identity,
            )
            self._callables[method] = mc
        return mc

    async def echo(self, payload: dict) -> dict:
        resp = await self._unary("Echo")(_json_bytes(payload))
        return json.loads(resp)

    async def generate(self, prompt: str, **kw: Any) -> dict:
        resp = await self._unary("Generate")(
            _json_bytes({"prompt": prompt, **kw}), metadata=_trace_metadata()
        )
        return json.loads(resp)

    async def generate_stream(self, prompt: str, **kw: Any):
        stream = self._channel.unary_stream(
            f"/{SERVICE_NAME}/GenerateStream",
            request_serializer=_identity,
            response_deserializer=_identity,
        )(_json_bytes({"prompt": prompt, **kw}), metadata=_trace_metadata())
        async for frame in stream:
            yield json.loads(frame)

    async def embed(self, texts: list[str]) -> dict:
        resp = await self._unary("Embed")(_json_bytes({"input": texts}))
        return json.loads(resp)

    async def health(self) -> bool:
        check = self._channel.unary_unary(
            "/grpc.health.v1.Health/Check",
            request_serializer=_identity,
            response_deserializer=_identity,
        )
        resp = await check(b"")
        return resp == b"\x08\x01"

    async def close(self) -> None:
        await self._channel.close()

"""The gRPC server wrapper.

Reference parity: pkg/gofr/grpc.go — server construction with chained
interceptors (recovery first, then observability, grpc.go:96-104), optional
reflection via GRPC_ENABLE_REFLECTION (grpc.go:131-134; served from the
committed descriptor sets, grpcx/reflection.py), graceful stop (grpc.go:185-197),
server status/error metrics (grpc.go:114-119), and reflection-based
container injection into registered servicers (grpc.go:222-269 → here: the
``container`` attribute is set on the servicer when present).

Services register either with a generated ``add_*_to_server`` adder or as
gofr generic services exposing ``gofr_service_name()`` +
``gofr_method_handlers()``.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Callable

import grpc
import grpc.aio

from gofr_tpu.tracing.trace import extract_traceparent

GRPC_STATUS_LABELS = {True: "OK", False: "ERROR"}


def _remote_trace(context: Any) -> tuple[str, str] | None:
    """Inbound W3C tracecontext from gRPC metadata: the ``traceparent``
    key carries the same header value HTTP does (grpc/log.go:179-202) —
    the server span continues the caller's trace instead of rooting a
    disconnected one."""
    try:
        metadata = dict(context.invocation_metadata() or ())
    except Exception:
        return None
    return extract_traceparent(metadata.get("traceparent"))


def _is_probe(method: str) -> bool:
    """Health/reflection keep serving during drain so orchestrators can
    observe NOT_SERVING instead of inferring it from UNAVAILABLE."""
    return method.startswith("/grpc.health.") or method.startswith("/grpc.reflection.")


def _health_handlers(container: Any) -> "grpc.GenericRpcHandler":
    """Standard grpc.health.v1.Health service, hand-framed protobuf:
    HealthCheckResponse{status=1} is `0x08 0x01` (SERVING) / `0x08 0x02`
    (NOT_SERVING)."""

    def check(request: bytes, context: Any) -> bytes:
        try:
            health = container.health()
            serving = health.get("status") == "UP"
        except Exception:
            serving = False
        return b"\x08\x01" if serving else b"\x08\x02"

    method = grpc.unary_unary_rpc_method_handler(
        check,
        request_deserializer=lambda b: b,
        response_serializer=lambda b: b,
    )
    return grpc.method_handlers_generic_handler(
        "grpc.health.v1.Health", {"Check": method}
    )


class _ObservabilityInterceptor(grpc.aio.ServerInterceptor):
    """Per-RPC span + structured log + ``app_grpc_server_stats`` histogram
    (grpc/log.go:150-251). Wraps each handler behavior; recovery included
    (panic → INTERNAL, grpc_recovery analogue)."""

    def __init__(self, container: Any) -> None:
        self.container = container
        # intercept_service runs PER RPC; rebuilding the wrapped handler
        # (4 closures + a method-handler object) each call showed up in
        # the echo-bench profile. Cache per method, holding the SOURCE
        # handler for identity validation: a handler object that changes
        # between calls (per-call factories are legal for generic
        # handlers) rebuilds instead of serving a stale wrap, and the
        # cache stays bounded by the method count.
        self._wrapped: dict[str, tuple[Any, Any]] = {}

    async def intercept_service(self, continuation: Callable, details: Any) -> Any:
        handler = await continuation(details)
        if handler is None:
            return None
        method = details.method
        cached = self._wrapped.get(method)
        if cached is not None and cached[0] is handler:
            return cached[1]
        container = self.container

        def wrap_unary(behavior: Callable) -> Callable:
            async def wrapped(request: Any, context: Any) -> Any:
                if getattr(container, "draining", False) and not _is_probe(method):
                    # retriable by contract: clients/LBs re-resolve and hit
                    # another replica (health keeps answering NOT_SERVING)
                    await context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "server draining; retry on another replica",
                    )
                start = time.perf_counter()
                remote = _remote_trace(context)
                span = container.tracer.start_span(
                    f"grpc {method}", kind="server",
                    remote_trace_id=remote[0] if remote else None,
                    remote_span_id=remote[1] if remote else None,
                )
                ok = True
                try:
                    with span:
                        return await _maybe_async(behavior, request, context)
                except grpc.aio.AbortError:
                    ok = False
                    raise
                except Exception as exc:
                    ok = False
                    container.logger.error(f"grpc handler panic in {method}: {exc}")
                    container.metrics_manager.increment_counter(
                        "grpc_server_errors_total", method=method
                    )
                    await context.abort(grpc.StatusCode.INTERNAL, "internal error")
                finally:
                    elapsed = time.perf_counter() - start
                    container.metrics_manager.record_histogram(
                        "app_grpc_server_stats", elapsed,
                        method=method, status=GRPC_STATUS_LABELS[ok],
                    )
                    container.logger.info(
                        f"gRPC {method} {'OK' if ok else 'ERROR'} {elapsed*1e6:.0f}µs"
                    )

            return wrapped

        def wrap_stream(behavior: Callable) -> Callable:
            async def wrapped(request: Any, context: Any):
                if getattr(container, "draining", False) and not _is_probe(method):
                    await context.abort(
                        grpc.StatusCode.UNAVAILABLE,
                        "server draining; retry on another replica",
                    )
                start = time.perf_counter()
                remote = _remote_trace(context)
                span = container.tracer.start_span(
                    f"grpc {method}", kind="server",
                    remote_trace_id=remote[0] if remote else None,
                    remote_span_id=remote[1] if remote else None,
                )
                ok = True
                try:
                    with span:
                        async for item in behavior(request, context):
                            yield item
                except grpc.aio.AbortError:
                    ok = False
                    raise
                except Exception as exc:
                    ok = False
                    container.logger.error(f"grpc stream panic in {method}: {exc}")
                    container.metrics_manager.increment_counter(
                        "grpc_server_errors_total", method=method
                    )
                    await context.abort(grpc.StatusCode.INTERNAL, "internal error")
                finally:
                    elapsed = time.perf_counter() - start
                    container.metrics_manager.record_histogram(
                        "app_grpc_stream_stats", elapsed,
                        method=method, status=GRPC_STATUS_LABELS[ok],
                    )
                    container.logger.info(
                        f"gRPC stream {method} {'OK' if ok else 'ERROR'} {elapsed*1e6:.0f}µs"
                    )

            return wrapped

        if handler.unary_unary is not None:
            wrapped = grpc.unary_unary_rpc_method_handler(
                wrap_unary(handler.unary_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        elif handler.unary_stream is not None:
            wrapped = grpc.unary_stream_rpc_method_handler(
                wrap_stream(handler.unary_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        elif handler.stream_unary is not None:
            wrapped = grpc.stream_unary_rpc_method_handler(
                wrap_unary(handler.stream_unary),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        elif handler.stream_stream is not None:
            wrapped = grpc.stream_stream_rpc_method_handler(
                wrap_stream(handler.stream_stream),
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )
        else:
            return handler
        self._wrapped[method] = (handler, wrapped)
        return wrapped


async def _maybe_async(fn: Callable, *args: Any) -> Any:
    result = fn(*args)
    if asyncio.iscoroutine(result):
        return await result
    return result


class GRPCServer:
    def __init__(self, container: Any, port: int, config: Any = None) -> None:
        self.container = container
        self.port = port
        self.config = config
        self._server: grpc.aio.Server | None = None
        self._pending: list[Any] = []  # registered before start
        self._register_metrics()

    def _register_metrics(self) -> None:
        m = self.container.metrics_manager
        if m.get("app_grpc_server_stats") is None:
            m.new_histogram("app_grpc_server_stats", "gRPC unary handler latency")
            m.new_histogram("app_grpc_stream_stats", "gRPC stream handler latency")
            m.new_counter("grpc_server_errors_total", "gRPC handler errors")
            m.new_counter(
                "app_grpc_message_total",
                "per-message Send/Recv count on generated streaming services",
            )
            m.new_gauge("grpc_server_status", "1 while the gRPC server is serving")

    def register(self, servicer: Any, adder: Callable | None = None) -> None:
        """RegisterService (grpc.go:200-220): container injection + deferred
        add (server object exists only at start). Registration after start
        raises — grpc.aio cannot add handlers to a serving server, and a
        silent UNIMPLEMENTED is worse than an error."""
        if self._server is not None:
            raise RuntimeError(
                "cannot register a gRPC service after the server has started"
            )
        if hasattr(servicer, "container") and servicer.container is None:
            servicer.container = self.container
        elif hasattr(servicer, "use_container"):
            servicer.use_container(self.container)
        self._pending.append((servicer, adder))

    async def start(self) -> None:
        self._server = grpc.aio.server(
            interceptors=[_ObservabilityInterceptor(self.container)]
        )
        self._server.add_generic_rpc_handlers((_health_handlers(self.container),))
        for servicer, adder in self._pending:
            if adder is not None:
                adder(servicer, self._server)
            elif hasattr(servicer, "gofr_method_handlers"):
                handler = grpc.method_handlers_generic_handler(
                    servicer.gofr_service_name(), servicer.gofr_method_handlers()
                )
                self._server.add_generic_rpc_handlers((handler,))
            else:
                raise TypeError(
                    f"servicer {type(servicer).__name__} has neither an adder "
                    "nor gofr_method_handlers()"
                )
        if self.config is not None and self.config.get_or_default(
            "GRPC_ENABLE_REFLECTION", "false"
        ).lower() == "true":
            # grpc.go:131-134 — reflection gated by env; built from the
            # committed descriptor sets (grpcx/reflection.py), no
            # grpc_reflection package needed
            from gofr_tpu.grpcx.reflection import ReflectionRegistry, ReflectionService

            registry = ReflectionRegistry()
            for servicer, _adder in self._pending:
                name_fn = getattr(servicer, "gofr_service_name", None)
                fds_fn = getattr(servicer, "gofr_file_descriptor_set", None)
                if callable(name_fn):
                    registry.add_service(
                        name_fn(), fds_fn() if callable(fds_fn) else None
                    )
            reflection = ReflectionService(registry)
            self._server.add_generic_rpc_handlers((
                grpc.method_handlers_generic_handler(
                    reflection.gofr_service_name(), reflection.gofr_method_handlers()
                ),
            ))
            self.container.logger.info("gRPC server reflection enabled")
        self._server.add_insecure_port(f"[::]:{self.port}")
        await self._server.start()
        self.container.metrics_manager.set_gauge("grpc_server_status", 1)
        self.container.logger.info(f"grpc server listening on :{self.port}")

    async def shutdown(self, grace: float = 5.0) -> None:
        if self._server is not None:
            self.container.metrics_manager.set_gauge("grpc_server_status", 0)
            await self._server.stop(grace)
            self._server = None

"""gRPC server reflection (v1alpha) — reference parity grpc.go:131-134.

The image has no ``grpc_reflection`` package, so the service is built
from the committed descriptor set (protos/reflection.binpb, compiled
from protos/reflection.proto by ``make protos``) through the same
descriptor-pool machinery the typed codegen uses. Registered services
contribute their ``FileDescriptorSet`` via
``gofr_file_descriptor_set()``; grpcurl-style clients can then
``list``/``describe`` every typed service plus grpc.health.v1.
"""

from __future__ import annotations

import os
from typing import Any

import grpc

from google.protobuf import descriptor_pb2

from gofr_tpu.grpcx.runtime import load_messages

_PROTO_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "protos")

SERVICE_NAME = "grpc.reflection.v1alpha.ServerReflection"


def _read_binpb(name: str) -> bytes:
    with open(os.path.join(_PROTO_DIR, name), "rb") as f:
        return f.read()


class ReflectionRegistry:
    """Symbol/file index over every registered service's descriptors."""

    def __init__(self) -> None:
        self._files: dict[str, descriptor_pb2.FileDescriptorProto] = {}
        self._symbol_to_file: dict[str, str] = {}
        self.services: list[str] = []
        # the server's built-ins are always describable
        self.add_service("grpc.health.v1.Health", _read_binpb("health.binpb"))
        self.add_service(SERVICE_NAME, _read_binpb("reflection.binpb"))

    def add_service(self, service_name: str, fds_bytes: bytes | None) -> None:
        if service_name and service_name not in self.services:
            self.services.append(service_name)
        if not fds_bytes:
            return
        fds = descriptor_pb2.FileDescriptorSet.FromString(fds_bytes)
        for fd in fds.file:
            if fd.name in self._files:
                continue
            self._files[fd.name] = fd
            self._index(fd)

    def _index(self, fd: descriptor_pb2.FileDescriptorProto) -> None:
        pkg = fd.package

        def full(name: str) -> str:
            return f"{pkg}.{name}" if pkg else name

        def walk_msgs(prefix: str, msgs: Any) -> None:
            for m in msgs:
                fq = f"{prefix}.{m.name}" if prefix else m.name
                self._symbol_to_file[fq] = fd.name
                walk_msgs(fq, m.nested_type)

        walk_msgs(pkg, fd.message_type)
        for e in fd.enum_type:
            self._symbol_to_file[full(e.name)] = fd.name
        for s in fd.service:
            self._symbol_to_file[full(s.name)] = fd.name
            for m in s.method:
                self._symbol_to_file[f"{full(s.name)}.{m.name}"] = fd.name

    def file_by_filename(self, name: str) -> list[bytes] | None:
        if name not in self._files:
            return None
        return self._closure(name)

    def file_containing_symbol(self, symbol: str) -> list[bytes] | None:
        fname = self._symbol_to_file.get(symbol)
        if fname is None:
            return None
        return self._closure(fname)

    def _closure(self, root: str) -> list[bytes]:
        """The root file plus its transitive deps we know about — grpcurl
        needs the full closure to build a pool client-side."""
        out: list[bytes] = []
        seen: set[str] = set()
        stack = [root]
        while stack:
            name = stack.pop()
            if name in seen or name not in self._files:
                continue
            seen.add(name)
            fd = self._files[name]
            out.append(fd.SerializeToString())
            stack.extend(fd.dependency)
        return out


class ReflectionService:
    """The ServerReflectionInfo bidi stream, as a gofr generic service."""

    def __init__(self, registry: ReflectionRegistry) -> None:
        self.container: Any = None  # injected at registration; unused
        self.registry = registry
        msgs = load_messages(_read_binpb("reflection.binpb"))
        self._req_cls = msgs["grpc.reflection.v1alpha.ServerReflectionRequest"]
        self._resp_cls = msgs["grpc.reflection.v1alpha.ServerReflectionResponse"]

    def gofr_service_name(self) -> str:
        return SERVICE_NAME

    def gofr_file_descriptor_set(self) -> bytes:
        return _read_binpb("reflection.binpb")

    def gofr_method_handlers(self) -> dict[str, Any]:
        return {
            "ServerReflectionInfo": grpc.stream_stream_rpc_method_handler(
                self._info,
                request_deserializer=self._req_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )
        }

    async def _info(self, request_iterator: Any, context: Any):
        async for req in request_iterator:
            yield self._respond(req)

    def _respond(self, req: Any) -> Any:
        resp = self._resp_cls()
        resp.valid_host = req.host
        resp.original_request.CopyFrom(req)
        which = req.WhichOneof("message_request")
        if which == "list_services":
            for name in self.registry.services:
                resp.list_services_response.service.add().name = name
        elif which == "file_by_filename":
            self._file_response(resp, self.registry.file_by_filename(req.file_by_filename),
                                req.file_by_filename)
        elif which == "file_containing_symbol":
            self._file_response(
                resp, self.registry.file_containing_symbol(req.file_containing_symbol),
                req.file_containing_symbol)
        else:
            resp.error_response.error_code = grpc.StatusCode.UNIMPLEMENTED.value[0]
            resp.error_response.error_message = f"unsupported reflection request: {which}"
        return resp

    def _file_response(self, resp: Any, files: list[bytes] | None, what: str) -> None:
        if files is None:
            resp.error_response.error_code = grpc.StatusCode.NOT_FOUND.value[0]
            resp.error_response.error_message = f"not found: {what}"
            return
        for f in files:
            resp.file_descriptor_response.file_descriptor_proto.append(f)

"""HTTP Responder: renders handler results into wire responses.

Reference parity: pkg/gofr/http/responder.go:29-99 — renders File / Template /
Redirect / Raw / Response types; status mapping from method+error
(:102-159: POST→201, DELETE→204, data+error→206 partial content); error
envelope with custom fields via ``response_fields`` (ResponseMarshaller,
:163-183); X-Correlation-ID header from the active trace
(middleware/logger.go:101).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

from gofr_tpu.http import response as resp_types
from gofr_tpu.http.errors import status_from_error
from gofr_tpu.tracing.trace import current_span


@dataclasses.dataclass
class WireResponse:
    status: int = 200
    headers: dict[str, str] = dataclasses.field(default_factory=dict)
    body: bytes = b""
    stream: Any = None  # async iterator of bytes chunks → chunked transfer


def draining_response() -> WireResponse:
    """The one retriable-503 the server sends while draining — shared by
    HTTP dispatch and the WebSocket upgrader so the wire contract
    (Retry-After, Connection: close, error envelope) cannot drift."""
    return WireResponse(
        status=503,
        headers={
            "Content-Type": "application/json",
            "Retry-After": "1",
            "Connection": "close",
        },
        body=b'{"error":{"message":"server draining; retry on another replica"}}',
    )


def _jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {k: _jsonable(v) for k, v in dataclasses.asdict(obj).items()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, bytes):
        return obj.decode("utf-8", "replace")
    if hasattr(obj, "__dict__") and not isinstance(obj, type):
        d = {k: _jsonable(v) for k, v in vars(obj).items() if not k.startswith("_")}
        if d:
            return d
    return obj


class Responder:
    """Builds the WireResponse for a (result, error) pair."""

    def respond(self, result: Any, err: BaseException | None, method: str = "GET") -> WireResponse:
        headers: dict[str, str] = {}
        span = current_span()
        if span is not None:
            headers["X-Correlation-ID"] = span.trace_id

        # unwrap Response envelope for metadata/headers
        metadata = None
        if isinstance(result, resp_types.Response):
            metadata = result.metadata
            if result.headers:
                headers.update(result.headers)
            result = result.data

        if err is None:
            special = self._render_special(result, headers, method)
            if special is not None:
                return special

        status = status_from_error(err, method, has_data=result is not None)
        envelope: dict[str, Any] = {}
        if err is not None:
            envelope["error"] = self._error_obj(err)
            hdr_fn = getattr(err, "response_headers", None)
            if callable(hdr_fn):  # Retry-After on shed/drain rejections
                headers.update(hdr_fn() or {})
        if result is not None or err is None:
            envelope["data"] = _jsonable(result)
        if metadata:
            envelope["metadata"] = _jsonable(metadata)

        if status == 204:
            return WireResponse(status=status, headers=headers)
        headers.setdefault("Content-Type", "application/json")
        body = json.dumps(envelope, default=str).encode("utf-8")
        return WireResponse(status=status, headers=headers, body=body)

    def _render_special(self, result: Any, headers: dict[str, str], method: str) -> WireResponse | None:
        if isinstance(result, resp_types.Raw):
            headers.setdefault("Content-Type", "application/json")
            return WireResponse(
                status=status_from_error(None, method, True),
                headers=headers,
                body=json.dumps(_jsonable(result.data), default=str).encode("utf-8"),
            )
        if isinstance(result, resp_types.File):
            headers["Content-Type"] = result.content_type
            return WireResponse(status=200, headers=headers, body=result.content)
        if isinstance(result, resp_types.Redirect):
            headers["Location"] = result.url
            return WireResponse(status=302, headers=headers)
        if isinstance(result, resp_types.Template):
            headers["Content-Type"] = "text/html"
            return WireResponse(status=200, headers=headers, body=result.render().encode("utf-8"))
        return None

    def _error_obj(self, err: BaseException) -> dict[str, Any]:
        obj: dict[str, Any] = {"message": str(err) or err.__class__.__name__}
        fields = getattr(err, "response_fields", None)
        if callable(fields):
            extra = fields()
            if extra:
                obj.update(_jsonable(extra))
        return obj

"""HTTP Request implementation: params, path params, body binding.

Reference parity: pkg/gofr/http/request.go — the Request interface
(request.go:29-32), JSON / multipart / x-www-form-urlencoded / binary body
binding (request.go:58-79, form_data_binder.go, multipart_file_bind.go), the
32 MB multipart memory cap (request.go:18), and hostname/params accessors.

Binding targets: ``dict`` (raw), dataclass types, or plain classes with
annotated fields. Form values are coerced to the annotated type (int, float,
bool, list) like the reference's reflect-based form mapper.
"""

from __future__ import annotations

import dataclasses
import io
import json
import typing
import urllib.parse
from email.parser import BytesParser
from email.policy import HTTP as HTTP_POLICY
from typing import Any

MAX_MULTIPART_MEMORY = 32 << 20  # 32 MB (request.go:18)


@dataclasses.dataclass
class UploadedFile:
    """A bound multipart file (multipart_file_bind.go)."""

    filename: str
    content_type: str
    content: bytes

    def read(self) -> bytes:
        return self.content

    def open(self) -> io.BytesIO:
        return io.BytesIO(self.content)


from gofr_tpu.http.errors import HTTPError
from gofr_tpu.logging.level import Level


class BindError(HTTPError):
    """Body-binding failures are client errors (400), like the reference's
    Bind error mapping (http/request.go:58-79)."""

    status_code = 400
    level = Level.INFO


class Request:
    """Adapts a raw HTTP request to the framework's Request contract
    (pkg/gofr/request.go:10-17): ``context``, ``param``, ``path_param``,
    ``bind``, ``host_name``."""

    def __init__(
        self,
        method: str,
        path: str,
        query: dict[str, list[str]],
        headers: dict[str, str],
        body: bytes,
        path_params: dict[str, str] | None = None,
        remote_addr: str = "",
    ) -> None:
        self.method = method.upper()
        self.path = path
        self.query = query
        self.headers = {k.lower(): v for k, v in headers.items()}
        self.body = body
        self.path_params = path_params or {}
        self.remote_addr = remote_addr

    # -- accessors (request.go:10-17) ----------------------------------------
    def param(self, key: str) -> str:
        vals = self.query.get(key)
        return vals[0] if vals else ""

    def params(self, key: str) -> list[str]:
        out: list[str] = []
        for v in self.query.get(key, []):
            out.extend(p for p in v.split(",") if p != "")
        return out

    def path_param(self, key: str) -> str:
        return self.path_params.get(key, "")

    def header(self, key: str) -> str:
        return self.headers.get(key.lower(), "")

    def host_name(self) -> str:
        proto = self.headers.get("x-forwarded-proto", "http")
        return f"{proto}://{self.headers.get('host', '')}"

    @property
    def content_type(self) -> str:
        return self.headers.get("content-type", "").split(";")[0].strip().lower()

    # -- binding (request.go:58-79) ------------------------------------------
    def bind(self, target: Any) -> Any:
        ct = self.content_type
        if ct == "application/json" or (not ct and self.body[:1] in (b"{", b"[")):
            return self._bind_json(target)
        if ct == "multipart/form-data":
            return self._bind_fields(self._parse_multipart(), target)
        if ct == "application/x-www-form-urlencoded":
            fields = {
                k: (vs[0] if len(vs) == 1 else vs)
                for k, vs in urllib.parse.parse_qs(
                    self.body.decode("utf-8", "replace"), keep_blank_values=True
                ).items()
            }
            return self._bind_fields(fields, target)
        if ct in ("application/octet-stream", "text/plain"):
            return self._bind_binary(target)
        raise BindError(f"unsupported Content-Type: {ct or '(none)'}")

    def _bind_json(self, target: Any) -> Any:
        try:
            data = json.loads(self.body.decode("utf-8")) if self.body else None
        except (json.JSONDecodeError, UnicodeDecodeError) as exc:
            raise BindError(f"invalid JSON body: {exc}") from exc
        if target is dict or target is None:
            return data
        if isinstance(target, dict):
            target.clear()
            if isinstance(data, dict):
                target.update(data)
            return target
        if isinstance(data, dict):
            return _construct(target, data)
        raise BindError("JSON body must be an object to bind into a struct")

    def _bind_binary(self, target: Any) -> Any:
        if target is bytes or target is None:
            return self.body
        if target is str:
            return self.body.decode("utf-8", "replace")
        raise BindError("binary body binds to bytes or str")

    def _parse_multipart(self) -> dict[str, Any]:
        if len(self.body) > MAX_MULTIPART_MEMORY:
            raise BindError("multipart body exceeds 32 MB limit")
        raw_ct = self.headers.get("content-type", "")
        header = (
            b"Content-Type: " + raw_ct.encode("latin-1") + b"\r\nMIME-Version: 1.0\r\n\r\n"
        )
        msg = BytesParser(policy=HTTP_POLICY).parsebytes(header + self.body)
        fields: dict[str, Any] = {}
        for part in msg.iter_parts():
            name = part.get_param("name", header="content-disposition")
            if not name:
                continue
            filename = part.get_filename()
            payload = part.get_payload(decode=True) or b""
            if filename:
                fields[name] = UploadedFile(
                    filename=filename,
                    content_type=part.get_content_type(),
                    content=payload,
                )
            else:
                fields[name] = payload.decode("utf-8", "replace")
        return fields

    def _bind_fields(self, fields: dict[str, Any], target: Any) -> Any:
        if target is dict or target is None:
            return fields
        if isinstance(target, dict):
            target.clear()
            target.update(fields)
            return target
        return _construct(target, fields, coerce=True)


def _construct(target: Any, data: dict[str, Any], coerce: bool = False) -> Any:
    """Build an instance of ``target`` from a field dict; unknown keys are
    ignored (reflect-based mapper semantics, form_data_binder.go)."""
    cls = target if isinstance(target, type) else type(target)
    hints = typing.get_type_hints(cls) if hasattr(cls, "__annotations__") else {}
    if dataclasses.is_dataclass(cls):
        names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, value in data.items():
            k = _match_field(key, names)
            if k is None:
                continue
            kwargs[k] = _coerce(value, hints.get(k)) if coerce else value
        try:
            obj = cls(**kwargs)
        except TypeError as exc:
            raise BindError(str(exc)) from exc
    else:
        obj = target if not isinstance(target, type) else _instantiate(cls)
        names = set(hints) | set(getattr(obj, "__dict__", {}))
        for key, value in data.items():
            k = _match_field(key, names)
            if k is None:
                continue
            setattr(obj, k, _coerce(value, hints.get(k)) if coerce else value)
    return obj


def _instantiate(cls: type) -> Any:
    try:
        return cls()
    except TypeError as exc:
        raise BindError(f"cannot instantiate {cls.__name__}: {exc}") from exc


def _match_field(key: str, names: set[str]) -> str | None:
    if key in names:
        return key
    lowered = key.lower().replace("-", "_")
    for n in names:
        if n.lower() == lowered:
            return n
    return None


def _coerce(value: Any, hint: Any) -> Any:
    if hint is None or isinstance(value, UploadedFile):
        return value
    origin = typing.get_origin(hint)
    if origin in (list, tuple):
        items = value if isinstance(value, list) else str(value).split(",")
        args = typing.get_args(hint)
        inner = args[0] if args else str
        return [_coerce(i, inner) for i in items]
    if hint is bool:
        return str(value).strip().lower() in ("1", "true", "yes", "on")
    if hint in (int, float, str):
        try:
            return hint(value)
        except (TypeError, ValueError) as exc:
            raise BindError(f"cannot convert {value!r} to {hint.__name__}") from exc
    return value

"""Typed HTTP errors with status codes and log levels.

Reference parity: pkg/gofr/http/errors.go (187 LoC) — ErrorInvalidRoute (404),
ErrorRequestTimeout (408), ErrorPanicRecovery (500), ErrorClientClosedRequest
(499), ErrorMissingParam, ErrorInvalidParam, ErrorEntityNotFound,
ErrorEntityAlreadyExist; errors carry both an HTTP status and the level they
log at (logging/logger.go:262-270).
"""

from __future__ import annotations

from typing import Any

from gofr_tpu.logging.level import Level


class HTTPError(Exception):
    """Base for framework errors: carries status_code and log level."""

    status_code: int = 500
    level: Level = Level.ERROR

    def __init__(self, message: str = "") -> None:
        super().__init__(message or self.__class__.default_message())
        self.message = message or self.__class__.default_message()

    @classmethod
    def default_message(cls) -> str:
        return "internal server error"

    def log_level(self) -> Level:
        return self.level

    def response_fields(self) -> dict[str, Any] | None:
        """Custom error payload fields (ResponseMarshaller analogue,
        http/responder.go:163-183). Override to add fields."""
        return None

    # retriable rejections (shed, drain) advertise when to come back;
    # the Responder copies these onto the wire response
    retry_after: float | None = None

    def response_headers(self) -> dict[str, str]:
        if self.retry_after is not None:
            import math

            return {"Retry-After": str(max(1, math.ceil(self.retry_after)))}
        return {}


class ErrorInvalidRoute(HTTPError):
    status_code = 404
    level = Level.INFO

    @classmethod
    def default_message(cls) -> str:
        return "route not registered"


class ErrorEntityNotFound(HTTPError):
    status_code = 404
    level = Level.INFO

    def __init__(self, name: str = "entity", value: str = "") -> None:
        self.name, self.value = name, value
        super().__init__(f"No entity found with {name}: {value}")


class ErrorEntityAlreadyExist(HTTPError):
    status_code = 409
    level = Level.WARN

    @classmethod
    def default_message(cls) -> str:
        return "entity already exists"


class ErrorInvalidParam(HTTPError):
    status_code = 400
    level = Level.INFO

    def __init__(self, *params: str) -> None:
        self.params = list(params)
        count = len(self.params)
        super().__init__(f"'{count}' invalid parameter(s): {', '.join(self.params)}")


class ErrorMissingParam(HTTPError):
    status_code = 400
    level = Level.INFO

    def __init__(self, *params: str) -> None:
        self.params = list(params)
        count = len(self.params)
        super().__init__(f"'{count}' missing parameter(s): {', '.join(self.params)}")


class ErrorValidation(HTTPError):
    status_code = 400
    level = Level.INFO

    def __init__(self, *errors: str) -> None:
        self.errors = list(errors)
        super().__init__("validation failed: " + "; ".join(self.errors))


class ErrorRequestTimeout(HTTPError):
    status_code = 408
    level = Level.INFO

    @classmethod
    def default_message(cls) -> str:
        return "request timed out"


class ErrorClientClosedRequest(HTTPError):
    status_code = 499
    level = Level.INFO

    @classmethod
    def default_message(cls) -> str:
        return "client closed request"


class ErrorPanicRecovery(HTTPError):
    status_code = 500
    level = Level.ERROR

    @classmethod
    def default_message(cls) -> str:
        return "some unexpected error has occurred"


class ErrorServiceUnavailable(HTTPError):
    status_code = 503
    level = Level.WARN

    def __init__(self, message: str = "", *, retry_after: float | None = None) -> None:
        super().__init__(message)
        self.retry_after = retry_after

    @classmethod
    def default_message(cls) -> str:
        return "service unavailable"


class ErrorTooManyRequests(HTTPError):
    """TPU-build addition: admission control rejection when the batch queue
    is saturated (continuous-batching backpressure) or the shed estimator
    predicts the request would wait past its deadline. ``retry_after``
    (seconds) is the estimator's predicted queue wait; it reaches clients
    as a ``Retry-After`` header (HTTP) / retry-delay detail (gRPC)."""

    status_code = 429
    level = Level.WARN

    def __init__(self, message: str = "", *, retry_after: float | None = None) -> None:
        super().__init__(message)
        self.retry_after = retry_after

    def response_fields(self) -> dict[str, Any] | None:
        if self.retry_after is not None:
            return {"retry_after_s": round(self.retry_after, 3)}
        return None

    @classmethod
    def default_message(cls) -> str:
        return "server overloaded, retry later"


class ErrorRequestEntityTooLarge(HTTPError):
    """TPU-build addition: the request can NEVER be served by this
    replica's configuration — a prompt needing more KV pages than the
    whole pool holds, however empty. Deliberately NOT a 429: 429 invites
    clients to retry a permanent condition forever. 413 (and gRPC
    ``FAILED_PRECONDITION``) tells them to shrink the request or find a
    bigger replica; no ``Retry-After`` is ever attached."""

    status_code = 413
    level = Level.INFO

    @classmethod
    def default_message(cls) -> str:
        return "request exceeds this replica's serving capacity"


class ErrorStaleEpoch(HTTPError):
    """HA-plane addition (docs/robustness.md "The HA plane"): the caller
    presented a fence epoch older than the replica's current one — it is
    acting on membership state from before a ``warm_restart`` /
    ``begin_reclaim`` / re-registration, so its view of this replica's
    scheduler, KV residency and request registry is stale. 409 and NOT
    retriable: a fenced zombie must refresh its membership view (the
    heartbeat gossips the current epoch), never blind-retry the same
    stale claim."""

    status_code = 409
    level = Level.WARN

    @classmethod
    def default_message(cls) -> str:
        return "stale fence epoch; refresh membership and retry"


class ErrorDeadlineExceeded(HTTPError):
    """Request-lifecycle addition: the caller's deadline passed before the
    request produced a result (expired in queue, or shed at admission after
    queueing). Mid-stream expiry instead resolves normally with finish
    reason ``deadline_exceeded``. 504: the server accepted but could not
    complete in time — distinct from 408 (client idle) and 429 (rejected
    up front)."""

    status_code = 504
    level = Level.INFO

    @classmethod
    def default_message(cls) -> str:
        return "deadline exceeded before completion"


def status_from_error(err: BaseException | None, method: str, has_data: bool) -> int:
    """Map (error, method) -> HTTP status (http/responder.go:102-159):
    no error: GET/PUT/PATCH→200, POST→201 (202 when partial), DELETE→204;
    typed errors use their own status; unknown errors → 500; data+error →
    206 partial content."""
    if err is None:
        if method == "POST":
            return 201
        if method == "DELETE":
            return 204
        return 200
    if has_data:
        return 206
    if isinstance(err, HTTPError):
        return err.status_code
    status = getattr(err, "status_code", None)
    if isinstance(status, int) and 100 <= status <= 599:
        return status
    return 500

"""HTTP plumbing (reference: pkg/gofr/http/)."""

from gofr_tpu.http.errors import (
    ErrorClientClosedRequest,
    ErrorEntityAlreadyExist,
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorInvalidRoute,
    ErrorMissingParam,
    ErrorPanicRecovery,
    ErrorRequestTimeout,
    ErrorServiceUnavailable,
    ErrorTooManyRequests,
    HTTPError,
)
from gofr_tpu.http.request import Request, UploadedFile
from gofr_tpu.http.responder import Responder, WireResponse
from gofr_tpu.http.router import Router

__all__ = [
    "HTTPError",
    "ErrorInvalidRoute",
    "ErrorEntityNotFound",
    "ErrorEntityAlreadyExist",
    "ErrorInvalidParam",
    "ErrorMissingParam",
    "ErrorRequestTimeout",
    "ErrorClientClosedRequest",
    "ErrorPanicRecovery",
    "ErrorServiceUnavailable",
    "ErrorTooManyRequests",
    "Request",
    "UploadedFile",
    "Responder",
    "WireResponse",
    "Router",
]

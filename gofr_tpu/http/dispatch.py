"""Route dispatch: wire request → Context → handler → wire response.

This is the glue the reference spreads across http_server.go:36-59 and
handler.go:55-113: router lookup (including static files and the catch-all
404), Context construction, timeout from ``REQUEST_TIMEOUT``, and rendering
through the Responder.
"""

from __future__ import annotations

import asyncio
import mimetypes
import os
from typing import Any

from gofr_tpu.context import Context
from gofr_tpu.handler import catch_all_handler, execute_handler
from gofr_tpu.http.responder import Responder, WireResponse, draining_response
from gofr_tpu.http.router import Router


class Dispatcher:
    def __init__(self, router: Router, container: Any, request_timeout: float | None = None) -> None:
        self.router = router
        self.container = container
        self.responder = Responder()
        self.request_timeout = request_timeout

    # probe routes stay served while draining so load balancers can SEE the
    # DRAINING state instead of inferring it from connection errors
    _DRAIN_EXEMPT = ("/.well-known/health", "/.well-known/alive")

    async def __call__(self, req: Any) -> WireResponse:
        if getattr(self.container, "draining", False) and req.path not in self._DRAIN_EXEMPT:
            return draining_response()
        # static files first-match after routes (router.go:66-78)
        match = self.router.lookup(req.method, req.path)
        if match is None:
            static = self.router.static_lookup(req.path)
            if static is not None:
                return await self._serve_static(static)
            if req.method == "HEAD":
                match_get = self.router.lookup("GET", req.path)
                if match_get is not None:
                    match = match_get
        if match is None:
            if self.router.path_exists(req.path):
                return WireResponse(
                    status=405,
                    headers={"Content-Type": "application/json"},
                    body=b'{"error":{"message":"method not allowed"}}',
                )
            handler, params = catch_all_handler, {}
        else:
            handler, params = match
        req.path_params = params

        ctx = Context(req, self.container, self.responder)
        result = await execute_handler(handler, ctx, self.request_timeout)

        if isinstance(result.data, WireResponse):  # raw wire responses (streams)
            return result.data
        return self.responder.respond(result.data, result.error, req.method)

    async def _serve_static(self, static: tuple[str, str]) -> WireResponse:
        path, disposition = static
        if disposition == "forbidden":
            return WireResponse(status=403, body=b"403 forbidden")
        ctype = mimetypes.guess_type(path)[0] or "application/octet-stream"

        def _read() -> bytes:  # sync file I/O runs off the event loop
            with open(path, "rb") as f:
                return f.read()

        try:
            content = await asyncio.get_running_loop().run_in_executor(None, _read)
        except OSError:
            return WireResponse(status=404, body=b"404 not found")
        status = 200 if disposition == "ok" else 404
        return WireResponse(status=status, headers={"Content-Type": ctype}, body=content)

"""Swagger / OpenAPI rendering.

Reference parity: pkg/gofr/swagger.go:15-70 — when ``./static/openapi.json``
exists it is served at ``/.well-known/openapi.json`` and an embedded
Swagger-UI page at ``/.well-known/swagger``. The UI here is a minimal
self-contained HTML page that loads the spec and renders it via the
swagger-ui CDN when reachable, with a plain JSON fallback (zero vendored
assets instead of go:embed)."""

from __future__ import annotations

import json
from typing import Any, Callable

from gofr_tpu.http.response import File, Raw

_UI_TEMPLATE = """<!DOCTYPE html>
<html>
<head>
  <title>API Documentation</title>
  <link rel="stylesheet" href="https://unpkg.com/swagger-ui-dist@5/swagger-ui.css">
</head>
<body>
  <div id="swagger-ui"><pre id="fallback" style="display:none"></pre></div>
  <script src="https://unpkg.com/swagger-ui-dist@5/swagger-ui-bundle.js"></script>
  <script>
    if (window.SwaggerUIBundle) {
      SwaggerUIBundle({url: '/.well-known/openapi.json', dom_id: '#swagger-ui'});
    } else {
      fetch('/.well-known/openapi.json').then(r => r.json()).then(spec => {
        const el = document.getElementById('fallback');
        el.style.display = 'block';
        el.textContent = JSON.stringify(spec, null, 2);
      });
    }
  </script>
</body>
</html>
"""


def swagger_handlers(spec_path: str) -> tuple[Callable, Callable]:
    def spec_handler(ctx: Any) -> Any:
        with open(spec_path, encoding="utf-8") as f:
            return Raw(json.load(f))

    def ui_handler(ctx: Any) -> Any:
        return File(content=_UI_TEMPLATE.encode(), content_type="text/html")

    return spec_handler, ui_handler

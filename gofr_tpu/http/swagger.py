"""Swagger / OpenAPI rendering.

Reference parity: pkg/gofr/swagger.go:15-70 + pkg/gofr/static/ — when
``./static/openapi.json`` exists it is served at
``/.well-known/openapi.json`` and an **embedded** UI at
``/.well-known/swagger``. The UI asset (http/static/swagger_ui.html) is
a fully self-contained vanilla-JS OpenAPI explorer — grouped operations,
parameter/schema tables, sample bodies resolved through ``$ref``, and
try-it-out execution — shipped in the package like the reference's
go:embed bundle; no CDN or external fetch is ever made."""

from __future__ import annotations

import functools
import json
import os
from typing import Any, Callable

from gofr_tpu.http.response import File, Raw

_UI_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "static", "swagger_ui.html")


@functools.lru_cache(maxsize=1)
def swagger_ui_html() -> bytes:
    # immutable at runtime — load once, like go:embed
    with open(_UI_PATH, "rb") as f:
        return f.read()


def swagger_handlers(spec_path: str) -> tuple[Callable, Callable]:
    def spec_handler(ctx: Any) -> Any:
        with open(spec_path, encoding="utf-8") as f:
            return Raw(json.load(f))

    def ui_handler(ctx: Any) -> Any:
        return File(content=swagger_ui_html(), content_type="text/html")

    return spec_handler, ui_handler

"""Auth middleware: Basic, API-key, OAuth/JWT.

Reference parity: pkg/gofr/http/middleware/{auth,basic_auth,apikey_auth,
oauth}.go — pluggable AuthProvider (auth.go:32-35), the generic middleware
that skips ``/.well-known/*`` routes (auth.go:38-57), Basic auth with
plain-map / validate-func / validate-with-container variants
(basic_auth.go:13-68), API-key auth with the same variants (apikey_auth.go),
and OAuth with JWKS refresh + claims into the context (oauth.go:33-148).
"""

from __future__ import annotations

import base64
import binascii
import hmac
import json
from typing import Any, Callable, Protocol

from gofr_tpu.context import AuthInfo
from gofr_tpu.http.middleware.core import Middleware, WireHandler
from gofr_tpu.http.middleware import jwt as jwtlib
from gofr_tpu.http.responder import WireResponse

WELL_KNOWN = "/.well-known/"


class AuthProvider(Protocol):
    """auth.go:32-35."""

    def get_auth_method(self) -> str: ...

    def authenticate(self, req: Any) -> AuthInfo | None: ...


def _unauthorized(message: str = "Unauthorized") -> WireResponse:
    return WireResponse(
        status=401,
        headers={"Content-Type": "application/json", "WWW-Authenticate": "Basic"},
        body=json.dumps({"error": {"message": message}}).encode(),
    )


def _auth_middleware(provider: AuthProvider) -> Middleware:
    """Generic auth wrapper (auth.go:38-57): probe routes stay open."""

    def mw(inner: WireHandler) -> WireHandler:
        async def handle(req: Any) -> WireResponse:
            if req.path.startswith(WELL_KNOWN) or req.method == "OPTIONS":
                return await inner(req)
            info = provider.authenticate(req)
            if info is None:
                return _unauthorized()
            req.auth = info
            return await inner(req)

        return handle

    return mw


class BasicAuthProvider:
    """basic_auth.go:13-68. Exactly one of ``users`` (user->password map),
    ``validate_func`` (user, pass) -> bool, or ``validate_with_container``
    (container, user, pass) -> bool."""

    def __init__(
        self,
        users: dict[str, str] | None = None,
        validate_func: Callable[[str, str], bool] | None = None,
        validate_with_container: Callable[[Any, str, str], bool] | None = None,
        container: Any = None,
    ) -> None:
        self.users = users or {}
        self.validate_func = validate_func
        self.validate_with_container = validate_with_container
        self.container = container

    def get_auth_method(self) -> str:
        return "basic"

    def authenticate(self, req: Any) -> AuthInfo | None:
        header = req.header("authorization")
        if not header.lower().startswith("basic "):
            return None
        try:
            decoded = base64.b64decode(header[6:].strip()).decode("utf-8")
        except (binascii.Error, UnicodeDecodeError):
            return None
        if ":" not in decoded:
            return None
        username, _, password = decoded.partition(":")
        if self.validate_with_container is not None:
            ok = self.validate_with_container(self.container, username, password)
        elif self.validate_func is not None:
            ok = self.validate_func(username, password)
        else:
            expected = self.users.get(username)
            ok = expected is not None and hmac.compare_digest(expected, password)
        return AuthInfo(method="basic", username=username) if ok else None


class APIKeyAuthProvider:
    """apikey_auth.go: keys from a static list or a validator."""

    def __init__(
        self,
        keys: list[str] | None = None,
        validate_func: Callable[[str], bool] | None = None,
        validate_with_container: Callable[[Any, str], bool] | None = None,
        container: Any = None,
    ) -> None:
        self.keys = set(keys or [])
        self.validate_func = validate_func
        self.validate_with_container = validate_with_container
        self.container = container

    def get_auth_method(self) -> str:
        return "apikey"

    def authenticate(self, req: Any) -> AuthInfo | None:
        key = req.header("x-api-key")
        if not key:
            return None
        if self.validate_with_container is not None:
            ok = self.validate_with_container(self.container, key)
        elif self.validate_func is not None:
            ok = self.validate_func(key)
        else:
            ok = key in self.keys
        return AuthInfo(method="apikey", api_key=key) if ok else None


class OAuthProvider:
    """oauth.go:33-148: Bearer JWT validated against a JWKS endpoint (RS256)
    or a shared secret (HS256); claims exposed via ctx.get_auth_info()."""

    def __init__(
        self,
        jwks_url: str | None = None,
        jwks_provider: Any = None,
        hs_secret: str | None = None,
        issuer: str | None = None,
        audience: str | None = None,
        refresh_interval: float = 3600.0,
    ) -> None:
        self.jwks = jwks_provider
        if self.jwks is None and jwks_url:
            self.jwks = jwtlib.JWKSProvider(jwks_url, refresh_interval)
        self.hs_secret = hs_secret
        self.issuer = issuer
        self.audience = audience

    def get_auth_method(self) -> str:
        return "oauth"

    def authenticate(self, req: Any) -> AuthInfo | None:
        header = req.header("authorization")
        if not header.lower().startswith("bearer "):
            return None
        token = header[7:].strip()
        try:
            claims = jwtlib.decode(
                token,
                hs_secret=self.hs_secret,
                rsa_keys=self.jwks.keys() if self.jwks else None,
                issuer=self.issuer,
                audience=self.audience,
            )
        except jwtlib.JWTError:
            return None
        return AuthInfo(method="oauth", username=str(claims.get("sub", "")), claims=claims)


def basic_auth_middleware(**kw: Any) -> Middleware:
    return _auth_middleware(BasicAuthProvider(**kw))


def api_key_auth_middleware(**kw: Any) -> Middleware:
    return _auth_middleware(APIKeyAuthProvider(**kw))


def oauth_middleware(**kw: Any) -> Middleware:
    return _auth_middleware(OAuthProvider(**kw))


def auth_middleware(provider: AuthProvider) -> Middleware:
    return _auth_middleware(provider)

"""HTTP middleware chain.

Reference parity: pkg/gofr/http/middleware/ — registered in the order
Tracer → Logging → CORS → Metrics (http_server.go:36-41), then optional auth
and the WebSocket upgrade. A middleware here is
``Callable[[next_handler], handler]`` over async wire handlers.
"""

from gofr_tpu.http.middleware.core import (
    Middleware,
    WireHandler,
    chain,
    cors_middleware,
    logging_middleware,
    metrics_middleware,
    tracing_middleware,
)
from gofr_tpu.http.middleware.auth import (
    AuthProvider,
    api_key_auth_middleware,
    basic_auth_middleware,
    oauth_middleware,
)

__all__ = [
    "Middleware",
    "WireHandler",
    "chain",
    "tracing_middleware",
    "logging_middleware",
    "cors_middleware",
    "metrics_middleware",
    "AuthProvider",
    "basic_auth_middleware",
    "api_key_auth_middleware",
    "oauth_middleware",
]

"""Core middleware: tracing, logging, CORS, metrics.

Reference parity:
- tracing: middleware/tracer.go:15-32 — extract W3C tracecontext, start span
  ``"METHOD /path"``.
- logging: middleware/logger.go — per-request structured log with trace id,
  span id, µs latency, client IP from X-Forwarded-For (:118-170), panic
  recovery to a 500 JSON (:177-201), probe-path suppression (:142-156),
  X-Correlation-ID response header (:101).
- CORS: middleware/cors.go:13-57 — defaults '*' + methods from registered
  routes, overridable via ACCESS_CONTROL_* configs (middleware/config.go:29-41).
- metrics: middleware/metrics.go:22-54 — app_http_response histogram with
  path-template/method/status labels.
"""

from __future__ import annotations

import io
import json
import time
import traceback
from typing import Any, Awaitable, Callable

from gofr_tpu.http.responder import WireResponse
from gofr_tpu.tracing.trace import extract_traceparent

# A wire handler maps the parsed request to a WireResponse.
WireHandler = Callable[[Any], Awaitable[WireResponse]]
Middleware = Callable[[WireHandler], WireHandler]

PROBE_PATHS = ("/.well-known/health", "/.well-known/alive", "/favicon.ico")


def chain(handler: WireHandler, middlewares: list[Middleware]) -> WireHandler:
    """Wrap ``handler`` so the first middleware in the list runs outermost
    (http_server.go:36-41 ordering)."""
    for mw in reversed(middlewares):
        handler = mw(handler)
    return handler


def tracing_middleware(tracer: Any) -> Middleware:
    def mw(inner: WireHandler) -> WireHandler:
        async def handle(req: Any) -> WireResponse:
            remote = extract_traceparent(req.header("traceparent"))
            span = tracer.start_span(
                f"{req.method} {req.path}",
                remote_trace_id=remote[0] if remote else None,
                remote_span_id=remote[1] if remote else None,
                kind="server",
            )
            try:
                with span:
                    span.set_attribute("http.method", req.method)
                    span.set_attribute("http.target", req.path)
                    resp = await inner(req)
                    span.set_attribute("http.status_code", resp.status)
                    if resp.status >= 500:
                        span.set_status("ERROR", f"HTTP {resp.status}")
                    return resp
            finally:
                pass

        return handle

    return mw


class RequestLog:
    """The per-request log payload (middleware/logger.go:60-91), pretty-
    printable for terminals."""

    def __init__(self, method: str, uri: str, status: int, duration_us: int, ip: str,
                 trace_id: str = "", span_id: str = "") -> None:
        self.method = method
        self.uri = uri
        self.response = status
        self.response_time = duration_us
        self.ip = ip
        self.trace_id = trace_id
        self.span_id = span_id

    def pretty_print(self, writer: io.TextIOBase) -> None:
        color = 34 if self.response < 400 else (33 if self.response < 500 else 31)
        writer.write(
            f"\x1b[{color}m{self.response}\x1b[0m "
            f"{self.response_time:>8}µs {self.method:>6} {self.uri}"
        )

    def __str__(self) -> str:
        return f"{self.response} {self.response_time}µs {self.method} {self.uri}"


def logging_middleware(logger: Any, *, suppress_probes: bool | None = None, config: Any = None) -> Middleware:
    if suppress_probes is None:
        suppress_probes = True
        if config is not None:
            suppress_probes = config.get_or_default("LOG_DISABLE_PROBES", "true").lower() == "true"

    def mw(inner: WireHandler) -> WireHandler:
        async def handle(req: Any) -> WireResponse:
            start = time.perf_counter_ns()
            try:
                resp = await inner(req)
            except Exception as exc:
                # panic recovery → 500 JSON (logger.go:177-201)
                logger.error(
                    f"panic in middleware chain: {exc}",
                    stack=traceback.format_exc(limit=20),
                )
                resp = WireResponse(
                    status=500,
                    headers={"Content-Type": "application/json"},
                    body=json.dumps(
                        {"error": {"message": "some unexpected error has occurred"}}
                    ).encode(),
                )
            duration_us = (time.perf_counter_ns() - start) // 1000
            if suppress_probes and req.path in PROBE_PATHS:
                return resp
            from gofr_tpu.tracing.trace import current_span

            span = current_span()
            ip = req.header("x-forwarded-for").split(",")[0].strip() or req.remote_addr
            entry = RequestLog(
                req.method, req.path, resp.status, duration_us, ip,
                trace_id=span.trace_id if span else "",
                span_id=span.span_id if span else "",
            )
            kw = {"trace_id": entry.trace_id or None, "span_id": entry.span_id or None}
            if resp.status >= 500:
                logger.error(entry, **kw)
            else:
                logger.info(entry, **kw)
            return resp

        return handle

    return mw


class CORSConfig:
    """ACCESS_CONTROL_* overrides (middleware/config.go:29-41)."""

    HEADER_NAMES = (
        "Access-Control-Allow-Origin",
        "Access-Control-Allow-Headers",
        "Access-Control-Allow-Methods",
        "Access-Control-Allow-Credentials",
        "Access-Control-Expose-Headers",
        "Access-Control-Max-Age",
    )

    def __init__(self, config: Any = None) -> None:
        self.overrides: dict[str, str] = {}
        if config is not None:
            for header in self.HEADER_NAMES:
                env_key = header.upper().replace("-", "_")
                val = config.get(env_key)
                if val:
                    self.overrides[header] = val


def cors_middleware(cors: CORSConfig | None = None, router: Any = None) -> Middleware:
    cors = cors or CORSConfig()

    def mw(inner: WireHandler) -> WireHandler:
        async def handle(req: Any) -> WireResponse:
            if req.method == "OPTIONS":
                resp = WireResponse(status=200)
            else:
                resp = await inner(req)
            resp.headers.setdefault(
                "Access-Control-Allow-Origin", cors.overrides.get("Access-Control-Allow-Origin", "*")
            )
            methods = cors.overrides.get("Access-Control-Allow-Methods")
            if not methods and router is not None:
                registered = router.registered_methods()
                methods = ", ".join(registered + ["OPTIONS"]) if registered else None
            if methods:
                resp.headers.setdefault("Access-Control-Allow-Methods", methods)
            resp.headers.setdefault(
                "Access-Control-Allow-Headers",
                cors.overrides.get(
                    "Access-Control-Allow-Headers",
                    "Authorization, Content-Type, x-requested-with, origin, true-client-ip, X-Correlation-ID",
                ),
            )
            for header in ("Access-Control-Allow-Credentials", "Access-Control-Expose-Headers", "Access-Control-Max-Age"):
                if header in cors.overrides:
                    resp.headers.setdefault(header, cors.overrides[header])
            return resp

        return handle

    return mw


def metrics_middleware(metrics: Any, router: Any = None) -> Middleware:
    def mw(inner: WireHandler) -> WireHandler:
        async def handle(req: Any) -> WireResponse:
            start = time.perf_counter()
            resp = await inner(req)
            elapsed = time.perf_counter() - start
            path = req.path
            if router is not None:
                path = router.route_template(req.method, req.path) or _normalize_static(path)
            metrics.record_histogram(
                "app_http_response", elapsed,
                path=path, method=req.method, status=str(resp.status),
            )
            return resp

        return handle

    return mw


def _normalize_static(path: str) -> str:
    """Collapse static asset paths to one label value
    (middleware/metrics.go static normalization)."""
    if "." in path.rsplit("/", 1)[-1]:
        return path.rsplit("/", 1)[0] + "/<asset>"
    return path

"""Minimal JWT + JWKS verification (no external jwt dependency).

Reference parity: the reference uses golang-jwt/jwt/v5 + a background JWKS
refresher (middleware/oauth.go:33-101 refresh loop, :138-148 parse+claims).
Supported algs: HS256 (shared secret) and RS256 (JWKS / PEM public key via
``cryptography``). Validates ``exp``/``nbf`` and optional issuer/audience.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import threading
import time
import urllib.request
from typing import Any

try:
    from cryptography.exceptions import InvalidSignature
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    _HAS_CRYPTO = True
except ImportError:  # image without cryptography: HS256 (pure stdlib)
    InvalidSignature = hashes = padding = rsa = None  # type: ignore[assignment]
    _HAS_CRYPTO = False


class JWTError(Exception):
    pass


def _b64url_decode(data: str) -> bytes:
    padded = data + "=" * (-len(data) % 4)
    return base64.urlsafe_b64decode(padded.encode("ascii"))


def _b64url_encode(data: bytes) -> str:
    return base64.urlsafe_b64encode(data).rstrip(b"=").decode("ascii")


def encode_hs256(claims: dict, secret: str | bytes, headers: dict | None = None) -> str:
    """Issue an HS256 token (used by tests and the oauth client-credentials
    test fixture)."""
    if isinstance(secret, str):
        secret = secret.encode()
    header = {"alg": "HS256", "typ": "JWT", **(headers or {})}
    h = _b64url_encode(json.dumps(header, separators=(",", ":")).encode())
    p = _b64url_encode(json.dumps(claims, separators=(",", ":")).encode())
    sig = hmac.new(secret, f"{h}.{p}".encode(), hashlib.sha256).digest()
    return f"{h}.{p}.{_b64url_encode(sig)}"


def decode(
    token: str,
    *,
    hs_secret: str | bytes | None = None,
    rsa_keys: dict[str, rsa.RSAPublicKey] | None = None,
    issuer: str | None = None,
    audience: str | None = None,
    leeway: float = 30.0,
) -> dict[str, Any]:
    """Verify and decode a JWT, returning its claims."""
    parts = token.split(".")
    if len(parts) != 3:
        raise JWTError("malformed token")
    try:
        header = json.loads(_b64url_decode(parts[0]))
        claims = json.loads(_b64url_decode(parts[1]))
        signature = _b64url_decode(parts[2])
    except (ValueError, json.JSONDecodeError) as exc:
        raise JWTError(f"malformed token: {exc}") from exc

    signing_input = f"{parts[0]}.{parts[1]}".encode()
    alg = header.get("alg")
    if alg == "HS256":
        if hs_secret is None:
            raise JWTError("HS256 token but no shared secret configured")
        secret = hs_secret.encode() if isinstance(hs_secret, str) else hs_secret
        expected = hmac.new(secret, signing_input, hashlib.sha256).digest()
        if not hmac.compare_digest(expected, signature):
            raise JWTError("signature verification failed")
    elif alg == "RS256":
        if not _HAS_CRYPTO:
            raise JWTError("RS256 token but the cryptography package is unavailable")
        if not rsa_keys:
            raise JWTError("RS256 token but no JWKS configured")
        kid = header.get("kid")
        candidates = [rsa_keys[kid]] if kid and kid in rsa_keys else list(rsa_keys.values())
        if not candidates:
            raise JWTError(f"no key for kid {kid}")
        for key in candidates:
            try:
                key.verify(signature, signing_input, padding.PKCS1v15(), hashes.SHA256())
                break
            except InvalidSignature:
                continue
        else:
            raise JWTError("signature verification failed")
    else:
        raise JWTError(f"unsupported alg {alg}")

    now = time.time()
    if "exp" in claims and now > float(claims["exp"]) + leeway:
        raise JWTError("token expired")
    if "nbf" in claims and now < float(claims["nbf"]) - leeway:
        raise JWTError("token not yet valid")
    if issuer is not None and claims.get("iss") != issuer:
        raise JWTError("issuer mismatch")
    if audience is not None:
        aud = claims.get("aud")
        auds = aud if isinstance(aud, list) else [aud]
        if audience not in auds:
            raise JWTError("audience mismatch")
    return claims


def jwk_to_rsa_key(jwk: dict) -> rsa.RSAPublicKey:
    if not _HAS_CRYPTO:
        raise JWTError("JWKS keys need the cryptography package")
    n = int.from_bytes(_b64url_decode(jwk["n"]), "big")
    e = int.from_bytes(_b64url_decode(jwk["e"]), "big")
    return rsa.RSAPublicNumbers(e, n).public_key()


class JWKSProvider:
    """Fetches a JWKS endpoint and refreshes on an interval in a daemon
    thread (oauth.go:33-101)."""

    def __init__(self, url: str, refresh_interval: float = 3600.0, timeout: float = 5.0) -> None:
        self.url = url
        self.timeout = timeout
        self._keys: dict[str, rsa.RSAPublicKey] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.refresh()
        self._thread = threading.Thread(target=self._loop, args=(refresh_interval,), daemon=True, name="jwks-refresh")
        self._thread.start()

    def refresh(self) -> None:
        try:
            with urllib.request.urlopen(self.url, timeout=self.timeout) as resp:
                data = json.loads(resp.read().decode())
        except Exception:
            return
        keys: dict[str, rsa.RSAPublicKey] = {}
        for jwk in data.get("keys", []):
            if jwk.get("kty") != "RSA":
                continue
            try:
                keys[jwk.get("kid", str(len(keys)))] = jwk_to_rsa_key(jwk)
            except (KeyError, ValueError):
                continue
        if keys:
            with self._lock:
                self._keys = keys

    def keys(self) -> dict[str, rsa.RSAPublicKey]:
        with self._lock:
            return dict(self._keys)

    def _loop(self, interval: float) -> None:
        while not self._stop.wait(interval):
            self.refresh()

    def close(self) -> None:
        self._stop.set()

"""Response wrapper types rendered by the Responder.

Reference parity: pkg/gofr/http/response/{file,raw,redirect,template,
response}.go — returning one of these from a handler short-circuits the
default JSON envelope.
"""

from __future__ import annotations

import dataclasses
import string
from typing import Any


@dataclasses.dataclass
class Response:
    """JSON envelope with metadata and custom headers
    (response/response.go)."""

    data: Any = None
    metadata: dict[str, Any] | None = None
    headers: dict[str, str] | None = None


@dataclasses.dataclass
class Raw:
    """Marshal ``data`` as-is, without the {"data": ...} envelope
    (response/raw.go)."""

    data: Any = None


@dataclasses.dataclass
class File:
    """Binary body with content type (response/file.go)."""

    content: bytes = b""
    content_type: str = "application/octet-stream"


@dataclasses.dataclass
class Redirect:
    """302 redirect (response/redirect.go)."""

    url: str = "/"


@dataclasses.dataclass
class Template:
    """Render ``$variable``-substituted template file from ./templates
    (response/template.go; html/template swapped for string.Template)."""

    data: dict[str, Any] | None = None
    name: str = ""
    directory: str = "./templates"

    def render(self) -> str:
        with open(f"{self.directory}/{self.name}", encoding="utf-8") as f:
            tpl = string.Template(f.read())
        return tpl.safe_substitute(self.data or {})

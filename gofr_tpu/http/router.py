"""HTTP router with path templates, per-route middleware, static files.

Reference parity: pkg/gofr/http/router.go — route registration wrapped in
tracing (:46-49), registered-method tracking for CORS (:29-48), static file
serving with 404.html support and the openapi.json restriction (:66-113).
Pattern syntax is the reference's mux style: ``/user/{id}`` path parameters
plus a trailing wildcard ``/static/{path...}``.
"""

from __future__ import annotations

import os
import re
from typing import Any, Callable

WELL_KNOWN_PREFIX = "/.well-known"
DEFAULT_SWAGGER_FILE = "openapi.json"


class Route:
    def __init__(self, method: str, pattern: str, handler: Any) -> None:
        self.method = method.upper()
        self.pattern = pattern
        self.handler = handler
        self.regex, self.param_names = _compile(pattern)

    def match(self, path: str) -> dict[str, str] | None:
        m = self.regex.match(path)
        if not m:
            return None
        return {name: m.group(name) for name in self.param_names}


def _compile(pattern: str) -> tuple[re.Pattern, list[str]]:
    parts: list[str] = []
    names: list[str] = []
    i = 0
    for seg in pattern.split("/"):
        if not seg:
            continue
        i += 1
        if seg.startswith("{") and seg.endswith("...}"):
            name = seg[1:-4]
            names.append(name)
            parts.append(f"(?P<{name}>.+)")
        elif seg.startswith("{") and seg.endswith("}"):
            name = seg[1:-1]
            names.append(name)
            parts.append(f"(?P<{name}>[^/]+)")
        else:
            parts.append(re.escape(seg))
    body = "/".join(parts)
    regex = re.compile("^/" + body + "/?$" if body else "^/$")
    return regex, names


class Router:
    """Method+path router. Middlewares registered via ``use_middleware`` wrap
    the matched handler outermost-first, mirroring the reference's chain
    (http_server.go:36-41)."""

    def __init__(self) -> None:
        self.routes: list[Route] = []
        self.middlewares: list[Callable] = []
        self._static_dirs: list[tuple[str, str]] = []  # (url_prefix, fs_dir)

    def add(self, method: str, pattern: str, handler: Any) -> None:
        self.routes.append(Route(method, pattern, handler))

    def use_middleware(self, *mws: Callable) -> None:
        self.middlewares.extend(mws)

    def registered_methods(self, path: str | None = None) -> list[str]:
        """Methods registered (optionally for one path) — feeds CORS
        Access-Control-Allow-Methods (router.go:29-48)."""
        methods = {
            r.method
            for r in self.routes
            if path is None or r.match(path) is not None
        }
        return sorted(methods)

    def add_static_files(self, url_prefix: str, fs_dir: str) -> None:
        """Serve a directory (router.go:66-78). openapi.json is only served
        via /.well-known/openapi.json, and a 404.html in the directory is
        used for missing files (router.go:92-113)."""
        self._static_dirs.append((url_prefix.rstrip("/"), os.path.abspath(fs_dir)))

    def lookup(self, method: str, path: str) -> tuple[Any, dict[str, str]] | None:
        for r in self.routes:
            if r.method != method.upper():
                continue
            params = r.match(path)
            if params is not None:
                return r.handler, params
        return None

    def path_exists(self, path: str) -> bool:
        return any(r.match(path) is not None for r in self.routes)

    def route_template(self, method: str, path: str) -> str | None:
        """The registered pattern a path matched — used as the low-cardinality
        metric label (middleware/metrics.go path templating)."""
        for r in self.routes:
            if r.method == method.upper() and r.match(path) is not None:
                return r.pattern
        return None

    def static_lookup(self, path: str) -> tuple[str, str] | None:
        """Resolve a static file. Returns (file_path, disposition) where
        disposition is 'ok' | 'not_found_page' | 'forbidden'."""
        for prefix, fs_dir in self._static_dirs:
            if not path.startswith(prefix + "/") and path != prefix:
                continue
            rel = path[len(prefix):].lstrip("/") or "index.html"
            if os.path.basename(rel) == DEFAULT_SWAGGER_FILE:
                return os.path.join(fs_dir, rel), "forbidden"
            full = os.path.normpath(os.path.join(fs_dir, rel))
            if not full.startswith(fs_dir):
                continue  # path traversal
            if os.path.isfile(full):
                return full, "ok"
            fallback = os.path.join(fs_dir, "404.html")
            if os.path.isfile(fallback):
                return fallback, "not_found_page"
        return None

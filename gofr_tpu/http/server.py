"""Asyncio HTTP/1.1 server.

The reference rides Go's net/http (http_server.go:17-121); Python's stdlib has
no production-grade async server, so this build ships its own: HTTP/1.1
parsing, keep-alive, Content-Length and chunked bodies, chunked/SSE streaming
responses (the token-decode path), optional TLS (CERT_FILE/KEY_FILE,
factory.go:43-44), and a WebSocket upgrade hook. One connection = one asyncio
task — the analogue of net/http's goroutine-per-connection.

Streaming: a WireResponse with ``stream`` set to an async iterator of bytes
is sent with ``Transfer-Encoding: chunked``, flushed per chunk — this is how
token-by-token decode reaches HTTP clients (SURVEY §7 phase 4).
"""

from __future__ import annotations

import asyncio
import ssl
import urllib.parse
from typing import Any, Awaitable, Callable

from gofr_tpu.http.request import Request
from gofr_tpu.http.responder import WireResponse

MAX_HEADER_BYTES = 64 * 1024
MAX_BODY_BYTES = 256 << 20  # generous: model uploads go through file APIs

STATUS_TEXT = {
    200: "OK", 201: "Created", 202: "Accepted", 204: "No Content",
    206: "Partial Content", 301: "Moved Permanently", 302: "Found",
    304: "Not Modified", 400: "Bad Request", 401: "Unauthorized",
    403: "Forbidden", 404: "Not Found", 405: "Method Not Allowed",
    408: "Request Timeout", 409: "Conflict", 413: "Payload Too Large",
    429: "Too Many Requests", 499: "Client Closed Request",
    500: "Internal Server Error", 501: "Not Implemented",
    502: "Bad Gateway", 503: "Service Unavailable", 504: "Gateway Timeout",
}


class BadRequest(Exception):
    pass


async def _read_request(reader: asyncio.StreamReader) -> Request | None:
    """Parse one HTTP/1.1 request; None on clean EOF."""
    try:
        header_blob = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as exc:
        if not exc.partial:
            return None
        raise BadRequest("truncated request") from exc
    except asyncio.LimitOverrunError as exc:
        raise BadRequest("headers too large") from exc
    if len(header_blob) > MAX_HEADER_BYTES:
        raise BadRequest("headers too large")

    lines = header_blob.decode("latin-1").split("\r\n")
    request_line = lines[0]
    parts = request_line.split(" ")
    if len(parts) != 3 or not parts[2].startswith("HTTP/1"):
        raise BadRequest(f"malformed request line: {request_line!r}")
    method, target, _version = parts
    parsed = urllib.parse.urlsplit(target)
    path = urllib.parse.unquote(parsed.path) or "/"
    query = urllib.parse.parse_qs(parsed.query, keep_blank_values=True)

    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        if ":" not in line:
            raise BadRequest(f"malformed header: {line!r}")
        key, _, value = line.partition(":")
        key = key.strip().lower()
        value = value.strip()
        if key in headers:
            headers[key] += ", " + value
        else:
            headers[key] = value

    body = b""
    if "content-length" in headers:
        try:
            length = int(headers["content-length"])
        except ValueError as exc:
            raise BadRequest("bad Content-Length") from exc
        if length > MAX_BODY_BYTES:
            raise BadRequest("body too large")
        if length:
            body = await reader.readexactly(length)
    elif headers.get("transfer-encoding", "").lower() == "chunked":
        chunks: list[bytes] = []
        total = 0
        while True:
            size_line = (await reader.readuntil(b"\r\n")).strip()
            try:
                size = int(size_line.split(b";")[0], 16)
            except ValueError as exc:
                raise BadRequest("bad chunk size") from exc
            if size == 0:
                await reader.readuntil(b"\r\n")
                break
            total += size
            if total > MAX_BODY_BYTES:
                raise BadRequest("body too large")
            chunks.append(await reader.readexactly(size))
            await reader.readexactly(2)  # trailing CRLF
        body = b"".join(chunks)

    return Request(method, path, query, headers, body)


def _serialize_head(resp: WireResponse, *, chunked: bool, keep_alive: bool) -> bytes:
    text = STATUS_TEXT.get(resp.status, "Unknown")
    out = [f"HTTP/1.1 {resp.status} {text}"]
    headers = dict(resp.headers)
    if chunked:
        headers["Transfer-Encoding"] = "chunked"
        headers.pop("Content-Length", None)
    else:
        headers.setdefault("Content-Length", str(len(resp.body)))
    headers.setdefault("Connection", "keep-alive" if keep_alive else "close")
    for k, v in headers.items():
        out.append(f"{k}: {v}")
    return ("\r\n".join(out) + "\r\n\r\n").encode("latin-1")


class HTTPServer:
    def __init__(
        self,
        handler: Callable[[Request], Awaitable[WireResponse]],
        port: int,
        host: str = "0.0.0.0",
        logger: Any = None,
        cert_file: str | None = None,
        key_file: str | None = None,
        ws_upgrader: Any = None,
    ) -> None:
        self.handler = handler
        self.host = host
        self.port = port
        self.logger = logger
        self.cert_file = cert_file
        self.key_file = key_file
        self.ws_upgrader = ws_upgrader  # async (request, reader, writer) -> bool
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> None:
        ssl_ctx = None
        if self.cert_file and self.key_file:
            ssl_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ssl_ctx.load_cert_chain(self.cert_file, self.key_file)
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port,
            ssl=ssl_ctx, limit=MAX_HEADER_BYTES,
        )
        if self.logger:
            scheme = "https" if ssl_ctx else "http"
            self.logger.info(f"{scheme} server listening on :{self.port}")

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def shutdown(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        remote = f"{peer[0]}" if peer else ""
        try:
            while True:
                try:
                    req = await _read_request(reader)
                except BadRequest as exc:
                    await self._write_simple(writer, 400, str(exc))
                    break
                if req is None:
                    break
                req.remote_addr = remote

                # WebSocket upgrade short-circuits the normal cycle
                if (
                    self.ws_upgrader is not None
                    and "upgrade" in req.headers.get("connection", "").lower()
                    and req.headers.get("upgrade", "").lower() == "websocket"
                ):
                    handled = await self.ws_upgrader(req, reader, writer)
                    if handled:
                        return  # connection consumed by the websocket session
                keep_alive = req.headers.get("connection", "").lower() != "close"
                try:
                    resp = await self.handler(req)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # last-resort isolation
                    if self.logger:
                        self.logger.error(f"unhandled server error: {exc}")
                    resp = WireResponse(status=500, body=b'{"error":{"message":"internal error"}}',
                                        headers={"Content-Type": "application/json"})

                if resp.stream is not None:
                    writer.write(_serialize_head(resp, chunked=True, keep_alive=keep_alive))
                    await writer.drain()
                    # aclose() runs on EVERY exit (disconnect, abort, timeout,
                    # cancellation) so the generator's finally-blocks fire —
                    # that's what frees the batched-decode slot. aclose on an
                    # exhausted generator is a no-op.
                    try:
                        async for chunk in resp.stream:
                            if not chunk:
                                continue
                            writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                            await writer.drain()
                    finally:
                        aclose = getattr(resp.stream, "aclose", None)
                        if aclose is not None:
                            try:
                                await aclose()
                            except Exception:
                                pass
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                else:
                    head = _serialize_head(resp, chunked=False, keep_alive=keep_alive)
                    body = b"" if req.method == "HEAD" else resp.body
                    writer.write(head + body)
                    await writer.drain()
                if not keep_alive:
                    break
        except (OSError, asyncio.IncompleteReadError, asyncio.TimeoutError):
            pass  # client aborted/timed out; writer closed in finally
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (ConnectionResetError, BrokenPipeError):
                pass

    async def _write_simple(self, writer: asyncio.StreamWriter, status: int, message: str) -> None:
        resp = WireResponse(status=status, body=message.encode(), headers={"Content-Type": "text/plain"})
        writer.write(_serialize_head(resp, chunked=False, keep_alive=False))
        writer.write(resp.body)
        try:
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass

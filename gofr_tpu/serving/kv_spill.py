"""Host-RAM KV spill tier: the DRAM layer under device HBM.

Prefix-cache entries (whole-prompt prefills AND chunk-boundary K/V delta
slabs) are bounded on device by the :class:`PrefixCache` LRU — before
this module, capacity eviction simply dropped them, so a repeated system
prompt whose slabs aged out of HBM paid a full re-prefill. AIBrix-style
multi-tier KV pooling (arXiv:2504.03648) says the next tier down is
nearly free: host DRAM is ~100x the size of the device prefix budget and
a re-upload is an async host→device copy the engine never waits on.

:class:`TieredPrefixCache` implements the container ``Cache`` contract
over two tiers:

- **device** — the existing :class:`PrefixCache` LRU of device arrays;
- **host** — :class:`HostSpillTier`, a byte-bounded LRU of the same
  pytrees as pinned host ``numpy`` arrays.

Eviction from the device tier *offers* the entry to a single-worker
spill executor; the worker materializes the slabs host-side
(``np.asarray`` — the device→host sync happens on the spill thread,
never the engine thread) and files them in the host LRU. A device-tier
miss that hits the host tier re-uploads via ``jnp.asarray`` — an async
host→device put that overlaps the in-flight decode block and commits at
the block's existing sync, so the one-sync-per-block contract
(docs/performance.md) is untouched — and promotes the entry back into
the device tier for the next hit.

The ``kv.spill`` chaos point sits on the spill worker: a fault there
drops the entry (the tier is advisory — a lost spill degrades to a
compute miss later, never an error).

Lock discipline (make lock-order, docs/static-analysis.md): the device
tier's lock and the host tier's lock are both LEAF-ONLY — neither tier
calls into the other, or into any callback, while holding its own lock.
"""

from __future__ import annotations

import concurrent.futures
import threading
from collections import OrderedDict
from typing import Any, Hashable

import numpy as np

from gofr_tpu import chaos
from gofr_tpu.serving.prefix_cache import PrefixCache, _tree_leaves

__all__ = ["HostSpillTier", "TieredPrefixCache"]


def _to_host(value: Any) -> Any:
    """Materialize a pytree of device arrays as host numpy arrays —
    structure-preserving for the (logits, k_slab, v_slab) tuples the
    prefix cache stores. Runs on the spill worker thread only."""
    if isinstance(value, tuple):
        return tuple(_to_host(v) for v in value)
    if isinstance(value, list):
        return [_to_host(v) for v in value]
    if isinstance(value, dict):
        return {k: _to_host(v) for k, v in value.items()}
    return np.asarray(value)


def _to_device(value: Any) -> Any:
    """Re-upload a host pytree as device arrays: ``jnp.asarray`` is an
    ASYNC host→device put (no sync) — safe on the engine thread; the
    transfer overlaps the in-flight block and lands by its sync."""
    import jax.numpy as jnp

    if isinstance(value, tuple):
        return tuple(_to_device(v) for v in value)
    if isinstance(value, list):
        return [_to_device(v) for v in value]
    if isinstance(value, dict):
        return {k: _to_device(v) for k, v in value.items()}
    return jnp.asarray(value)


def _host_bytes(value: Any) -> int:
    return sum(
        int(getattr(leaf, "nbytes", 0)) for leaf in _tree_leaves(value)
    )


class HostSpillTier:
    """Byte-bounded LRU of host (numpy) KV pytrees. Thread-safe; the
    lock is leaf-only (never held across a call out — ``score`` is read
    BEFORE taking it).

    ``score`` (optional) upgrades the byte-pressure eviction order from
    raw LRU to timeline-observed reuse (serving/timeline.py
    ``TimelineRecorder.reuse_count``): among resident entries the LOWEST
    (score, LRU-age) evicts first, so a hot system prompt's slabs
    outlive a one-shot prompt's even when the one-shot was touched more
    recently — demotion follows what the request timelines actually
    observed being reused, not access recency alone."""

    def __init__(self, max_bytes: int,
                 score: Any = None) -> None:
        self.max_bytes = max_bytes
        self._score = score  # Callable[[key], number] | None
        self._entries: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._sizes: dict[Hashable, int] = {}
        self._total_bytes = 0
        self._mu = threading.Lock()
        self._hits = 0
        self._misses = 0

    def put(self, key: Hashable, host_value: Any) -> None:
        size = _host_bytes(host_value)
        if size > self.max_bytes:
            return  # cannot ever fit: don't flush the tier for it
        scores: dict[Hashable, float] = {}
        if self._score is not None:
            # snapshot the resident keys UNDER the tier lock (engine
            # threads pop/touch the dict concurrently — iterating it
            # unlocked can raise mid-iteration), then score OUTSIDE it
            # (the scorer takes the timeline recorder's own leaf lock).
            # Keys racing in behind the snapshot default to 0 — a
            # brand-new entry has no observed reuse yet by definition.
            with self._mu:
                resident = list(self._entries.keys())
            for k in resident:
                try:
                    scores[k] = float(self._score(k))
                except Exception:
                    scores[k] = 0.0
        with self._mu:
            if key in self._entries:
                self._total_bytes -= self._sizes.get(key, 0)
            self._entries[key] = host_value
            self._sizes[key] = size
            self._total_bytes += size
            self._entries.move_to_end(key)
            while self._entries and self._total_bytes > self.max_bytes:
                if scores:
                    # reuse-scored demotion: lowest observed reuse goes
                    # first; ties fall back to LRU order (dict order is
                    # LRU; min() keeps the first == oldest on ties). The
                    # just-inserted key is exempt — evicting what we are
                    # inserting would thrash.
                    victims = [k for k in self._entries if k != key]
                    if not victims:
                        break
                    old_key = min(
                        victims, key=lambda k: scores.get(k, 0.0)
                    )
                    self._entries.pop(old_key, None)
                else:
                    old_key, _ = self._entries.popitem(last=False)
                self._total_bytes -= self._sizes.pop(old_key, 0)

    def get(self, key: Hashable) -> Any | None:
        with self._mu:
            value = self._entries.get(key)
            if value is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return value

    def pop(self, key: Hashable) -> Any | None:
        with self._mu:
            value = self._entries.pop(key, None)
            if value is not None:
                self._total_bytes -= self._sizes.pop(key, 0)
                self._hits += 1
            else:
                self._misses += 1
            return value

    def keys(self) -> list[Hashable]:
        with self._mu:
            return list(self._entries.keys())

    @property
    def total_bytes(self) -> int:
        with self._mu:
            return self._total_bytes

    def clear(self) -> None:
        with self._mu:
            self._entries.clear()
            self._sizes.clear()
            self._total_bytes = 0

    def stats(self) -> dict[str, Any]:
        with self._mu:
            return {
                "entries": len(self._entries),
                "bytes": self._total_bytes,
                "max_bytes": self.max_bytes,
                "hits": self._hits,
                "misses": self._misses,
            }


class TieredPrefixCache:
    """Two-tier prefix cache: a device :class:`PrefixCache` LRU over a
    host-RAM :class:`HostSpillTier`, presenting the same ``Cache``
    contract (get/put/evict/clear/stats) the engine already consumes —
    plus :meth:`get_with_tier` for per-request tier attribution
    (``/requestz`` ``prefix_tier``, ``app_kv_prefix_hits_total``).

    The spill executor is single-worker and process-cheap: device→host
    copies are serialized behind it, so a burst of evictions can never
    fan out sync pressure, and ``flush()`` gives tests/drain a
    deterministic settle point. A bounded backlog refuses NEW offers
    while full (counted in ``spill_dropped_total``) — the tier is
    advisory, and a spill queue growing without bound would just be a
    slower way to lose entries.
    """

    MAX_PENDING = 64

    def __init__(
        self,
        max_entries: int = 32,
        max_bytes: int = 256 * 1024 * 1024,
        spill_bytes: int = 1024 * 1024 * 1024,
        *,
        metrics: Any = None,
        reuse_score: Any = None,
    ) -> None:
        self._device = PrefixCache(
            max_entries, max_bytes=max_bytes, on_evict=self._offer
        )
        # reuse_score (Callable[[key], number], typically the timeline
        # recorder's reuse_count) upgrades host-tier demotion from raw
        # LRU to timeline-observed reuse ordering
        self._host = HostSpillTier(spill_bytes, score=reuse_score)
        self._metrics = metrics
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="kv-spill"
        )
        self._pending = 0
        self._pending_mu = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        self.spilled_total = 0
        self.dropped_total = 0

    # -- Cache contract --------------------------------------------------------
    def get(self, key: Hashable) -> Any | None:
        value, _tier = self.get_with_tier(key)
        return value

    def get_with_tier(self, key: Hashable) -> tuple[Any | None, str]:
        """Lookup across tiers: returns ``(value, tier)`` with tier one
        of ``device`` / ``host`` / ``miss``. A host hit re-uploads (async
        put, engine thread never syncs), promotes the entry back into
        the device tier, and removes the host copy — if the promotion is
        evicted again it re-spills through the normal path."""
        value = self._device.get(key)
        if value is not None:
            return value, "device"
        host_value = self._host.pop(key)
        if host_value is None:
            return None, "miss"
        device_value = _to_device(host_value)
        self._device.put(key, device_value)
        return device_value, "host"

    def put(self, key: Hashable, value: Any) -> None:
        self._device.put(key, value)

    def peek(self, key: Hashable) -> Any | None:
        """NON-MUTATING cross-tier read for peer-serving fetches
        (``/kv/fetch``, ``local_engine_fetcher``): no host-tier pop, no
        device promotion — a remote replica reading this cache must not
        thrash the owner's device LRU or delete its only host copy.
        Returns device arrays from the device tier or host numpy arrays
        from the spill tier; the fetching side handles either."""
        value = self._device.get(key)
        if value is not None:
            return value
        return self._host.get(key)

    def keys(self) -> list[Hashable]:
        """All resident keys, device tier first (LRU order within each
        tier) — the bulk-evacuation walk enumerates both tiers: a
        paged-out span demoted to host RAM is exactly the KV a doomed
        replica most needs to push out."""
        device = self._device.keys()
        seen = set(device)
        return device + [k for k in self._host.keys() if k not in seen]

    def evict(self, key: Hashable) -> None:
        self._device.evict(key)
        self._host.pop(key)

    def clear(self) -> None:
        """Drop BOTH tiers (the engine's device-poison recovery path —
        a host copy of a poisoned slab would fail its re-upload probe
        anyway, and a cold tier only costs recompute)."""
        self._device.clear()
        self._host.clear()

    def stats(self) -> dict[str, Any]:
        out = self._device.stats()
        out["host"] = self._host.stats()
        out["spilled_total"] = self.spilled_total
        out["spill_dropped_total"] = self.dropped_total
        return out

    # -- the distributed index reads this --------------------------------------
    def advertised(self, limit: int = 128) -> list[tuple[str, str]]:
        """(key, tier) pairs for the gossip advertisement
        (serving/prefix_index.py), newest-first per tier, device tier
        first — bounded so a heartbeat stays a heartbeat."""
        out: list[tuple[str, str]] = []
        for key in reversed(self._device.keys()):
            out.append((str(key), "device"))
            if len(out) >= limit:
                return out
        for key in reversed(self._host.keys()):
            out.append((str(key), "host"))
            if len(out) >= limit:
                break
        return out

    # -- spill path (device-tier eviction → host tier) --------------------------
    def _offer(self, key: Hashable, value: Any) -> None:
        """Device-tier eviction hook: hand the dropped entry to the
        spill worker. Engine-thread side does NO device reads — the
        device→host materialization happens on the worker."""
        with self._pending_mu:
            if self._pending >= self.MAX_PENDING:
                self.dropped_total += 1
                return
            self._pending += 1
            self._idle.clear()
        try:
            self._exec.submit(self._spill_task, key, value)
        except RuntimeError:  # executor shut down: the tier is closing
            self._spill_done()

    def _spill_task(self, key: Hashable, value: Any) -> None:
        try:
            chaos.maybe_fail("kv.spill")
            host_value = _to_host(value)
            self._host.put(key, host_value)
            with self._pending_mu:
                self.spilled_total += 1
            if self._metrics is not None:
                self._metrics.set_gauge(
                    "app_kv_spill_bytes", float(self._host.total_bytes)
                )
        except Exception:
            # a poisoned device array (its dispatch died after donation)
            # raises out of np.asarray; an injected kv.spill fault lands
            # here too — either way the entry is dropped and a future
            # lookup degrades to a compute miss
            with self._pending_mu:
                self.dropped_total += 1
        finally:
            self._spill_done()

    def _spill_done(self) -> None:
        with self._pending_mu:
            self._pending -= 1
            if self._pending <= 0:
                self._idle.set()

    def flush(self, timeout: float = 5.0) -> bool:
        """Wait for every queued spill to settle (tests, drain)."""
        return self._idle.wait(timeout=timeout)

    def close(self) -> None:
        self._exec.shutdown(wait=False)

"""Tokenizer boundary.

The engine only needs encode/decode + special ids; any implementation
(SentencePiece, HF tokenizers loaded from local files) plugs in. The default
ByteTokenizer is dependency-free: UTF-8 bytes offset by the special-token
block — real text in/out with a 259-token vocab, which keeps tests, demos
and the bench self-contained (no downloaded assets in the image).
"""

from __future__ import annotations

from typing import Protocol, runtime_checkable


@runtime_checkable
class Tokenizer(Protocol):
    bos_id: int
    eos_id: int
    pad_id: int
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...

    def decode(self, ids: list[int]) -> str: ...


class ByteTokenizer:
    """ids 0..2 = pad/bos/eos; byte b -> id b+3."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _offset = 3

    def __init__(self, vocab_size: int | None = None) -> None:
        self.vocab_size = vocab_size or (256 + self._offset)

    def encode(self, text: str) -> list[int]:
        return [self.bos_id] + [b + self._offset for b in text.encode("utf-8")]

    def decode(self, ids: list[int]) -> str:
        data = bytes(
            i - self._offset for i in ids if self._offset <= i < self._offset + 256
        )
        return data.decode("utf-8", "replace")


def pad_batch(tokenizer: Tokenizer, texts: list[str], max_seq_len: int):
    """Tokenize + right-pad a text batch to a power-of-two bucket clamped to
    ``max_seq_len`` (limits XLA recompiles to a few shapes). Returns
    (tokens [B, bucket] int32 ndarray, lens [B] list)."""
    import numpy as np

    ids = [tokenizer.encode(t)[:max_seq_len] for t in texts]
    max_len = max((len(i) for i in ids), default=1)
    bucket = 1 << (max_len - 1).bit_length() if max_len > 1 else 1
    bucket = min(max(bucket, 8), max_seq_len)
    arr = np.full((len(ids), bucket), tokenizer.pad_id, np.int32)
    lens = []
    for row, seq in enumerate(ids):
        seq = seq[:bucket]
        arr[row, : len(seq)] = seq
        lens.append(len(seq))
    return arr, lens


class HFTokenizer:
    """Adapter for a local `transformers` tokenizer directory (no network:
    pass a path that already contains tokenizer.json)."""

    def __init__(self, path: str) -> None:
        from transformers import AutoTokenizer

        self._tok = AutoTokenizer.from_pretrained(path, local_files_only=True)
        self.bos_id = self._tok.bos_token_id or 1
        self.eos_id = self._tok.eos_token_id or 2
        self.pad_id = self._tok.pad_token_id or 0
        self.vocab_size = self._tok.vocab_size

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text)

    def decode(self, ids: list[int]) -> str:
        return self._tok.decode(ids, skip_special_tokens=True)

"""The headroom-driven replica autoscaler (ROADMAP item 2, AIBrix
arXiv:2504.03648).

The router tier (PR 7) watches every replica's queue-wait EWMA and HBM
headroom ride the membership heartbeats — but until this module nothing
ACTED on the signal: an operator read ``/routerz`` and resized the pool
by hand. The :class:`Autoscaler` closes the loop, per role (a role-split
tier sizes its prefill and decode pools independently — a prefill
backlog must grow the prefill pool, not add decode replicas that would
sit idle):

- **signal**: the role pool's mean queue-wait EWMA
  (``MembershipTable.aggregate_queue_wait`` — the same series
  ``app_router_queue_wait_seconds`` exports) and its tightest reported
  HBM headroom (``min_hbm_headroom``, fed by the PR 9 device-telemetry
  poller);
- **hysteresis**: pressure must PERSIST for ``up_stable_s`` before a
  scale-up, idleness for ``down_stable_s`` before a scale-down, and
  every action starts a per-role ``cooldown_s`` — a bursty signal must
  not flap the pool (adding a replica costs a cold jit cache; removing
  one costs its warm KV);
- **the scale-down invariant** (chaos-tested,
  tests/test_router_chaos.py): a victim is DRAINED, never killed — the
  driver's ``begin_drain`` runs the replica's graceful-drain contract
  (in-flight streams and handoffs finish, the DRAINING heartbeat stops
  new routes) and the replica is reaped only once it reports idle.
  Zero lost requests, whatever the scaler does.

The **driver** is the deployment-shaped seam: :class:`ReplicaPoolDriver`
is the k8s-shaped interface (scale a Deployment per role, cordon+drain a
pod, reap it when idle); :class:`SimulatedPoolDriver` implements it over
an in-process replica factory so the control loop's behavior is testable
— and chaos-testable — without a cluster.

The ``scale.decision`` chaos point sits on each per-role decision: a
fault there skips the round's action (counted, never raised into the
loop) — the control plane misfiring must degrade to "pool stays its
current size", never to a kill.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable

from gofr_tpu import chaos
from gofr_tpu.serving import membership as ms

__all__ = ["AutoscalerConfig", "Autoscaler", "SimulatedPoolDriver"]


@dataclasses.dataclass
class AutoscalerConfig:
    """Every knob env-tunable, like RouterConfig (docs/robustness.md has
    the table)."""

    interval_s: float = 1.0
    min_replicas: int = 1
    max_replicas: int = 8
    # queue-wait EWMA above this → the pool is under pressure
    scale_up_wait_s: float = 1.0
    # queue-wait EWMA below this (with no HBM pressure) → the pool idles
    scale_down_wait_s: float = 0.1
    # tightest reported HBM headroom below this fraction → pressure
    # (replicas that publish no device sample never trigger it)
    hbm_floor_frac: float = 0.05
    # hysteresis: how long the signal must persist before acting, and
    # the per-role quiet period after every action
    up_stable_s: float = 2.0
    down_stable_s: float = 10.0
    cooldown_s: float = 5.0

    @classmethod
    def from_config(cls, config: Any) -> "AutoscalerConfig":
        return cls(
            interval_s=float(
                config.get_or_default("TPU_AUTOSCALE_INTERVAL_S", "1.0")
            ),
            min_replicas=int(
                config.get_or_default("TPU_AUTOSCALE_MIN_REPLICAS", "1")
            ),
            max_replicas=int(
                config.get_or_default("TPU_AUTOSCALE_MAX_REPLICAS", "8")
            ),
            scale_up_wait_s=float(
                config.get_or_default("TPU_AUTOSCALE_UP_WAIT_S", "1.0")
            ),
            scale_down_wait_s=float(
                config.get_or_default("TPU_AUTOSCALE_DOWN_WAIT_S", "0.1")
            ),
            hbm_floor_frac=float(
                config.get_or_default("TPU_AUTOSCALE_HBM_FLOOR", "0.05")
            ),
            up_stable_s=float(
                config.get_or_default("TPU_AUTOSCALE_UP_STABLE_S", "2.0")
            ),
            down_stable_s=float(
                config.get_or_default("TPU_AUTOSCALE_DOWN_STABLE_S", "10.0")
            ),
            cooldown_s=float(
                config.get_or_default("TPU_AUTOSCALE_COOLDOWN_S", "5.0")
            ),
        )


class ReplicaPoolDriver:
    """The deployment seam the autoscaler drives — k8s-shaped on
    purpose: ``scale_up`` maps to growing a per-role Deployment,
    ``begin_drain`` to cordoning a pod and invoking its graceful-drain
    hook, ``reap`` to deleting it once idle. Implementations must make
    ``begin_drain`` NON-DESTRUCTIVE: in-flight streams and handoffs on
    the victim run to completion (the scale-down invariant)."""

    def replica_ids(self, role: str) -> list[str]:
        """Live (non-draining) replica ids of this role."""
        raise NotImplementedError

    def scale_up(self, role: str, n: int, *,
                 preemptible: bool = False) -> list[str]:
        """Add ``n`` replicas to the role's pool; returns their ids.
        ``preemptible`` requests reclaimable (spot) capacity — cheaper,
        but the provider may :meth:`notice` it back at any time."""
        raise NotImplementedError

    def begin_drain(self, replica_id: str) -> None:
        """Start the victim's graceful drain (never blocks the caller,
        never kills in-flight work)."""
        raise NotImplementedError

    def notice(self, replica_id: str, deadline_s: float = 5.0) -> bool:
        """Deliver a reclamation notice (docs/robustness.md "The
        reclamation plane"): the provider takes ``replica_id``'s machine
        back in ``deadline_s`` seconds. Implementations run the
        replica's ``begin_reclaim`` ladder (deadline-bounded drain +
        bulk KV evacuation) without blocking the caller, and report the
        event to a wired ``on_notice`` observer. Returns False when the
        notice was lost in delivery."""
        raise NotImplementedError

    def reap(self, replica_id: str) -> bool:
        """Remove a draining replica IF it is idle; False = still busy,
        try again next tick."""
        raise NotImplementedError


class SimulatedPoolDriver(ReplicaPoolDriver):
    """An in-process pool: ``factory(role, replica_id) -> handle`` builds
    a replica (a LocalReplica-compatible handle over a real engine or a
    stub), the driver registers it with the router and tracks its
    lifecycle. Drains run the handle's (or its engine's) ``drain`` on a
    daemon thread — an engine's drain blocks until its streams finish,
    which is exactly the semantics the invariant wants — and ``reap``
    removes the replica only once its health reports nothing in flight.
    """

    def __init__(self, router: Any,
                 factory: Callable[[str, str], Any],
                 *, on_reap: Callable[[Any], None] | None = None,
                 on_notice: Callable[..., None] | None = None) -> None:
        self.router = router
        self.factory = factory
        self._on_reap = on_reap
        # reclamation observer: called (replica_id, role=, deadline_s=)
        # after a notice is DELIVERED — the autoscaler self-wires here
        # (Autoscaler.observe_notice) to backfill outside its hysteresis
        self.on_notice = on_notice
        self._mu = threading.Lock()
        self._handles: dict[str, Any] = {}
        self._roles: dict[str, str] = {}
        self._preemptible: set[str] = set()
        self._draining: set[str] = set()
        self._drained: set[str] = set()  # drain call returned
        self._next = 0
        self.notices_total = 0
        self.notices_dropped_total = 0  # replica.reclaim chaos faults

    # -- driver surface --------------------------------------------------------
    def replica_ids(self, role: str) -> list[str]:
        with self._mu:
            return [
                rid for rid, r in self._roles.items()
                if r == role and rid not in self._draining
            ]

    def preemptible_ids(self, role: str | None = None) -> list[str]:
        """Live (non-draining) preemptible replica ids — the notice-storm
        injectors and the capacity planner enumerate the reclaimable
        share of the fleet through this."""
        with self._mu:
            return [
                rid for rid, r in self._roles.items()
                if (role is None or r == role)
                and rid in self._preemptible
                and rid not in self._draining
            ]

    def role_of(self, replica_id: str) -> str | None:
        with self._mu:
            return self._roles.get(replica_id)

    def scale_up(self, role: str, n: int, *,
                 preemptible: bool = False) -> list[str]:
        out = []
        for _ in range(n):
            with self._mu:
                self._next += 1
                rid = f"{role}-{self._next}"
            handle = self._make(role, rid, preemptible)
            with self._mu:
                self._handles[rid] = handle
                self._roles[rid] = role
                if preemptible:
                    self._preemptible.add(rid)
            self.router.add_replica(handle, role=role)
            out.append(rid)
        return out

    def _make(self, role: str, rid: str, preemptible: bool) -> Any:
        """Build one replica. Existing 2-arg factories keep working; a
        factory declaring ``preemptible`` (or **kwargs) receives the
        capacity class so it can set ``EngineConfig.preemptible`` and
        the handle attribute the router's steering reads."""
        if preemptible:
            try:
                import inspect

                params = inspect.signature(self.factory).parameters
                accepts = "preemptible" in params or any(
                    p.kind == p.VAR_KEYWORD for p in params.values()
                )
            except (TypeError, ValueError):
                accepts = False
            if accepts:
                return self.factory(role, rid, preemptible=True)
            handle = self.factory(role, rid)
            # best-effort: mark the handle (and its engine) so the
            # heartbeat + router steering still see the capacity class
            try:
                handle.preemptible = True
                engine = getattr(handle, "engine", None)
                if engine is not None:
                    engine.preemptible = True
            except Exception:
                pass
            return handle
        return self.factory(role, rid)

    def begin_drain(self, replica_id: str) -> None:
        with self._mu:
            if replica_id in self._draining:
                return
            handle = self._handles.get(replica_id)
            if handle is None:
                return
            self._draining.add(replica_id)
        drain = getattr(handle, "drain", None) or getattr(
            getattr(handle, "engine", None), "drain", None
        )

        def run() -> None:
            try:
                if drain is not None:
                    drain()  # blocks until in-flight work finished
            finally:
                with self._mu:
                    self._drained.add(replica_id)

        threading.Thread(
            target=run, daemon=True, name=f"drain-{replica_id}"
        ).start()

    def notice(self, replica_id: str, deadline_s: float = 5.0) -> bool:
        """Reclamation-notice injector: delivers the provider's
        "machine goes away in ``deadline_s`` seconds" webhook to the
        replica. The ``replica.reclaim`` chaos point sits ON delivery —
        a fault there means the notice is LOST (counted; the replica
        keeps serving until the machine actually dies, which the loadlab
        kill path models separately) — never a kill here. A delivered
        notice runs the replica's ``begin_reclaim`` ladder
        (deadline-bounded drain + bulk KV evacuation; plain ``drain``
        when the handle predates the reclamation plane) on a daemon
        thread and reports to ``on_notice`` so the autoscaler can
        backfill immediately."""
        with self._mu:
            handle = self._handles.get(replica_id)
            role = self._roles.get(replica_id)
        if handle is None:
            return False
        try:
            chaos.maybe_fail("replica.reclaim")
        except Exception:
            self.notices_dropped_total += 1
            return False
        self.notices_total += 1
        with self._mu:
            already = replica_id in self._draining
            self._draining.add(replica_id)
        if not already:
            engine = getattr(handle, "engine", None)
            reclaim = getattr(handle, "begin_reclaim", None) or getattr(
                engine, "begin_reclaim", None
            )
            drain = getattr(handle, "drain", None) or getattr(
                engine, "drain", None
            )

            def run() -> None:
                try:
                    if reclaim is not None:
                        reclaim(deadline_s)
                    elif drain is not None:
                        drain(deadline_s)
                finally:
                    with self._mu:
                        self._drained.add(replica_id)

            threading.Thread(
                target=run, daemon=True, name=f"reclaim-{replica_id}"
            ).start()
        if self.on_notice is not None:
            try:
                self.on_notice(
                    replica_id, role=role, deadline_s=deadline_s
                )
            except Exception:
                pass  # the observer must not break notice delivery
        return True

    def _idle(self, handle: Any) -> bool:
        try:
            health = handle.health_check() or {}
        except Exception:
            return True  # a replica too dead to answer holds nothing
        details = health.get("details") or {}
        return (
            int(details.get("slots_active", 0)) == 0
            and int(details.get("queue_depth", 0)) == 0
        )

    def reap(self, replica_id: str) -> bool:
        with self._mu:
            handle = self._handles.get(replica_id)
            drained = replica_id in self._drained
        if handle is None:
            return True
        if not drained and not self._idle(handle):
            return False  # in-flight streams/handoffs still running
        self.router.remove_replica(replica_id)
        with self._mu:
            self._handles.pop(replica_id, None)
            self._roles.pop(replica_id, None)
            self._preemptible.discard(replica_id)
            self._draining.discard(replica_id)
            self._drained.discard(replica_id)
        if self._on_reap is not None:
            try:
                self._on_reap(handle)
            except Exception:
                pass  # teardown hooks must not wedge the scaler
        return True

    def handle(self, replica_id: str) -> Any:
        with self._mu:
            return self._handles.get(replica_id)


class _RoleState:
    __slots__ = ("pressure_since", "idle_since", "last_action_at")

    def __init__(self) -> None:
        self.pressure_since: float | None = None
        self.idle_since: float | None = None
        self.last_action_at = 0.0


class Autoscaler:
    """The control loop: one decision per role per tick, hysteresis on
    both edges, drain-then-reap on the way down."""

    def __init__(
        self,
        router: Any,
        driver: ReplicaPoolDriver,
        config: AutoscalerConfig | None = None,
        *,
        roles: tuple[str, ...] = (ms.ROLE_UNIFIED,),
        metrics: Any = None,
        logger: Any = None,
    ) -> None:
        self.router = router
        self.driver = driver
        self.config = config or AutoscalerConfig()
        self.roles = tuple(roles)
        self._metrics = metrics
        self._logger = logger
        self._states: dict[str, _RoleState] = {
            role: _RoleState() for role in self.roles
        }
        self._reaping: set[str] = set()
        self._mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.decisions_skipped_total = 0  # scale.decision chaos faults
        self.notices_observed_total = 0  # reclamation forced-drains seen
        self.decisions: list[dict[str, Any]] = []  # bounded action log
        # reclamation wiring: a SimulatedPoolDriver-shaped driver exposes
        # on_notice — self-wire the forced-drain observer unless the
        # caller already installed one
        if getattr(driver, "on_notice", False) is None:
            driver.on_notice = self.observe_notice

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="autoscaler"
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
        self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception as exc:
                # the control plane misfiring must never take the data
                # plane with it: log, keep ticking
                if self._logger is not None:
                    self._logger.error(f"autoscaler tick failed: {exc}")

    # -- the decision ----------------------------------------------------------
    def tick(self, now: float | None = None) -> None:
        """One control round: reap any draining victims, then one
        decision per role. Public for deterministic tests (the loop just
        calls it on the interval)."""
        now = time.monotonic() if now is None else now
        with self._mu:
            reaping = list(self._reaping)
        for rid in reaping:
            if self.driver.reap(rid):
                with self._mu:
                    self._reaping.discard(rid)
        for role in self.roles:
            self._decide(role, now)
        if self._metrics is not None:
            for role in self.roles:
                self._metrics.set_gauge(
                    "app_autoscaler_replicas",
                    float(len(self.driver.replica_ids(role))),
                    role=role,
                )

    def _decide(self, role: str, now: float) -> None:
        cfg = self.config
        state = self._states[role]
        try:
            chaos.maybe_fail("scale.decision")
        except Exception:
            # a faulted decision round: the pool keeps its size — the
            # failure mode is "no action", never a kill
            self.decisions_skipped_total += 1
            return
        wait = self.router.membership.aggregate_queue_wait(role)
        hbm = self.router.membership.min_hbm_headroom(role)
        current = len(self.driver.replica_ids(role))
        pressure = wait > cfg.scale_up_wait_s or (
            hbm is not None and hbm < cfg.hbm_floor_frac
        )
        idle = not pressure and wait < cfg.scale_down_wait_s
        # hysteresis edges: the signal must persist
        if pressure:
            state.idle_since = None
            if state.pressure_since is None:
                state.pressure_since = now
        elif idle:
            state.pressure_since = None
            if state.idle_since is None:
                state.idle_since = now
        else:
            state.pressure_since = None
            state.idle_since = None
        in_cooldown = now - state.last_action_at < cfg.cooldown_s
        if in_cooldown:
            return
        if (
            pressure
            and state.pressure_since is not None
            and now - state.pressure_since >= cfg.up_stable_s
            and current < cfg.max_replicas
        ):
            added = self.driver.scale_up(role, 1)
            state.last_action_at = now
            state.pressure_since = None
            self.scale_ups_total += 1
            self._record(role, "up", added, wait, hbm, current + 1)
            return
        if (
            idle
            and state.idle_since is not None
            and now - state.idle_since >= cfg.down_stable_s
            and current > cfg.min_replicas
        ):
            victim = self._pick_victim(role)
            if victim is None:
                return
            # DRAIN, never kill: the victim finishes its in-flight
            # streams and handoffs, stops receiving routes via its
            # DRAINING heartbeat, and is reaped only once idle
            self.driver.begin_drain(victim)
            with self._mu:
                self._reaping.add(victim)
            state.last_action_at = now
            state.idle_since = None
            self.scale_downs_total += 1
            self._record(role, "down", [victim], wait, hbm, current - 1)

    def observe_notice(self, replica_id: str, *, role: str | None = None,
                       deadline_s: float | None = None) -> None:
        """A reclamation notice is a FORCED drain from outside the
        control loop: the victim is already reclaiming (the driver ran
        its ladder at delivery), so hysteresis and cooldown do not apply
        — this round's job is (a) adopt the victim into the reap cycle
        (drain-never-kill: reap waits for idle exactly like a scale-down
        victim) and (b) backfill the lost capacity IMMEDIATELY, ahead of
        the queue-wait signal the notice will cause seconds from now.
        The backfill is ON-DEMAND capacity by construction (scale_up's
        default): replacing reclaimed spot with more spot mid-storm
        would just get noticed again."""
        self.notices_observed_total += 1
        with self._mu:
            self._reaping.add(replica_id)
        if role is None:
            role_of = getattr(self.driver, "role_of", None)
            role = role_of(replica_id) if role_of is not None else None
        if role is None or role not in self.roles:
            return  # not a pool this scaler sizes: adopt-for-reap only
        cfg = self.config
        current = len(self.driver.replica_ids(role))
        if current >= cfg.max_replicas:
            return
        added = self.driver.scale_up(role, 1)
        self.scale_ups_total += 1
        self._record(
            role, "backfill", added,
            self.router.membership.aggregate_queue_wait(role),
            None, current + 1,
        )

    def _pick_victim(self, role: str) -> str | None:
        """Least-loaded live replica of the role — draining the emptiest
        pod loses the least warm KV and finishes fastest."""
        ids = self.driver.replica_ids(role)
        if not ids:
            return None
        loads = [
            (self.router.membership.load_of(rid), rid) for rid in ids
        ]
        loads.sort()
        return loads[0][1]

    def _record(self, role: str, direction: str, ids: list[str],
                wait: float, hbm: float | None, target: int) -> None:
        entry = {
            "role": role, "direction": direction, "replicas": ids,
            "queue_wait_s": round(wait, 4),
            "hbm_free_frac": round(hbm, 4) if hbm is not None else None,
            "target": target, "t": time.time(),
        }
        self.decisions.append(entry)
        del self.decisions[:-64]  # bounded
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_autoscaler_scale_events_total", direction=direction,
            )
        if self._logger is not None:
            self._logger.info(
                f"autoscaler: {role} scale-{direction} → {target} "
                f"(queue_wait={wait:.3f}s hbm={hbm})"
            )

    # -- observability ---------------------------------------------------------
    def snapshot(self) -> dict[str, Any]:
        with self._mu:
            reaping = sorted(self._reaping)
        return {
            "roles": {
                role: {
                    "replicas": self.driver.replica_ids(role),
                    "queue_wait_s": round(
                        self.router.membership.aggregate_queue_wait(role), 4
                    ),
                }
                for role in self.roles
            },
            "draining": reaping,
            "scale_ups_total": self.scale_ups_total,
            "scale_downs_total": self.scale_downs_total,
            "decisions_skipped_total": self.decisions_skipped_total,
            "notices_observed_total": self.notices_observed_total,
            "decisions": list(self.decisions[-16:]),
        }

"""Replica membership & failure detection for the router tier.

The scale-out era's first invariant (ROADMAP item 3, AIBrix
arXiv:2504.03648): a router that fronts N engine replicas must know, at
every moment, which replicas may receive new work — without a central
coordinator and without trusting any single signal. Three signals feed
the table:

- **heartbeats**: every replica runs a :class:`ReplicaAnnouncer` that
  publishes its supervisor state (UP/SUSPECT/DRAINING/WEDGED — PRs 3/5),
  shed queue-wait EWMA and KV/HBM headroom over the existing pubsub
  layer (PR 4 at-least-once delivery; heartbeats are idempotent by
  ``seq``, so redelivery is harmless);
- **silence**: a replica that misses heartbeats goes SUSPECT after
  ``suspect_after_s`` and DOWN after ``down_after_s`` — the router never
  waits for a failed replica to say it failed;
- **the breaker**: an inter-replica circuit breaker opening
  (service/options.py) forces the replica DOWN immediately, ahead of the
  heartbeat timers — the data path learned faster than the control path.

Routability: UP replicas route; SUSPECT replicas route only when no UP
replica exists (a heartbeat blip must not take the whole tier down);
DRAINING / WEDGED / RESTARTING / DOWN replicas receive **zero** new
routes, ever — DRAINING finishes its in-flight streams, WEDGED needs
replacing (docs/robustness.md "The router plane").
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from typing import Any, Callable

from gofr_tpu import chaos

HEARTBEAT_TOPIC = "replica.heartbeat"

# replica states as seen by the router (superset of the supervisor's:
# DOWN covers both "announced down" and "went silent")
UP = "UP"
SUSPECT = "SUSPECT"
RESTARTING = "RESTARTING"
DRAINING = "DRAINING"
WEDGED = "WEDGED"
DOWN = "DOWN"
# RECLAIMING: a preemptible replica under a reclamation notice — it is
# DRAINING with a hard external deadline (the provider takes the machine
# back whether or not we finish), so it additionally evacuates committed
# KV to survivors. Like DRAINING it receives zero new routes, ever.
RECLAIMING = "RECLAIMING"

# gauge encoding for app_router_replica_state
STATE_VALUES = {
    UP: 0, SUSPECT: 1, RESTARTING: 2, DRAINING: 3, WEDGED: 4, DOWN: 5,
    RECLAIMING: 6,
}

# states that may receive new routes (SUSPECT only as a last resort)
_NEVER_ROUTE = (DRAINING, WEDGED, RESTARTING, DOWN, RECLAIMING)

# replica roles (disaggregated prefill/decode serving, ROADMAP item 2,
# AIBrix arXiv:2504.03648): a UNIFIED replica serves whole generations;
# a PREFILL replica only computes prompt KV (handed off to a decode
# replica over the PR 11 transfer machinery); a DECODE replica admits
# handed-off KV chains and streams tokens. Roles ride the heartbeat so
# the router's policy follows the pool's actual shape, live.
ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_UNIFIED = "unified"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_UNIFIED)


@dataclasses.dataclass
class Heartbeat:
    """One replica's announcement: identity, supervisor state, and the
    load/headroom signals the router's spill + autoscaling decisions key
    on. ``seq`` is a per-replica monotonic counter — at-least-once pubsub
    may redeliver or reorder beats, and a stale beat must never overwrite
    a newer observation."""

    replica_id: str
    seq: int
    state: str = UP
    # disaggregation role (prefill/decode/unified). Part of the beat, not
    # static registration: a pool driver can repurpose a replica between
    # roles and the router's policy follows within one heartbeat.
    role: str = ROLE_UNIFIED
    queue_wait_s: float = 0.0   # shed EWMA estimate (serving/shed.py)
    queue_depth: int = 0
    slots_free: int = 0
    kv_free_frac: float = 1.0   # paged-KV pool headroom (0..1)
    hbm_free_frac: float | None = None  # device HBM headroom, if known
    ts: float = 0.0             # publisher wall clock, informational only
    # preemptible capacity class (ROADMAP item 5, AIBrix
    # arXiv:2504.03648): True marks a replica the provider may reclaim on
    # short notice. Rides the beat so router steering and the capacity
    # planner see the fleet's actual cost mix, live.
    preemptible: bool = False
    # remaining seconds of an in-progress reclamation notice (None when
    # not reclaiming) — the router/autoscaler read the budget off the
    # beat instead of asking the doomed replica.
    reclaim_deadline_s: float | None = None
    # distributed prefix index (serving/prefix_index.py): a BOUNDED
    # [key, tier] advertisement of this replica's cached prefixes —
    # piggybacked here so the index rides the same idempotent per-replica
    # seq discipline the membership table already enforces. None when
    # the replica advertises nothing (no prefix cache wired).
    prefix_keys: list | None = None
    # HA plane (docs/robustness.md "The HA plane"): the replica's fence
    # epoch — monotonic, bumped on warm_restart / begin_reclaim /
    # announcer re-register. Routers stamp it on every per-attempt call;
    # the engine rejects a stale epoch at the wire (ErrorStaleEpoch),
    # which fences a zombie router acting on a pre-restart view. 0 =
    # unfenced (an engine predating the epoch, or a stub).
    epoch: int = 0

    def to_json(self) -> bytes:
        return json.dumps(dataclasses.asdict(self)).encode()

    @classmethod
    def from_json(cls, raw: bytes) -> "Heartbeat":
        data = json.loads(raw.decode("utf-8"))
        names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in names})


class _ReplicaView:
    """The membership table's record of one replica."""

    def __init__(self, replica_id: str, role: str = ROLE_UNIFIED) -> None:
        self.replica_id = replica_id
        self.seq = -1
        self.reported_state = UP
        self.role = role
        self.last_seen: float | None = None  # monotonic arrival time
        self.queue_wait_s = 0.0
        self.queue_depth = 0
        self.slots_free = 0
        self.kv_free_frac = 1.0
        self.hbm_free_frac: float | None = None
        self.forced_down_reason: str | None = None  # breaker-open etc.
        self.preemptible = False
        self.reclaim_deadline_s: float | None = None
        self.epoch = 0  # fence epoch as last gossiped (0 = unfenced)

    def effective_state(self, now: float, suspect_after: float,
                        down_after: float) -> str:
        if self.forced_down_reason is not None:
            return DOWN
        if self.reported_state in _NEVER_ROUTE:
            return self.reported_state
        if self.last_seen is None:
            return SUSPECT  # registered but never heard from
        age = now - self.last_seen
        if age > down_after:
            return DOWN
        if age > suspect_after:
            return SUSPECT
        return self.reported_state

    def snapshot(self, now: float, suspect_after: float,
                 down_after: float) -> dict[str, Any]:
        out: dict[str, Any] = {
            "state": self.effective_state(now, suspect_after, down_after),
            "reported_state": self.reported_state,
            "role": self.role,
            "seq": self.seq,
            "queue_wait_s": round(self.queue_wait_s, 4),
            "queue_depth": self.queue_depth,
            "slots_free": self.slots_free,
            "kv_free_frac": round(self.kv_free_frac, 4),
        }
        if self.epoch:
            out["epoch"] = self.epoch
        if self.preemptible:
            out["preemptible"] = True
        if self.reclaim_deadline_s is not None:
            out["reclaim_deadline_s"] = round(self.reclaim_deadline_s, 3)
        if self.hbm_free_frac is not None:
            out["hbm_free_frac"] = round(self.hbm_free_frac, 4)
        if self.last_seen is not None:
            out["heartbeat_age_s"] = round(now - self.last_seen, 3)
        if self.forced_down_reason is not None:
            out["forced_down"] = self.forced_down_reason
        return out


class MembershipTable:
    """Thread-safe replica table: heartbeats in, routability out.

    ``observe`` ingests a heartbeat (stale ``seq`` dropped — the pubsub
    layer is at-least-once, not ordered), ``mark_down`` is the breaker's
    fast path, ``candidates`` answers the router's question: which
    replicas may receive this request, best first."""

    def __init__(self, suspect_after_s: float = 3.0,
                 down_after_s: float = 10.0) -> None:
        self.suspect_after_s = suspect_after_s
        self.down_after_s = down_after_s
        self._mu = threading.Lock()
        self._replicas: dict[str, _ReplicaView] = {}

    def register(self, replica_id: str, role: str = ROLE_UNIFIED, *,
                 preemptible: bool = False) -> None:
        """Pre-register a replica (the router knows its handles up front);
        it stays SUSPECT until its first heartbeat arrives. ``role`` and
        ``preemptible`` are the registration-time defaults — the
        replica's own heartbeats are authoritative and overwrite them."""
        with self._mu:
            view = self._replicas.setdefault(
                replica_id, _ReplicaView(replica_id, role)
            )
            if view.last_seen is None:
                view.role = role  # never heard from: registration decides
                view.preemptible = preemptible

    def forget(self, replica_id: str) -> None:
        with self._mu:
            self._replicas.pop(replica_id, None)

    def observe(self, hb: Heartbeat, now: float | None = None) -> bool:
        """Ingest one heartbeat; returns False for stale/duplicate beats
        (redelivered or reordered by the at-least-once pubsub layer)."""
        now = time.monotonic() if now is None else now
        with self._mu:
            view = self._replicas.setdefault(
                hb.replica_id, _ReplicaView(hb.replica_id)
            )
            if hb.seq <= view.seq:
                return False
            view.seq = hb.seq
            view.reported_state = hb.state
            if hb.role in ROLES:
                view.role = hb.role  # the beat is authoritative; an
                # unknown role string keeps the last known one (a newer
                # announcer gossiping a role this router predates must
                # not un-route the replica)
            view.last_seen = now
            view.queue_wait_s = float(hb.queue_wait_s)
            view.queue_depth = int(hb.queue_depth)
            view.slots_free = int(hb.slots_free)
            view.kv_free_frac = float(hb.kv_free_frac)
            view.hbm_free_frac = hb.hbm_free_frac
            view.preemptible = bool(hb.preemptible)
            view.reclaim_deadline_s = (
                float(hb.reclaim_deadline_s)
                if hb.reclaim_deadline_s is not None else None
            )
            if hb.epoch > view.epoch:
                # monotonic like seq: a redelivered pre-restart beat must
                # never roll the fence back to an epoch the engine rejects
                view.epoch = int(hb.epoch)
            if hb.state == UP and view.forced_down_reason is not None:
                # a FRESH healthy announcement outranks a stale breaker
                # verdict: the replica proved liveness after the breaker
                # opened (the breaker's own probe will re-close it too)
                view.forced_down_reason = None
            return True

    def mark_down(self, replica_id: str, reason: str = "breaker-open") -> None:
        """The breaker's fast path: the data plane saw the replica fail
        before the heartbeat timers did. Cleared by the next fresh UP
        heartbeat."""
        with self._mu:
            view = self._replicas.setdefault(
                replica_id, _ReplicaView(replica_id)
            )
            view.forced_down_reason = reason

    def state_of(self, replica_id: str, now: float | None = None) -> str:
        now = time.monotonic() if now is None else now
        with self._mu:
            view = self._replicas.get(replica_id)
            if view is None:
                return DOWN
            return view.effective_state(
                now, self.suspect_after_s, self.down_after_s
            )

    def load_of(self, replica_id: str) -> tuple[float, int]:
        """(queue_wait_s EWMA, queue_depth) as last reported."""
        with self._mu:
            view = self._replicas.get(replica_id)
            if view is None:
                return (float("inf"), 0)
            return (view.queue_wait_s, view.queue_depth)

    def headroom_of(self, replica_id: str) -> tuple[float, float | None]:
        """(kv_free_frac, hbm_free_frac) as last reported — the router's
        HBM-pressure spill reads these; hbm is None when the replica
        publishes no device-telemetry signal."""
        with self._mu:
            view = self._replicas.get(replica_id)
            if view is None:
                return (1.0, None)
            return (view.kv_free_frac, view.hbm_free_frac)

    def candidates(self, now: float | None = None, *,
                   role: str | None = None) -> list[str]:
        """Replica ids eligible for NEW work: every UP replica (least
        estimated wait first); when no UP replica exists, SUSPECT
        replicas (same order) — a tier-wide heartbeat blip must degrade
        to best-effort routing, not a total outage. DRAINING / WEDGED /
        RESTARTING / DOWN are never returned.

        ``role`` filters by disaggregation phase: ``role="decode"``
        returns decode + unified replicas, ``role="prefill"`` returns
        prefill + unified — a role-split replica is NEVER handed the
        other phase's work (role-mismatch rejection happens here, at
        candidate assembly, so no later path can route around it).
        ``None`` asks for whole-generation routing: prefill specialists
        are excluded (they must never stream tokens), while decode and
        unified replicas both qualify — a decode replica CAN compute its
        own prefill (role is policy, not capability), which is exactly
        the degrade path a dead handoff source falls back on."""
        now = time.monotonic() if now is None else now
        up: list[_ReplicaView] = []
        suspect: list[_ReplicaView] = []
        with self._mu:
            for view in self._replicas.values():
                if role is None:
                    if view.role == ROLE_PREFILL:
                        continue  # a prefill specialist never streams
                elif view.role not in (role, ROLE_UNIFIED):
                    continue
                state = view.effective_state(
                    now, self.suspect_after_s, self.down_after_s
                )
                if state == UP:
                    up.append(view)
                elif state == SUSPECT:
                    suspect.append(view)
        pool = up if up else suspect
        pool.sort(key=lambda v: (v.queue_wait_s, -v.slots_free, v.replica_id))
        return [v.replica_id for v in pool]

    def epoch_of(self, replica_id: str) -> int:
        """The replica's fence epoch as last gossiped (0 = unknown or
        unfenced). Routers stamp this on every per-attempt engine call;
        an engine that restarted since returns ErrorStaleEpoch and the
        router refreshes from the next beat."""
        with self._mu:
            view = self._replicas.get(replica_id)
            return view.epoch if view is not None else 0

    def is_preemptible(self, replica_id: str) -> bool:
        """Whether the replica runs on reclaimable capacity (as last
        registered or reported) — the router's interactive-class
        steering keys on this."""
        with self._mu:
            view = self._replicas.get(replica_id)
            return view.preemptible if view is not None else False

    def role_of(self, replica_id: str) -> str:
        with self._mu:
            view = self._replicas.get(replica_id)
            return view.role if view is not None else ROLE_UNIFIED

    def roles_present(self, now: float | None = None) -> set[str]:
        """Roles with at least one routable (UP/SUSPECT) replica — the
        router's disaggregation switch: a prefill AND a decode pool both
        present means requests split into a prefill phase + a KV handoff
        + a decode phase."""
        now = time.monotonic() if now is None else now
        out: set[str] = set()
        with self._mu:
            for view in self._replicas.values():
                state = view.effective_state(
                    now, self.suspect_after_s, self.down_after_s
                )
                if state in (UP, SUSPECT):
                    out.add(view.role)
        return out

    def snapshot(self, now: float | None = None) -> dict[str, Any]:
        now = time.monotonic() if now is None else now
        with self._mu:
            views = list(self._replicas.values())
        return {
            v.replica_id: v.snapshot(
                now, self.suspect_after_s, self.down_after_s
            )
            for v in views
        }

    def aggregate_queue_wait(self, role: str | None = None) -> float:
        """Mean reported queue-wait across live (UP/SUSPECT) replicas —
        the tier-level autoscaling signal (scale up when the whole tier
        is waiting, not when one replica hiccups). ``role`` narrows the
        mean to one pool — the SAME pool ``candidates(role=)`` routes
        to, unified replicas included: they absorb that role's traffic,
        and a signal blind to them would read a saturated mixed pool as
        idle and scale it down. The autoscaler sizes prefill and decode
        pools independently (a prefill backlog must grow the prefill
        pool, not add decode replicas that would sit idle)."""
        now = time.monotonic()
        with self._mu:
            waits = [
                v.queue_wait_s for v in self._replicas.values()
                if (role is None or v.role in (role, ROLE_UNIFIED))
                and v.effective_state(
                    now, self.suspect_after_s, self.down_after_s
                ) in (UP, SUSPECT)
            ]
        return sum(waits) / len(waits) if waits else 0.0

    def min_hbm_headroom(self, role: str | None = None) -> float | None:
        """The tightest reported HBM headroom across live replicas (of
        ``role``'s pool — unified replicas included, matching
        ``candidates(role=)`` — or all) — the autoscaler's
        memory-pressure signal. None when no live replica publishes a
        device-telemetry sample."""
        now = time.monotonic()
        best: float | None = None
        with self._mu:
            for v in self._replicas.values():
                if role is not None and v.role not in (role, ROLE_UNIFIED):
                    continue
                if v.effective_state(
                    now, self.suspect_after_s, self.down_after_s
                ) not in (UP, SUSPECT):
                    continue
                if v.hbm_free_frac is None:
                    continue
                if best is None or v.hbm_free_frac < best:
                    best = v.hbm_free_frac
        return best


class ReplicaAnnouncer:
    """The replica-side half of membership: a daemon thread that
    publishes this engine's heartbeat every ``interval_s`` over the
    pubsub layer, carrying supervisor state, shed EWMA queue-wait and
    KV headroom straight out of ``engine.health_check()``.

    The ``router.heartbeat`` chaos point sits on the publish path: a
    fault there IS a network partition — the beat is dropped (counted,
    never raised into the engine) and the router's timers must do the
    rest. Stop publishes one final beat so a deliberate drain/stop
    reaches the router ahead of the suspect timer."""

    def __init__(
        self,
        replica_id: str,
        engine: Any,
        publisher: Any,
        *,
        topic: str = HEARTBEAT_TOPIC,
        interval_s: float = 1.0,
        logger: Any = None,
        hbm_headroom: Callable[[], float | None] | None = None,
        advert_limit: int = 128,
        role: str | None = None,
    ) -> None:
        self.replica_id = replica_id
        self.engine = engine
        self.publisher = publisher
        self.topic = topic
        self.interval_s = interval_s
        self._logger = logger
        self._hbm_headroom = hbm_headroom
        # disaggregation role carried on every beat: explicit param wins,
        # else the engine's own declared role, else unified. A plain
        # string attribute — a pool driver repurposing the replica flips
        # it and the next beat reroutes the tier.
        self.role = role or getattr(engine, "role", None) or ROLE_UNIFIED
        # prefix-index advertisement bound: a heartbeat must stay a
        # heartbeat (0 disables advertising entirely)
        self.advert_limit = advert_limit
        self._seq = 0
        self._seq_mu = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.dropped_beats = 0  # partitioned (chaos) or failed publishes
        # terminal beats (stop's final announcement) lost even after the
        # bounded retry: the router waits out the SUSPECT timer instead
        self.dropped_final_beats = 0
        self._started_once = False  # a second start() is a re-register

    # -- heartbeat composition -------------------------------------------------
    def compose(self) -> Heartbeat:
        health: dict[str, Any] = {}
        try:
            health = self.engine.health_check() or {}
        except Exception:
            health = {"status": WEDGED, "details": {}}
        details = health.get("details") or {}
        shed = details.get("shed") or {}
        slots_total = details.get("slots_total", 0)
        slots_active = details.get("slots_active", 0)
        kv = details.get("kv_pages") or {}
        total_blocks = kv.get("total_blocks") or 0
        free_blocks = kv.get("free_blocks") or 0
        kv_free = (free_blocks / total_blocks) if total_blocks else 1.0
        depth = int(details.get("queue_depth", 0))
        ewma = float(shed.get("ewma_request_s", 0.0))
        waves = depth / max(int(slots_total) or 1, 1)
        if self._hbm_headroom is not None:
            hbm = self._hbm_headroom()
        else:
            # default wiring: the engine's device-telemetry poller
            # (serving/device_telemetry.py) publishes real HBM headroom —
            # the router's spill decisions act on actual device pressure,
            # not a permanently-stubbed None
            poller = getattr(self.engine, "device_telemetry", None)
            hbm = poller.hbm_headroom() if poller is not None else None
        prefix_keys = None
        if self.advert_limit > 0:
            advertise = getattr(self.engine, "prefix_advertisement", None)
            if advertise is not None:
                try:
                    prefix_keys = advertise(self.advert_limit)
                except Exception:
                    prefix_keys = None  # the index is advisory: never
                    # let it break the heartbeat the router's failure
                    # detection depends on
        # reclamation plane: the capacity class and, mid-notice, the
        # remaining evacuation budget ride the same beat the router's
        # failure detection already trusts
        preemptible = bool(getattr(self.engine, "preemptible", False))
        reclaim_deadline = None
        remaining = getattr(self.engine, "reclaim_remaining_s", None)
        if remaining is not None:
            try:
                reclaim_deadline = remaining()
            except Exception:
                reclaim_deadline = None
        with self._seq_mu:
            self._seq += 1
            seq = self._seq
        return Heartbeat(
            replica_id=self.replica_id,
            seq=seq,
            state=str(health.get("status", UP)),
            role=self.role,
            queue_wait_s=waves * ewma,
            queue_depth=depth,
            slots_free=max(int(slots_total) - int(slots_active), 0),
            kv_free_frac=kv_free,
            hbm_free_frac=hbm,
            ts=time.time(),
            prefix_keys=prefix_keys,
            preemptible=preemptible,
            reclaim_deadline_s=reclaim_deadline,
            # fence epoch gossips on every beat (0 for engines/stubs
            # that predate the HA plane — unfenced)
            epoch=int(getattr(self.engine, "epoch", 0) or 0),
        )

    def beat(self) -> bool:
        """Compose and publish one heartbeat. Returns False when the beat
        was dropped — an injected partition (``router.heartbeat``) or a
        broker failure; the announcer never lets either escape into the
        engine, because losing the control path must not hurt the data
        path."""
        hb = self.compose()
        try:
            chaos.maybe_fail("router.heartbeat")
            self.publisher.publish(self.topic, hb.to_json())
            return True
        except Exception as exc:
            self.dropped_beats += 1
            if self._logger is not None:
                self._logger.debug(
                    f"replica {self.replica_id}: heartbeat dropped: {exc}"
                )
            return False

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        if self._started_once:
            # re-register: this announcer is re-joining the tier (a
            # stop/start cycle a router may have observed as DOWN). Bump
            # the engine's fence epoch so any caller still holding the
            # pre-departure view is fenced — same rule as warm_restart.
            epoch = getattr(self.engine, "epoch", None)
            if isinstance(epoch, int):
                self.engine.epoch = epoch + 1
        self._started_once = True
        self.beat()  # announce immediately: the router learns of this
        # replica one beat sooner than the interval
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"replica-announcer-{self.replica_id}",
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.beat()

    def stop(self, final_beat: bool = True) -> None:
        """Stop announcing. ``final_beat`` publishes the engine's current
        state one last time (DRAINING on a graceful drain, DOWN after a
        stop) so the router reacts immediately instead of waiting out the
        suspect timer.

        The terminal beat is the one beat with no successor to paper over
        a dropped publish, so it gets ONE bounded, jittered retry (the
        jitter is deterministic per replica — a fleet-wide drain must not
        retry in lockstep against the broker that just dropped it). A
        beat lost twice is counted in ``dropped_final_beats``: the router
        falls back to its SUSPECT timer, which is the pre-existing
        behavior — the retry only narrows the window, never blocks stop
        beyond one interval."""
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
        self._thread = None
        if final_beat and not self.beat():
            import zlib

            jitter = (zlib.crc32(self.replica_id.encode()) % 50) / 1000.0
            time.sleep(min(self.interval_s * 0.5, 0.1) + jitter)
            if not self.beat():
                self.dropped_final_beats += 1
                if self._logger is not None:
                    self._logger.warn(
                        f"replica {self.replica_id}: terminal heartbeat "
                        "lost twice; router will rely on its suspect timer"
                    )

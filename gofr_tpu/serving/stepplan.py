"""Token-budget step scheduling for continuous batching.

The engine loop used to alternate monolithic bucketed prefills with decode
blocks, so one long prompt's prefill head-of-line-blocked every decoding
row (ROADMAP item 1). This module is the policy half of the fix: each
engine iteration a :class:`StepPlanner` assembles ONE :class:`StepPlan`
that mixes

- every live decode row (decode is reserved FIRST — the starvation
  guarantee: however much prefill work is queued, the next N-step decode
  block always dispatches), and
- up to ``prefill_chunk_tokens`` of prefill-chunk work, split across the
  oldest partially-prefilled requests (their :class:`ChunkCursor` carries
  the per-request chunk position between iterations), plus an admission
  quota for fresh requests.

The mechanism half — running the granted chunks and the decode block in
one unified ragged dispatch against the KV pool — lives in
``serving/batch.py`` (``ragged_step*``) and ``serving/engine.py``
(Ragged Paged Attention, arXiv:2604.15464).

Budget policy (docs/performance.md "Continuous batching"):

- ``step_token_budget == 0`` (auto, the default) reserves the decode
  block implicitly and grants exactly ``prefill_chunk_tokens`` of prefill
  per iteration — neither side can starve the other.
- An explicit ``step_token_budget`` is a hard per-iteration token target:
  decode rows (``rows * block_steps`` tokens) are subtracted first and
  prefill chunks fill whatever remains. Setting it at or below the decode
  reservation is an explicit decode-priority stance — prefill then only
  progresses in iterations with idle slots.
- Chunk grants go to cursors OLDEST FIRST (FIFO over admission order), so
  a long prompt drains steadily instead of interleaving fairly-but-
  forever with every later arrival; admission of new requests is gated on
  leftover budget so a saturated step admits nothing it cannot serve.

This module is pure policy: no device work, no locks — the engine thread
is the only caller. ``plan`` is a ``sched.plan`` chaos point (a fault
while assembling a step plan exercises the engine's per-step recovery).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from gofr_tpu import chaos


@dataclasses.dataclass
class ChunkCursor:
    """Per-request chunked-prefill carry: which prefix of the prompt is
    already committed to KV, and how far ahead dispatched-but-unconsumed
    chunk work runs (the device writes ahead of the committed host mirror
    by the in-flight ragged dispatches, exactly like decode's
    dispatched-ahead gap)."""

    req: Any                 # the engine's _Request
    slot: int
    total: int               # prompt tokens to prefill
    seq: int                 # admission order (FIFO grant order)
    priority: int = 0        # tenant priority (lower first): grants walk
    #                          (priority, seq), so a batch-class long
    #                          prompt cannot absorb the chunk budget
    #                          ahead of an interactive one
    committed: int = 0       # tokens confirmed resident at a consume
    dispatched: int = 0      # tokens handed to a ragged dispatch
    chunk_index: int = 0     # next chunk ordinal (timeline/span labels)
    prefix_hit: int = 0      # tokens skipped via cached chunk prefixes
    allocated: bool = False  # paged: slot pages claimed
    blocked: bool = False    # KV-pool pressure: requeue once not in flight
    # chunk-boundary prefix-cache keys, computed once per tenancy by the
    # engine ((start, end) -> key); None when chunk caching is off
    cache_keys: dict | None = None

    @property
    def remaining(self) -> int:
        return self.total - self.dispatched

    @property
    def in_flight(self) -> int:
        return self.dispatched - self.committed

    @property
    def done(self) -> bool:
        return self.committed >= self.total


@dataclasses.dataclass
class StepPlan:
    """One iteration's work assignment, assembled before any dispatch."""

    decode_rows: int                       # live rows the block serves
    decode_tokens: int                     # rows * block_steps (reserved)
    prefill_budget: int                    # chunk+admission tokens granted
    grants: list[tuple[int, int]]          # (slot, tokens) chunk grants
    admit_cap: int                         # fresh admissions this step
    budget_left: int                       # after chunk grants

    @property
    def prefill_tokens(self) -> int:
        return sum(n for _, n in self.grants)


class StepPlanner:
    """Assembles one :class:`StepPlan` per engine iteration."""

    def __init__(
        self,
        *,
        chunk_tokens: int,
        block_steps: int,
        step_token_budget: int = 0,
        max_admissions: int = 4,
    ) -> None:
        if chunk_tokens <= 0:
            raise ValueError("prefill_chunk_tokens must be positive")
        self.chunk_tokens = int(chunk_tokens)
        self.block_steps = max(1, int(block_steps))
        self.step_token_budget = max(0, int(step_token_budget))
        self.max_admissions = max(1, int(max_admissions))

    def plan(
        self,
        *,
        decode_rows: int,
        cursors: list[ChunkCursor],
        free_slots: int,
        queue_depth: int,
    ) -> StepPlan:
        """Decode first, then chunk grants oldest-cursor-first, then an
        admission quota out of the leftover budget."""
        chaos.maybe_fail("sched.plan")
        decode_tokens = decode_rows * self.block_steps
        if self.step_token_budget:
            prefill_budget = max(0, self.step_token_budget - decode_tokens)
        else:
            # auto: decode is implicitly reserved (the block dispatches
            # regardless); prefill gets one chunk budget per iteration
            prefill_budget = self.chunk_tokens
        budget = prefill_budget
        grants: list[tuple[int, int]] = []
        # priority-aware grant order (multi-tenant plane, docs/serving.md
        # "Multi-tenancy"): higher classes (lower priority number) drain
        # first; FIFO within a class — the PR 10 starvation guarantee
        # (decode reserved first) is unchanged, only the PREFILL budget
        # walk became class-aware
        for cur in sorted(cursors, key=lambda c: (c.priority, c.seq)):
            if budget <= 0:
                break
            if cur.blocked or cur.remaining <= 0:
                continue
            # grants are WHOLE chunks (or the prompt's final ragged tail),
            # never budget-truncated partials: chunk boundaries double as
            # page-grid write boundaries and chunk-prefix cache keys, so a
            # mid-chunk split would misalign both. A cursor whose next
            # chunk does not fit the remaining budget waits an iteration
            # instead of fragmenting it.
            grant = min(self.chunk_tokens, cur.remaining)
            if grant > budget:
                continue
            grants.append((cur.slot, grant))
            budget -= grant
        # fresh admissions scale with leftover budget and free slots;
        # single-chunk (bucketed) prefills are additionally bounded by the
        # native scheduler's own per-admit token budget, so one iteration
        # can never absorb an unbounded monolithic prefill burst. The
        # quota NEVER drops below one while the queue is non-empty:
        # canceled-but-queued requests are only ever delivered (and
        # settled) through an admit() call, and a zero-cap iteration
        # would strand them behind a saturated batch forever.
        admit_cap = 0
        if queue_depth > 0:
            admit_cap = 1
            if free_slots > 0 and budget > 0:
                admit_cap = min(self.max_admissions, max(free_slots, 1))
        return StepPlan(
            decode_rows=decode_rows,
            decode_tokens=decode_tokens,
            prefill_budget=prefill_budget,
            grants=grants,
            admit_cap=admit_cap,
            budget_left=budget,
        )

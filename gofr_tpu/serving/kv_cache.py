"""Paged KV cache: pooled pages + native block-table accounting.

Replaces per-slot dense KV rows ([slots, max_seq] preallocation) with a
shared page pool ([L, N_pages+1, Hkv, page, Dh] — the +1 is a trash page
for inactive rows' redirected writes): sequences own pages through the
native BlockAllocator (native/runtime/gofr_runtime.cc — the refcounted
allocator with copy-on-write forks), so HBM is committed by tokens
actually resident, not by worst-case slots. SURVEY §5.7 lever (a).

Host side (this class): page accounting, block tables, seq lens.
Device side: scatter prefilled slabs into owned pages (_write_pages); the
decode-step append lives inside llama.decode_step_paged (per layer), and
the read path is ops/paged_attention.py.

shardcheck retrace/donation zone: the pool buffers are donated through
every _write_pages*/decode dispatch and MUST be rebound in the same
statement (``use-after-donation``, docs/static-analysis.md) — a stale
``self.k_pool`` read after a donating call is the round-4 on-TPU crash.
The ``_write_pages*`` entries are declared in the kernel contract table
(``gofr_tpu/analysis/kernel_contracts.KERNELS``) — pool/slab signatures
and the donation sets are enforced by kernelcheck and replayed by the
kerneltrace eval_shape matrix.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu import chaos
from gofr_tpu.models.llama import quantize_kv
from gofr_tpu.native.runtime import BlockAllocator, OutOfBlocks

__all__ = ["PagedKVCache", "OutOfBlocks"]


@partial(jax.jit, donate_argnums=(0, 1))
def _write_pages(
    k_pool: jnp.ndarray,  # [L, N, Hkv, page, Dh] donated
    v_pool: jnp.ndarray,
    k_slab: jnp.ndarray,  # [L, S_pad, Hkv, Dh] (S_pad = n_pages*page)
    v_slab: jnp.ndarray,
    page_ids: jnp.ndarray,  # [n_pages] int32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    L, S_pad, Hkv, Dh = k_slab.shape
    n_pages = page_ids.shape[0]
    page = S_pad // n_pages
    # [L, n_pages, Hkv, page, Dh] to match the pool's kernel-friendly layout
    k_pages = k_slab.reshape(L, n_pages, page, Hkv, Dh).transpose(0, 1, 3, 2, 4)
    v_pages = v_slab.reshape(L, n_pages, page, Hkv, Dh).transpose(0, 1, 3, 2, 4)
    return (
        k_pool.at[:, page_ids].set(k_pages),
        v_pool.at[:, page_ids].set(v_pages),
    )


@partial(jax.jit, donate_argnums=(0, 1, 2, 3))
def _write_pages_q(
    k_pool: jnp.ndarray,  # [L, N, Hkv, page, Dh] int8, donated
    v_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # [L, N, Hkv, page, 1] f32, donated
    vs_pool: jnp.ndarray,
    k_slab: jnp.ndarray,  # [L, S_pad, Hkv, Dh] full-width prefill slab
    v_slab: jnp.ndarray,
    page_ids: jnp.ndarray,  # [n_pages] int32
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """int8 twin of :func:`_write_pages`: per-vector absmax quantization
    at the prefill scatter."""
    L, S_pad, Hkv, Dh = k_slab.shape
    n_pages = page_ids.shape[0]
    page = S_pad // n_pages
    kq, ks = quantize_kv(k_slab)  # int8 [L,S,Hkv,Dh], f32 [L,S,Hkv]
    vq, vs = quantize_kv(v_slab)
    k_pages = kq.reshape(L, n_pages, page, Hkv, Dh).transpose(0, 1, 3, 2, 4)
    v_pages = vq.reshape(L, n_pages, page, Hkv, Dh).transpose(0, 1, 3, 2, 4)
    ks_pages = ks.reshape(L, n_pages, page, Hkv, 1).transpose(0, 1, 3, 2, 4)
    vs_pages = vs.reshape(L, n_pages, page, Hkv, 1).transpose(0, 1, 3, 2, 4)
    return (
        k_pool.at[:, page_ids].set(k_pages),
        v_pool.at[:, page_ids].set(v_pages),
        ks_pool.at[:, page_ids].set(ks_pages),
        vs_pool.at[:, page_ids].set(vs_pages),
    )


class PagedKVCache:
    """Owns the device page pool + host page accounting for up to
    ``max_slots`` concurrent sequences."""

    def __init__(
        self,
        cfg: Any,  # LlamaConfig-shaped (n_layers, n_kv_heads, head_dim)
        *,
        num_pages: int,
        page_size: int = 16,
        max_slots: int = 8,
        max_seq_len: int = 1024,
        dtype: Any = None,
        kv_dtype: str | None = None,
    ) -> None:
        self.cfg = cfg
        self.page_size = page_size
        self.num_pages = num_pages
        self.max_slots = max_slots
        self.max_seq_len = max_seq_len
        self.max_pages_per_seq = (max_seq_len + page_size - 1) // page_size
        self.quantized = kv_dtype == "int8"
        dtype = dtype or cfg.dtype
        self._pool_dtype = dtype
        self.reset_pools()
        self.allocator = BlockAllocator(num_pages, page_size)
        # host mirrors (authoritative): per-slot block table + length
        self.tables = np.zeros((max_slots, self.max_pages_per_seq), np.int32)
        self.seq_lens = np.zeros(max_slots, np.int32)
        self._slot_seq: list[int | None] = [None] * max_slots

    def reset_pools(self) -> None:
        """(Re)allocate the device page pools. Called at init and by engine
        recovery when a dispatch that failed after donation committed left
        the pools deleted (serving/engine.py:_rebuild_kv) — resident pages
        are unrecoverable either way; fresh zeros restore a servable pool.

        [L, N+1, Hkv, page, Dh]: trailing (page, Dh) are full dims in the
        pallas BlockSpecs (ops/paged_attention.py) — Mosaic tiling rule.
        The extra LAST page is the trash page: inactive rows' decode
        appends are redirected there (llama.decode_step_paged), so the
        scatter never has conflicting writes to a live page."""
        cfg = self.cfg
        shape = (
            cfg.n_layers, self.num_pages + 1, cfg.n_kv_heads,
            self.page_size, cfg.head_dim,
        )
        # build every array BEFORE assigning any: a mid-rebuild failure
        # (backend still down during recovery) must not leave a half-fresh
        # pool set that the engine's health probe — it samples k_pool —
        # would report healthy while v_pool is still deleted
        if self.quantized:
            sshape = shape[:-1] + (1,)
            pools = (
                jnp.zeros(shape, jnp.int8), jnp.zeros(shape, jnp.int8),
                jnp.zeros(sshape, jnp.float32), jnp.zeros(sshape, jnp.float32),
            )
        else:
            pools = (
                jnp.zeros(shape, self._pool_dtype),
                jnp.zeros(shape, self._pool_dtype),
                None, None,
            )
        self.k_pool, self.v_pool, self.ks_pool, self.vs_pool = pools

    # ------------------------------------------------------------- accounting
    def alloc_slot(
        self, slot: int, seq_id: int, prompt_len: int,
        reserve_tokens: int | None = None,
    ) -> None:
        """Reserve pages for a prompt (``reserve_tokens`` ≥ prompt_len when
        prefill buckets pad past the prompt). Raises OutOfBlocks (caller
        keeps the request queued) without touching slot state on failure.
        The allocator tracks RESERVED capacity; true length lives in
        ``seq_lens``."""
        if self._slot_seq[slot] is not None:
            raise KeyError(f"slot {slot} busy")
        chaos.maybe_fail("kv.alloc")
        self.allocator.alloc(seq_id, max(prompt_len, reserve_tokens or 0))
        table = self.allocator.block_table(seq_id)
        self._slot_seq[slot] = seq_id
        self.tables[slot, : len(table)] = table
        self.tables[slot, len(table):] = 0
        self.seq_lens[slot] = prompt_len

    def extend_slot(self, slot: int) -> None:
        """Account one appended token (decode). Raises OutOfBlocks when the
        pool is exhausted — the engine must retire or spill a sequence."""
        seq_id = self._slot_seq[slot]
        assert seq_id is not None
        new_len = int(self.seq_lens[slot]) + 1
        if new_len > self.allocator.seq_length(seq_id):
            chaos.maybe_fail("kv.alloc")
            self.allocator.extend(seq_id, new_len)
            table = self.allocator.block_table(seq_id)
            self.tables[slot, : len(table)] = table
        self.seq_lens[slot] = new_len

    def try_reserve_chunk(self, slots: list[int], tokens: int) -> bool:
        """Reserve page COVERAGE for up to ``tokens`` further positions on
        every slot, or none — WITHOUT advancing seq_lens (speculative
        verify writes up to ``tokens`` positions but commits only the
        accepted prefix; lengths advance later via :meth:`advance_slot` —
        the block-stepped decode path uses the per-row twin
        :meth:`try_reserve_slot` the same way). Per-slot targets clamp to
        max_seq_len: a row one token short of the limit reserves exactly
        its last page rather than overflowing the block-table width —
        chunk positions past the clamp divert to the trash page via the
        kv_capacity write guard. Returns False untouched when the pool
        can't cover all slots."""
        targets = []
        needed = 0
        for slot in slots:
            seq_id = self._slot_seq[slot]
            assert seq_id is not None
            target = min(int(self.seq_lens[slot]) + tokens, self.max_seq_len)
            targets.append((slot, seq_id, target))
            # compare against blocks actually OWNED: the reservation may
            # sit mid-page, in which case the remaining page capacity
            # absorbs the chunk with zero new blocks (code-review r4)
            owned = len(self.allocator.block_table(seq_id))
            needed += max(0, self.pages_needed(target) - owned)
        if needed > self.allocator.stats()["free_blocks"]:
            return False
        for slot, seq_id, target in targets:
            if target > self.allocator.seq_length(seq_id):
                self.allocator.extend(seq_id, target)
                table = self.allocator.block_table(seq_id)
                self.tables[slot, : len(table)] = table
        return True

    def try_reserve_slot(self, slot: int, tokens: int) -> bool:
        """Reserve page COVERAGE for up to ``tokens`` positions past the
        slot's committed length, or nothing — the per-row twin of
        :meth:`try_reserve_chunk`, used by the block-stepped decode loop
        where each row's dispatched-ahead depth differs (the device runs
        ahead of the committed host mirror by the in-flight blocks).
        Clamps to max_seq_len like the chunk variant; lengths advance
        later via :meth:`advance_slot` as blocks are consumed. Returns
        False untouched when the pool cannot cover the target."""
        seq_id = self._slot_seq[slot]
        assert seq_id is not None
        target = min(int(self.seq_lens[slot]) + tokens, self.max_seq_len)
        owned = len(self.allocator.block_table(seq_id))
        needed = max(0, self.pages_needed(target) - owned)
        if needed > self.allocator.stats()["free_blocks"]:
            return False
        if target > self.allocator.seq_length(seq_id):
            try:
                chaos.maybe_fail("kv.alloc")
                self.allocator.extend(seq_id, target)
            except OutOfBlocks:
                # free_blocks raced another consumer (or the chaos point
                # fired): same contract as the capacity check above
                return False
            table = self.allocator.block_table(seq_id)
            self.tables[slot, : len(table)] = table
        return True

    def advance_slot(self, slot: int, n_tokens: int) -> None:
        """Commit ``n_tokens`` accepted positions (speculative decode).
        The caller reserved coverage up front (try_reserve_chunk), so this
        never allocates."""
        self.seq_lens[slot] = int(self.seq_lens[slot]) + n_tokens

    def owned_capacity(self, slot: int) -> int:
        """Tokens covered by the slot's OWNED pages — the write guard for
        chunk verifies (positions past this must spill to the trash page,
        never through the zero-filled table tail into live page 0)."""
        seq_id = self._slot_seq[slot]
        if seq_id is None:
            return 0
        return len(self.allocator.block_table(seq_id)) * self.page_size

    def free_slot(self, slot: int) -> None:
        seq_id = self._slot_seq[slot]
        if seq_id is None:
            return
        self.allocator.free(seq_id)
        self._slot_seq[slot] = None
        self.tables[slot] = 0
        self.seq_lens[slot] = 0

    def pages_needed(self, tokens: int) -> int:
        return (tokens + self.page_size - 1) // self.page_size

    def stats(self) -> dict[str, int]:
        s = self.allocator.stats()
        s["page_size"] = self.page_size
        return s

    # ------------------------------------------------------------- device ops
    def write_prefill(self, slot: int, k_slab: jnp.ndarray, v_slab: jnp.ndarray) -> None:
        """Scatter a prefilled slab [L, S_bucket, Hkv, Dh] into the slot's
        pages (S_bucket rounded up to whole pages; surplus pages of the
        bucket beyond the owned table are masked by seq_lens at read)."""
        seq_id = self._slot_seq[slot]
        assert seq_id is not None
        L, S, Hkv, Dh = k_slab.shape
        n_pages = self.pages_needed(S)
        pad = n_pages * self.page_size - S
        if pad:
            k_slab = jnp.pad(k_slab, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v_slab = jnp.pad(v_slab, ((0, 0), (0, pad), (0, 0), (0, 0)))
        owned = self.allocator.block_table(seq_id)
        if n_pages > len(owned):
            # bucket padding spilled past the reservation: grow it
            self.allocator.extend(seq_id, n_pages * self.page_size)
            owned = self.allocator.block_table(seq_id)
            self.tables[slot, : len(owned)] = owned
        page_ids = jnp.asarray(owned[:n_pages], jnp.int32)
        if self.quantized:
            (self.k_pool, self.v_pool, self.ks_pool, self.vs_pool) = _write_pages_q(
                self.k_pool, self.v_pool, self.ks_pool, self.vs_pool,
                k_slab, v_slab, page_ids,
            )
        else:
            self.k_pool, self.v_pool = _write_pages(
                self.k_pool, self.v_pool, k_slab, v_slab, page_ids
            )

    def write_span(
        self, slot: int, start: int, k_slab: jnp.ndarray, v_slab: jnp.ndarray
    ) -> None:
        """Scatter a cached chunk-prefix slab [L, C, Hkv, Dh] into the
        pages covering token span [start, start+C) — ``write_prefill``'s
        offset twin for chunked admissions that skip cached chunk
        prefixes. ``start`` must be page-aligned; the caller reserved
        coverage through ``alloc_slot``/``try_reserve_slot`` first. The
        slab is padded to whole pages (pad positions sit beyond
        ``seq_lens`` and are masked at read)."""
        if self.quantized:
            raise ValueError("write_span: int8 pools take no cached slabs")
        if start % self.page_size:
            raise ValueError(f"write_span start {start} not page-aligned")
        seq_id = self._slot_seq[slot]
        assert seq_id is not None
        L, C, Hkv, Dh = k_slab.shape
        p0 = start // self.page_size
        p1 = self.pages_needed(start + C)
        pad = (p1 - p0) * self.page_size - C
        if pad:
            k_slab = jnp.pad(k_slab, ((0, 0), (0, pad), (0, 0), (0, 0)))
            v_slab = jnp.pad(v_slab, ((0, 0), (0, pad), (0, 0), (0, 0)))
        owned = self.allocator.block_table(seq_id)
        if p1 > len(owned):
            self.allocator.extend(seq_id, p1 * self.page_size)
            owned = self.allocator.block_table(seq_id)
            self.tables[slot, : len(owned)] = owned
        page_ids = jnp.asarray(owned[p0:p1], jnp.int32)
        self.k_pool, self.v_pool = _write_pages(
            self.k_pool, self.v_pool, k_slab, v_slab, page_ids
        )

    def read_span(
        self, slot: int, start: int, end: int
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Gather the slot's resident K/V for token span [start, end) out
        of the page pool into contiguous slabs [L, end-start, Hkv, Dh] —
        the chunk-prefix cache's extraction path (serving/engine.py).
        ``start`` must be page-aligned (chunk boundaries are); the gather
        is a pure device read (no sync, nothing donated) and the returned
        slabs are fresh buffers safe to retain across later dispatches.
        bf16 pools only: a quantized pool would have to dequantize here,
        and re-quantizing on the next hit would drift — the engine keeps
        chunk-prefix caching off for int8 layouts."""
        if self.quantized:
            raise ValueError("read_span: int8 pools are not extractable")
        if start % self.page_size:
            raise ValueError(f"read_span start {start} not page-aligned")
        p0 = start // self.page_size
        p1 = self.pages_needed(end)
        page_ids = self.tables[slot, p0:p1]
        k = self.k_pool[:, page_ids]  # [L, n, Hkv, page, Dh]
        v = self.v_pool[:, page_ids]
        L, n, Hkv, page, Dh = k.shape
        k = k.transpose(0, 1, 3, 2, 4).reshape(L, n * page, Hkv, Dh)
        v = v.transpose(0, 1, 3, 2, 4).reshape(L, n * page, Hkv, Dh)
        off = start - p0 * self.page_size  # 0 by alignment, kept explicit
        return k[:, off : off + (end - start)], v[:, off : off + (end - start)]

    def tables_device(self) -> jnp.ndarray:
        # .copy(): host→device transfers are async, and the engine's
        # pipelined dispatch mutates self.tables (extend_slot) while the
        # previous step's transfer may still be pending — upload a snapshot
        # the host never touches again
        return jnp.asarray(self.tables.copy())

    def seq_lens_device(self) -> jnp.ndarray:
        return jnp.asarray(self.seq_lens.copy())

    def close(self) -> None:
        self.allocator.close()

    def leak(self) -> None:  # leakcheck: transfer(quarantine)
        """Quarantine-leak the native allocator (engine warm restart under
        a hung thread): the page pools are plain device arrays the GC can
        reclaim once the thread thaws, but the native handle must never be
        destroyed under a thread that may still be inside it."""
        self.allocator.leak()

"""Jitted fixed-shape device functions for the continuous-batching engine.

All shapes are static (slot count, padded prompt buckets) so everything
compiles once per bucket and never again — the XLA contract. Slots are rows
of a persistent batch KV cache; requests come and go between steps by
scattering into / masking out rows, with buffers donated end-to-end so the
cache never copies.

Device-side state per engine:
- ``SlotCache``: k/v [L, B_slots, S_max, Hkv, Dh]
- ``cache_len``  [B_slots] valid length per slot (0 = free)
- ``last_token`` [B_slots]
- per-slot sampling params (temperature/top_k/top_p) + PRNG key

This file is a shardcheck retrace zone (``make lint``): donated buffers
must be rebound at every call site (``use-after-donation``) and nothing
here may branch on traced values or take unhashable statics
(``retrace-hazard``) — one per-request recompile eats the whole TTFT
budget.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from gofr_tpu.models import llama
from gofr_tpu.ops.sampling import sample_logits


@partial(jax.jit, static_argnums=0)
def prefill_compute(
    cfg: llama.LlamaConfig,
    params: dict,
    tokens: jnp.ndarray,  # [1, S_bucket] right-padded
    seq_len: jnp.ndarray,  # [1]
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run prefill WITHOUT a persistent cache: returns (last_logits [1,V],
    k_slab, v_slab [L, S_bucket, Hkv, Dh]) for scatter into a slot."""
    scratch = llama.KVCache.create(cfg, 1, max_len=tokens.shape[1])
    last, cache = llama.prefill(cfg, params, tokens, scratch, seq_len)
    return last, cache.k[:, 0], cache.v[:, 0]


@partial(jax.jit, donate_argnums=(0, 1))
def insert_slot(
    k_cache: jnp.ndarray,  # [L, B, S_max, Hkv, Dh] donated
    v_cache: jnp.ndarray,
    k_slab: jnp.ndarray,  # [L, S_bucket, Hkv, Dh]
    v_slab: jnp.ndarray,
    slot: jnp.ndarray,  # scalar int32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefilled slab into slot row [.., slot, :S_bucket]."""
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_slab[:, None], (0, slot, 0, 0, 0)
    )
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_slab[:, None], (0, slot, 0, 0, 0)
    )
    return k_cache, v_cache


@partial(jax.jit, donate_argnums=(0,))
def insert_slot_quantized(
    cache: llama.KVCache,  # int8 cache (donated)
    k_slab: jnp.ndarray,  # [L, S_bucket, Hkv, Dh] full-width prefill slab
    v_slab: jnp.ndarray,
    slot: jnp.ndarray,  # scalar int32
) -> llama.KVCache:
    """int8 twin of :func:`insert_slot`: quantize the full-width prefill
    slabs (per-vector absmax) and scatter payload + scales into the slot
    row of the quantized cache."""
    kq, kscale = llama.quantize_kv(k_slab)
    vq, vscale = llama.quantize_kv(v_slab)
    return llama.KVCache(
        jax.lax.dynamic_update_slice(cache.k, kq[:, None], (0, slot, 0, 0, 0)),
        jax.lax.dynamic_update_slice(cache.v, vq[:, None], (0, slot, 0, 0, 0)),
        jax.lax.dynamic_update_slice(cache.ks, kscale[:, None], (0, slot, 0, 0)),
        jax.lax.dynamic_update_slice(cache.vs, vscale[:, None], (0, slot, 0, 0)),
    )


@partial(jax.jit, static_argnums=0, donate_argnums=(2,))
def decode_and_sample_pipelined(
    cfg: llama.LlamaConfig,
    params: dict,
    cache: llama.KVCache,  # donated
    last_token: jnp.ndarray,  # [B] device-resident (prev step's output)
    cache_len: jnp.ndarray,  # [B] device-resident
    active: jnp.ndarray,  # [B] bool
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, llama.KVCache, jnp.ndarray, jax.Array]:
    """One continuous-batching decode step over all slots: forward, per-slot
    sampling. Advances cache_len device-side (active rows only) so the
    host never uploads it per step — the engine's dispatch loop stays
    upload-free in steady state (VERDICT r3 weak #2). Inactive slots
    compute garbage safely (step_len clamped to 1) and are ignored by the
    host."""
    step_len = jnp.where(active, cache_len + 1, 1)
    logits, cache = llama.decode_step(cfg, params, last_token, cache, step_len)
    rng, sample_key = jax.random.split(rng)
    next_token = sample_logits(
        logits, sample_key, temperature=temperature, top_k=top_k, top_p=top_p
    )
    new_len = jnp.where(active, cache_len + 1, cache_len)
    return next_token, cache, new_len, rng


@partial(jax.jit, static_argnums=(0, 10), donate_argnums=(2,))
def decode_and_sample_multi(
    cfg: llama.LlamaConfig,
    params: dict,
    cache: llama.KVCache,  # donated
    last_token: jnp.ndarray,  # [B] device-resident
    cache_len: jnp.ndarray,  # [B] device-resident
    active: jnp.ndarray,  # [B] bool
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
    steps: int,
) -> tuple[jnp.ndarray, jnp.ndarray, llama.KVCache, jnp.ndarray, jax.Array]:
    """``steps`` decode iterations in ONE dispatch (lax.scan): the host
    pays per-dispatch overhead once per chunk instead of once per token —
    the decisive lever when dispatch latency rivals step compute (remote/
    tunneled backends, small models). Returns (tokens [B, steps],
    final_token [B], cache, cache_len, rng). The engine only uses chunks
    for rows that need ≥steps more tokens; a row that emits a stop token
    mid-chunk wastes the tail steps (bounded, host discards them)."""

    def step(carry, _):
        cache, last, clen, r = carry
        step_len = jnp.where(active, clen + 1, 1)
        logits, cache = llama.decode_step(cfg, params, last, cache, step_len)
        r, key = jax.random.split(r)
        nxt = sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )
        new_len = jnp.where(active, clen + 1, clen)
        return (cache, nxt, new_len, r), nxt

    (cache, last, new_len, rng), toks = jax.lax.scan(
        step, (cache, last_token, cache_len, rng), None, length=steps
    )
    return jnp.transpose(toks), last, cache, new_len, rng


@jax.jit
def scatter_slot_state(
    last_token: jnp.ndarray,  # [B] NOT donated: it aliases the in-flight
    # step's next_token, which the host still has to read at consume time
    cache_len: jnp.ndarray,  # [B] NOT donated either: at 4·B bytes donation
    # saves nothing, and it was the engine's only donated int32[B] buffer —
    # the exact shape of the round-4 on-TPU crash ("Array has been deleted
    # with shape=int32[32]", BENCH_LOCAL.jsonl). Over an unreliable remote
    # backend a dispatch that fails after donation commits leaves the host
    # handle deleted; per-step scalar state is never worth that class of bug.
    slots: jnp.ndarray,  # [K] int32
    tokens: jnp.ndarray,  # [K] int32
    lens: jnp.ndarray,  # [K] int32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Fold freshly-prefilled slots' (first token, prompt len) into the
    device-resident decode state in one fused scatter."""
    return last_token.at[slots].set(tokens), cache_len.at[slots].set(lens)


@partial(jax.jit, static_argnums=0, donate_argnums=(2, 3))
def decode_and_sample_paged(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # [L, N_pages+1, Hkv, page, Dh] donated (+1: trash page)
    v_pool: jnp.ndarray,  # donated
    block_tables: jnp.ndarray,  # [B, M]
    seq_lens: jnp.ndarray,  # [B] length incl. this token (>=1 when active)
    last_token: jnp.ndarray,  # [B]
    active: jnp.ndarray,  # [B] bool
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array]:
    """Paged-cache twin of :func:`decode_and_sample_pipelined`: one step over the
    page pool (llama.decode_step_paged), per-slot sampling."""
    step_len = jnp.where(active, jnp.maximum(seq_lens, 1), 1)
    logits, k_pool, v_pool = llama.decode_step_paged(
        cfg, params, last_token, k_pool, v_pool, block_tables, step_len, active
    )
    rng, sample_key = jax.random.split(rng)
    next_token = sample_logits(
        logits, sample_key, temperature=temperature, top_k=top_k, top_p=top_p
    )
    return next_token, k_pool, v_pool, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2, 3, 4, 5))
def decode_and_sample_paged_q(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # int8, donated
    v_pool: jnp.ndarray,  # donated
    ks_pool: jnp.ndarray,  # f32 scales, donated
    vs_pool: jnp.ndarray,  # donated
    block_tables: jnp.ndarray,
    seq_lens: jnp.ndarray,
    last_token: jnp.ndarray,
    active: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array]:
    """int8 twin of :func:`decode_and_sample_paged`."""
    step_len = jnp.where(active, jnp.maximum(seq_lens, 1), 1)
    logits, k_pool, v_pool, ks_pool, vs_pool = llama.decode_step_paged_q(
        cfg, params, last_token, k_pool, v_pool, ks_pool, vs_pool,
        block_tables, step_len, active,
    )
    rng, sample_key = jax.random.split(rng)
    next_token = sample_logits(
        logits, sample_key, temperature=temperature, top_k=top_k, top_p=top_p
    )
    return next_token, k_pool, v_pool, ks_pool, vs_pool, rng


@partial(jax.jit, static_argnums=(0, 12), donate_argnums=(2, 3))
def decode_and_sample_paged_multi(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # donated
    v_pool: jnp.ndarray,  # donated
    block_tables: jnp.ndarray,  # [B, M] — already covers the whole chunk
    seq_start: jnp.ndarray,  # [B] length INCLUDING the chunk's first token
    last_token: jnp.ndarray,  # [B]
    active: jnp.ndarray,  # [B] bool
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
    steps: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array]:
    """``steps`` paged decode iterations in ONE dispatch. The page
    accounting happened up front (PagedKVCache.try_extend_chunk), so the
    block tables already address every position the chunk writes; step s
    runs at length ``seq_start + s``. Returns (tokens [B, steps],
    final_token, k_pool, v_pool, rng)."""

    def step(carry, s):
        kp, vp, last, r = carry
        step_len = jnp.where(active, seq_start + s, 1)
        logits, kp, vp = llama.decode_step_paged(
            cfg, params, last, kp, vp, block_tables, step_len, active
        )
        r, key = jax.random.split(r)
        nxt = sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )
        return (kp, vp, nxt, r), nxt

    (k_pool, v_pool, last, rng), toks = jax.lax.scan(
        step, (k_pool, v_pool, last_token, rng), jnp.arange(steps)
    )
    return jnp.transpose(toks), last, k_pool, v_pool, rng


@partial(jax.jit, static_argnums=(0, 14), donate_argnums=(2, 3, 4, 5))
def decode_and_sample_paged_multi_q(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # int8, donated
    v_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # f32 scales, donated
    vs_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    seq_start: jnp.ndarray,
    last_token: jnp.ndarray,
    active: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
    steps: int,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray, jax.Array]:
    """int8 twin of :func:`decode_and_sample_paged_multi`."""

    def step(carry, s):
        kp, vp, ksp, vsp, last, r = carry
        step_len = jnp.where(active, seq_start + s, 1)
        logits, kp, vp, ksp, vsp = llama.decode_step_paged_q(
            cfg, params, last, kp, vp, ksp, vsp, block_tables, step_len, active
        )
        r, key = jax.random.split(r)
        nxt = sample_logits(
            logits, key, temperature=temperature, top_k=top_k, top_p=top_p
        )
        return (kp, vp, ksp, vsp, nxt, r), nxt

    (k_pool, v_pool, ks_pool, vs_pool, last, rng), toks = jax.lax.scan(
        step, (k_pool, v_pool, ks_pool, vs_pool, last_token, rng),
        jnp.arange(steps),
    )
    return jnp.transpose(toks), last, k_pool, v_pool, ks_pool, vs_pool, rng


# ----------------------------------------------------- speculative decoding
def _accept_and_bonus(
    chunk: jnp.ndarray,  # [B, T] (pos 0 = last committed; 1.. = drafts, -1 pad)
    logits: jnp.ndarray,  # [B, T, V] from a chunk verify forward
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jax.Array]:
    """Greedy draft acceptance + per-row bonus sampling, fused device-side.

    Position i's logits predict the token after chunk token i, so draft
    chunk[:, i+1] is accepted iff argmax(logits[:, i]) equals it AND every
    earlier draft was accepted (cumulative product). -1 padding never
    matches, so per-row draft counts need no separate length input. The
    bonus token samples from logits at the first rejected position with
    the row's own sampling params — rows the engine didn't draft for
    (temperature > 0) therefore take exactly a normal sampled step.
    Returns (tokens [B, T] — accepted drafts then bonus, -1 beyond —
    n_accept [B], rng)."""
    B, T = chunk.shape
    greedy = jnp.argmax(logits, axis=-1)  # [B, T]
    drafts = chunk[:, 1:]  # [B, T-1]
    match = (greedy[:, :-1] == drafts) & (drafts >= 0)
    n_accept = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    bonus_logits = jnp.take_along_axis(
        logits, n_accept[:, None, None], axis=1
    )[:, 0]  # [B, V]
    rng, key = jax.random.split(rng)
    bonus = sample_logits(
        bonus_logits, key, temperature=temperature, top_k=top_k, top_p=top_p
    )
    idx = jnp.arange(T)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), drafts.dtype)], axis=1
    )
    out = jnp.where(
        idx < n_accept[:, None], drafts_pad,
        jnp.where(idx == n_accept[:, None], bonus[:, None], -1),
    )
    return out, n_accept, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2,))
def verify_and_sample(
    cfg: llama.LlamaConfig,
    params: dict,
    cache: llama.KVCache,  # donated (bf16 or int8 dense)
    chunk: jnp.ndarray,  # [B, T]
    start_len: jnp.ndarray,  # [B] committed length before the chunk
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, llama.KVCache, jax.Array]:
    """Speculative engine step, dense cache: chunk-verify forward + draft
    acceptance + bonus sampling in ONE dispatch. Returns
    (tokens [B, T], n_accept [B], cache, rng)."""
    logits, cache = llama.decode_chunk.__wrapped__(
        cfg, params, chunk, cache, start_len
    )
    out, n_accept, rng = _accept_and_bonus(
        chunk, logits, temperature, top_k, top_p, rng
    )
    return out, n_accept, cache, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2, 3))
def verify_and_sample_paged(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # donated
    v_pool: jnp.ndarray,  # donated
    block_tables: jnp.ndarray,
    chunk: jnp.ndarray,
    start_len: jnp.ndarray,
    active: jnp.ndarray,
    kv_capacity: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array]:
    """Paged twin of :func:`verify_and_sample`."""
    logits, k_pool, v_pool = llama.decode_chunk_paged.__wrapped__(
        cfg, params, chunk, k_pool, v_pool, block_tables, start_len,
        active, kv_capacity,
    )
    out, n_accept, rng = _accept_and_bonus(
        chunk, logits, temperature, top_k, top_p, rng
    )
    return out, n_accept, k_pool, v_pool, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2, 3, 4, 5))
def verify_and_sample_paged_q(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # int8, donated
    v_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # f32 scales, donated
    vs_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    chunk: jnp.ndarray,
    start_len: jnp.ndarray,
    active: jnp.ndarray,
    kv_capacity: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray, jax.Array]:
    """int8-paged twin of :func:`verify_and_sample`."""
    logits, k_pool, v_pool, ks_pool, vs_pool = (
        llama.decode_chunk_paged_q.__wrapped__(
            cfg, params, chunk, k_pool, v_pool, ks_pool, vs_pool,
            block_tables, start_len, active, kv_capacity,
        )
    )
    out, n_accept, rng = _accept_and_bonus(
        chunk, logits, temperature, top_k, top_p, rng
    )
    return out, n_accept, k_pool, v_pool, ks_pool, vs_pool, rng


def pad_bucket(length: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket ≥ length (prompt padding, limits recompiles)."""
    for b in buckets:
        if length <= b:
            return b
    return buckets[-1]

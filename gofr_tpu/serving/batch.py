"""Jitted fixed-shape device functions for the continuous-batching engine.

All shapes are static (slot count, padded prompt buckets) so everything
compiles once per bucket and never again — the XLA contract. Slots are rows
of a persistent batch KV cache; requests come and go between steps by
scattering into / masking out rows, with buffers donated end-to-end so the
cache never copies.

Device-side state per engine:
- ``SlotCache``: k/v [L, B_slots, S_max, Hkv, Dh]
- ``DecodeState``: the per-row decode carry (last token, resident length,
  done flag, remaining token budget, stop id, sampling params, PRNG key) —
  donated through every block dispatch and every admission scatter, so the
  host never reads it and nothing aliases it

The decode hot loop is CPU-free (Blink, arXiv:2604.07609): sampling AND
stop-condition evaluation run inside the jitted N-step block
(``decode_block*``), which returns ONE packed int32 [B, steps+2] array —
``steps`` token columns (-1 past each row's stop), a done column, and an
n_valid column — so the engine's single host sync happens once per N
tokens instead of once per token.

This file is a shardcheck retrace zone (``make lint``): donated buffers
must be rebound at every call site (``use-after-donation``) and nothing
here may branch on traced values or take unhashable statics
(``retrace-hazard``) — one per-request recompile eats the whole TTFT
budget.

Every jitted entry here is ALSO under device contract: its parameter
tuple, donated/static sets, packed output layout, and carry signatures
are declared in ``gofr_tpu/analysis/kernel_contracts.KERNELS`` and
enforced by kernelcheck + the eval_shape runtime twin
(docs/static-analysis.md "kernelcheck — device-contract analysis").
Changing a signature, a pack column, or a ``DecodeState`` field means
updating the contract table in the same commit — the lint gate and the
tier-1 matrix both fail otherwise, by design.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from gofr_tpu.models import llama
from gofr_tpu.ops.sampling import sample_logits, stop_eval


@partial(jax.jit, static_argnums=0)
def prefill_compute(
    cfg: llama.LlamaConfig,
    params: dict,
    tokens: jnp.ndarray,  # [1, S_bucket] right-padded
    seq_len: jnp.ndarray,  # [1]
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Run prefill WITHOUT a persistent cache: returns (last_logits [1,V],
    k_slab, v_slab [L, S_bucket, Hkv, Dh]) for scatter into a slot."""
    scratch = llama.KVCache.create(cfg, 1, max_len=tokens.shape[1])
    last, cache = llama.prefill(cfg, params, tokens, scratch, seq_len)
    return last, cache.k[:, 0], cache.v[:, 0]


@partial(jax.jit, donate_argnums=(0, 1))
def insert_slot(
    k_cache: jnp.ndarray,  # [L, B, S_max, Hkv, Dh] donated
    v_cache: jnp.ndarray,
    k_slab: jnp.ndarray,  # [L, S_bucket, Hkv, Dh]
    v_slab: jnp.ndarray,
    slot: jnp.ndarray,  # scalar int32
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a prefilled slab into slot row [.., slot, :S_bucket]."""
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_slab[:, None], (0, slot, 0, 0, 0)
    )
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_slab[:, None], (0, slot, 0, 0, 0)
    )
    return k_cache, v_cache


@partial(jax.jit, donate_argnums=(0,))
def insert_slot_quantized(
    cache: llama.KVCache,  # int8 cache (donated)
    k_slab: jnp.ndarray,  # [L, S_bucket, Hkv, Dh] full-width prefill slab
    v_slab: jnp.ndarray,
    slot: jnp.ndarray,  # scalar int32
) -> llama.KVCache:
    """int8 twin of :func:`insert_slot`: quantize the full-width prefill
    slabs (per-vector absmax) and scatter payload + scales into the slot
    row of the quantized cache."""
    kq, kscale = llama.quantize_kv(k_slab)
    vq, vscale = llama.quantize_kv(v_slab)
    return llama.KVCache(
        jax.lax.dynamic_update_slice(cache.k, kq[:, None], (0, slot, 0, 0, 0)),
        jax.lax.dynamic_update_slice(cache.v, vq[:, None], (0, slot, 0, 0, 0)),
        jax.lax.dynamic_update_slice(cache.ks, kscale[:, None], (0, slot, 0, 0)),
        jax.lax.dynamic_update_slice(cache.vs, vscale[:, None], (0, slot, 0, 0)),
    )


# ------------------------------------------------------- CPU-free hot loop
@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class DecodeState:
    """The per-row decode carry: everything the device needs to run N
    steps without the host. Donated through every ``decode_block*``
    dispatch and every :func:`admit_decode_state` scatter — the host NEVER
    reads these buffers (results come back only through the packed block
    output), so the donation can never alias a host-held reference: the
    aliasing that produced the round-4 on-TPU crash ("Array has been
    deleted with shape=int32[32]") is impossible by construction here.

    ``budget`` is the number of tokens the row may still emit — the engine
    folds ``max_new_tokens`` AND the sequence-length cap into it at
    admission, so the device's stop evaluation covers both. ``stop_tok``
    is the row's EOS id (-1 disables). ``done`` rows are frozen: they stop
    spending budget and emit -1, and their garbage KV writes land where
    they cannot matter — the trash page on the paged layout; PAST the
    cache bound on dense (``.at[].set`` drops out-of-bounds writes).
    Position 0 was the old dense target, which became a corruption bug
    the moment rows could be frozen while still holding LIVE prompt KV
    (mid-chunked-prefill cursor rows).

    ``adapter`` is the row's LoRA device-table slot (serving/lora.py; 0 =
    base model). It rides the donated carry like ``stop_tok`` so the
    heterogeneous-adapter gather inside the block needs no per-step host
    traffic — the adapter index is admitted once and stays on device."""

    last_token: jnp.ndarray  # [B] int32
    seq_len: jnp.ndarray  # [B] int32 — tokens RESIDENT in KV (incl. prompt)
    done: jnp.ndarray  # [B] bool
    budget: jnp.ndarray  # [B] int32 — tokens the row may still emit
    stop_tok: jnp.ndarray  # [B] int32
    temperature: jnp.ndarray  # [B] f32
    top_k: jnp.ndarray  # [B] int32
    top_p: jnp.ndarray  # [B] f32
    rng: jax.Array
    adapter: jnp.ndarray = None  # [B] int32 — LoRA table slot (0 = base)

    def tree_flatten(self):
        return (
            self.last_token, self.seq_len, self.done, self.budget,
            self.stop_tok, self.temperature, self.top_k, self.top_p, self.rng,
            self.adapter,
        ), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)


def make_decode_state(
    last_token: Any, seq_len: Any, done: Any, budget: Any, stop_tok: Any,
    temperature: Any, top_k: Any, top_p: Any, rng: jax.Array,
    adapter: Any = None,
) -> DecodeState:
    """Upload a fresh device-resident DecodeState from host (numpy)
    mirrors — the cold path (engine start, post-failure rebuild). Steady
    state never re-uploads: admissions fold in via the donated scatter
    below, and everything else advances on device. ``adapter`` defaults
    to all-base (slot 0) for callers predating the LoRA plane."""
    import numpy as _np

    if adapter is None:
        adapter = _np.zeros(_np.asarray(last_token).shape[0], _np.int32)
    return DecodeState(
        jnp.asarray(last_token, jnp.int32),
        jnp.asarray(seq_len, jnp.int32),
        jnp.asarray(done, bool),
        jnp.asarray(budget, jnp.int32),
        jnp.asarray(stop_tok, jnp.int32),
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        jnp.asarray(top_p, jnp.float32),
        rng,
        jnp.asarray(adapter, jnp.int32),
    )


@partial(jax.jit, donate_argnums=(0,))
def admit_decode_state(
    state: DecodeState,  # donated: nothing aliases it (see DecodeState)
    slots: jnp.ndarray,  # [K] int32
    tokens: jnp.ndarray,  # [K] int32 — each slot's prefill-sampled token
    lens: jnp.ndarray,  # [K] int32 — resident prompt length
    budgets: jnp.ndarray,  # [K] int32
    stops: jnp.ndarray,  # [K] int32
    temps: jnp.ndarray,  # [K] f32
    topks: jnp.ndarray,  # [K] int32
    topps: jnp.ndarray,  # [K] f32
    adapters: jnp.ndarray,  # [K] int32 — LoRA table slots (0 = base)
) -> DecodeState:
    """Fold freshly-prefilled slots into the device-resident decode state
    in one fused scatter (un-done + new budget + sampling params + the
    per-row adapter index the block kernels gather with)."""
    return DecodeState(
        state.last_token.at[slots].set(tokens),
        state.seq_len.at[slots].set(lens),
        state.done.at[slots].set(False),
        state.budget.at[slots].set(budgets),
        state.stop_tok.at[slots].set(stops),
        state.temperature.at[slots].set(temps),
        state.top_k.at[slots].set(topks),
        state.top_p.at[slots].set(topps),
        state.rng,
        state.adapter.at[slots].set(adapters),
    )


def _pack_block(toks: jnp.ndarray, done: jnp.ndarray,
                active: jnp.ndarray) -> jnp.ndarray:
    """Pack a block's results into ONE int32 [B, steps+2] array — columns
    [0, steps) are the sampled tokens (-1 past each row's stop), column
    ``steps`` the done flag, column ``steps+1`` the per-row valid count —
    so the host pays exactly one device sync per block."""
    n_valid = jnp.sum(toks >= 0, axis=1, dtype=jnp.int32)
    return jnp.concatenate(
        [
            toks.astype(jnp.int32),
            (done & active)[:, None].astype(jnp.int32),
            n_valid[:, None],
        ],
        axis=1,
    )


def _lora_delta(
    embedding: jnp.ndarray,  # [V, D] — the model's token embedding table
    a_tab: jnp.ndarray,      # [n_adapters, D, r]
    b_tab: jnp.ndarray,      # [n_adapters, r, V]
    tokens: jnp.ndarray,     # [B] — the input tokens whose forward made logits
    adapter: jnp.ndarray,    # [B] int32 — per-row adapter table slot
) -> jnp.ndarray:
    """Grouped low-rank logits delta for a heterogeneous-adapter batch:
    a per-row ADAPTER-INDEX GATHER out of the stacked factor tables, then
    two batched low-rank matmuls — ``emb[t] @ A_i @ B_i`` per row. Slot 0
    is all-zero (base model), so mixed base/adapter batches need no mask.
    Pure device math inside the fused block: no host traffic, no syncs."""
    e = embedding[tokens].astype(jnp.float32)               # [B, D]
    h = jnp.einsum("bd,bdr->br", e, a_tab[adapter])         # [B, r]
    return jnp.einsum("br,brv->bv", h, b_tab[adapter])      # [B, V]


def _lora_logits(params: dict, lora, tokens, adapter, logits):
    """Apply the per-row adapter delta to a sampling site's logits.
    ``lora`` is ``(a_table, b_table)`` or None (base-only engines trace
    the exact pre-LoRA graph — the None path adds zero ops)."""
    if lora is None or adapter is None:
        return logits
    a_tab, b_tab = lora
    return logits + _lora_delta(
        params["embedding"], a_tab, b_tab, tokens, adapter
    )


@jax.jit
def lora_adjust_logits(
    embedding: jnp.ndarray,  # [V, D]
    a_row: jnp.ndarray,      # [D, r] — ONE adapter's factors
    b_row: jnp.ndarray,      # [r, V]
    token: jnp.ndarray,      # scalar int32 — the logits' input token
    logits: jnp.ndarray,     # [1, V]
) -> jnp.ndarray:
    """Single-row adapter delta for the HOST-path first-token sampling
    sites (monolithic prefill, full chunk-prefix-cache hits): the same
    math as :func:`_lora_delta`, applied to one row's last-position
    logits before ``sample_logits``. Pure device op — no sync."""
    e = embedding[token].astype(jnp.float32)
    h = e @ a_row.astype(jnp.float32)
    return logits + (h @ b_row.astype(jnp.float32))[None]


def _block_step(st: DecodeState, active, logits, params=None, lora=None):
    """Shared per-step tail of every decode_block* scan body: apply the
    per-row LoRA delta (heterogeneous-adapter batching, serving/lora.py),
    sample with the row's own params, evaluate stop conditions, advance
    the carry. Frozen (done/inactive) rows keep their token and length
    and emit -1."""
    live = active & ~st.done
    # the logits came from forwarding st.last_token — the delta is the
    # same token's low-rank bypass, gathered by the row's adapter slot
    logits = _lora_logits(params, lora, st.last_token, st.adapter, logits)
    rng, key = jax.random.split(st.rng)
    nxt = sample_logits(
        logits, key, temperature=st.temperature, top_k=st.top_k, top_p=st.top_p
    )
    nxt = jnp.where(live, nxt, st.last_token)
    done = st.done | (live & stop_eval(nxt, st.stop_tok, st.budget))
    new_st = DecodeState(
        nxt,
        jnp.where(live, st.seq_len + 1, st.seq_len),
        done,
        jnp.where(live, st.budget - 1, st.budget),
        st.stop_tok, st.temperature, st.top_k, st.top_p, rng,
        st.adapter,
    )
    return new_st, jnp.where(live, nxt, -1)


@partial(jax.jit, static_argnums=(0, 5), donate_argnums=(2, 3))
def decode_block(
    cfg: llama.LlamaConfig,
    params: dict,
    cache: llama.KVCache,  # donated (bf16 or int8 dense)
    state: DecodeState,  # donated
    active: jnp.ndarray,  # [B] bool — rows the host dispatched this block
    steps: int,
    lora: tuple | None = None,  # (a_table, b_table) — heterogeneous LoRA
) -> tuple[jnp.ndarray, llama.KVCache, DecodeState]:
    """``steps`` fused decode+sample+stop-eval iterations in ONE dispatch
    over the dense slot cache. A row that stops mid-block freezes: no
    further KV writes or budget spend, its remaining columns are -1.
    Frozen rows aim their scatter PAST the cache bound (``.at[].set``
    drops out-of-bounds writes) — position 0 would corrupt live prompt
    KV for a row that is frozen because it is still mid-chunked-prefill.
    ``lora`` (never donated) carries the stacked adapter factor tables;
    each step gathers per-row slots out of the carry's ``adapter`` index
    — one dispatch serves rows with DIFFERENT adapters (serving/lora.py).
    Returns (packed [B, steps+2] — see :func:`_pack_block` — cache,
    state); the packed array is the block's ONLY host-read value."""
    oob = cache.k.shape[2] + 1  # static: one past the slot's last position

    def step(carry, _):
        cache, st = carry
        live = active & ~st.done
        step_len = jnp.where(live, st.seq_len + 1, oob)
        logits, cache = llama.decode_step(
            cfg, params, st.last_token, cache, step_len
        )
        st, out = _block_step(st, active, logits, params, lora)
        return (cache, st), out

    (cache, state), toks = jax.lax.scan(
        step, (cache, state), None, length=steps
    )
    return _pack_block(jnp.transpose(toks), state.done, active), cache, state


@partial(jax.jit, static_argnums=(0, 7), donate_argnums=(2, 3, 4))
def decode_block_paged(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # [L, N_pages+1, Hkv, page, Dh] donated (+1: trash)
    v_pool: jnp.ndarray,  # donated
    state: DecodeState,  # donated
    block_tables: jnp.ndarray,  # [B, M] — covers the whole block's writes
    active: jnp.ndarray,  # [B] bool
    steps: int,
    lora: tuple | None = None,  # (a_table, b_table) — heterogeneous LoRA
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, DecodeState]:
    """Paged twin of :func:`decode_block`: frozen rows' appends divert to
    the trash page (llama.decode_step_paged's ``active`` redirect), so a
    mid-block stop never writes a live page."""

    def step(carry, _):
        kp, vp, st = carry
        live = active & ~st.done
        step_len = jnp.where(live, st.seq_len + 1, 1)
        logits, kp, vp = llama.decode_step_paged(
            cfg, params, st.last_token, kp, vp, block_tables, step_len, live
        )
        st, out = _block_step(st, active, logits, params, lora)
        return (kp, vp, st), out

    (k_pool, v_pool, state), toks = jax.lax.scan(
        step, (k_pool, v_pool, state), None, length=steps
    )
    packed = _pack_block(jnp.transpose(toks), state.done, active)
    return packed, k_pool, v_pool, state


@partial(jax.jit, static_argnums=(0, 9), donate_argnums=(2, 3, 4, 5, 6))
def decode_block_paged_q(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # int8, donated
    v_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # f32 scales, donated
    vs_pool: jnp.ndarray,
    state: DecodeState,  # donated
    block_tables: jnp.ndarray,
    active: jnp.ndarray,
    steps: int,
    lora: tuple | None = None,  # (a_table, b_table) — heterogeneous LoRA
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           DecodeState]:
    """int8 twin of :func:`decode_block_paged`."""

    def step(carry, _):
        kp, vp, ksp, vsp, st = carry
        live = active & ~st.done
        step_len = jnp.where(live, st.seq_len + 1, 1)
        logits, kp, vp, ksp, vsp = llama.decode_step_paged_q(
            cfg, params, st.last_token, kp, vp, ksp, vsp, block_tables,
            step_len, live,
        )
        st, out = _block_step(st, active, logits, params, lora)
        return (kp, vp, ksp, vsp, st), out

    (k_pool, v_pool, ks_pool, vs_pool, state), toks = jax.lax.scan(
        step, (k_pool, v_pool, ks_pool, vs_pool, state), None, length=steps
    )
    packed = _pack_block(jnp.transpose(toks), state.done, active)
    return packed, k_pool, v_pool, ks_pool, vs_pool, state


# ------------------------------------------------- unified ragged dispatch
#
# Continuous batching (Ragged Paged Attention, arXiv:2604.15464): one
# dispatch runs a ragged mix of PREFILL CHUNKS (the next <=C prompt tokens
# of each partially-prefilled row, written into the same slot cache / page
# pool decode reads) and an N-step DECODE BLOCK, returning ONE packed
# array so the host still pays exactly one sync per block. A row whose
# chunk completes its prompt gets its first token sampled ON DEVICE (with
# the same fold_in(root, request_id) key the host path uses) and is folded
# into the donated DecodeState in the same dispatch — admission to decode
# costs no extra host round trip.


def _fold_finished_prefill(
    st: DecodeState,
    logits_c: jnp.ndarray,   # [B, C, V] chunk-forward logits
    chunk: jnp.ndarray,      # [B, C] the chunk's input tokens
    chunk_start: jnp.ndarray,  # [B] resident length before the chunk
    finish: jnp.ndarray,     # [B] bool — this chunk completes the prompt
    new_len: jnp.ndarray,    # [B] resident length after the chunk
    budgets: jnp.ndarray,    # [B] tokens the row may emit AFTER the first
    stops: jnp.ndarray,      # [B] per-row stop id (-1 disables)
    temps: jnp.ndarray,
    topks: jnp.ndarray,
    topps: jnp.ndarray,
    rids: jnp.ndarray,       # [B] request ids (first-token RNG keys)
    rng_root: jax.Array,
    adapters: jnp.ndarray | None = None,  # [B] LoRA table slots
    params: dict | None = None,
    lora: tuple | None = None,
) -> tuple[DecodeState, jnp.ndarray, jnp.ndarray]:
    """Sample first tokens for rows whose prompt just finished prefilling
    and fold them into the decode carry (including each row's LoRA
    adapter slot, so the decode steps gather the right delta). Returns
    (state, first [B] — -1 on non-finishing rows — last_logits [B, V] at
    each row's final chunk position, for the chunk-prefix cache —
    BASE-model logits: the adapter delta applies at sampling sites, so
    cached entries stay adapter-independent while the adapter-id-scoped
    keys keep cross-adapter hits impossible anyway)."""
    C = logits_c.shape[1]
    pos = jnp.clip(new_len - chunk_start - 1, 0, C - 1)
    last_logits = jnp.take_along_axis(
        logits_c, pos[:, None, None], axis=1
    )[:, 0]  # [B, V]
    # the logits sampled from were produced by the chunk's last prompt
    # token — the same token keys the low-rank bypass delta
    last_tok = jnp.take_along_axis(chunk, pos[:, None], axis=1)[:, 0]
    if adapters is None:
        adapters = jnp.zeros_like(rids)
    sample_from = _lora_logits(params, lora, last_tok, adapters, last_logits)
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(rng_root, rids)

    def sample_one(lg, key, t, tk, tp):
        return sample_logits(
            lg[None], key, temperature=t, top_k=tk, top_p=tp
        )[0]

    sampled = jax.vmap(sample_one)(sample_from, keys, temps, topks, topps)
    done_f = (sampled == stops) | (budgets <= 0)
    st = DecodeState(
        jnp.where(finish, sampled, st.last_token),
        jnp.where(finish, new_len, st.seq_len),
        jnp.where(finish, done_f, st.done),
        jnp.where(finish, budgets, st.budget),
        jnp.where(finish, stops, st.stop_tok),
        jnp.where(finish, temps, st.temperature),
        jnp.where(finish, topks, st.top_k),
        jnp.where(finish, topps, st.top_p),
        st.rng,
        jnp.where(finish, adapters, st.adapter),
    )
    return st, jnp.where(finish, sampled, -1), last_logits


def _pack_ragged(toks: jnp.ndarray, done: jnp.ndarray, active: jnp.ndarray,
                 first: jnp.ndarray) -> jnp.ndarray:
    """:func:`_pack_block` plus one trailing column: the on-device-sampled
    first token of rows whose prefill finished this dispatch (-1
    elsewhere). Layout [B, steps+3]: tokens | done | n_valid | first."""
    return jnp.concatenate(
        [_pack_block(toks, done, active), first[:, None].astype(jnp.int32)],
        axis=1,
    )


@partial(jax.jit, static_argnums=(0, 16), donate_argnums=(2, 3))
def ragged_step(
    cfg: llama.LlamaConfig,
    params: dict,
    cache: llama.KVCache,      # donated (bf16 or int8 dense)
    state: DecodeState,        # donated
    chunk: jnp.ndarray,        # [B, C] next prompt tokens (pad past len)
    chunk_start: jnp.ndarray,  # [B] resident length before the chunk;
                               # NON-chunk rows pass max_seq_len so their
                               # writes fall out of bounds and are dropped
    finish: jnp.ndarray,       # [B] bool — chunk completes the prompt
    new_len: jnp.ndarray,      # [B] resident length after the chunk
    budgets: jnp.ndarray,      # [B] decode budget once admitted
    stops: jnp.ndarray,        # [B]
    temps: jnp.ndarray,        # [B]
    topks: jnp.ndarray,        # [B]
    topps: jnp.ndarray,        # [B]
    rids: jnp.ndarray,         # [B] request ids (first-token keys)
    rng_root: jax.Array,
    decode_active: jnp.ndarray,  # [B] bool — rows decoding THIS block
    steps: int,
    adapters: jnp.ndarray | None = None,  # [B] LoRA slots for chunk rows
    lora: tuple | None = None,  # (a_table, b_table) — never donated
) -> tuple[jnp.ndarray, jnp.ndarray, llama.KVCache, DecodeState]:
    """Unified ragged dispatch, dense cache: prefill-chunk forward for the
    chunk rows, first-token fold for finishing rows, then the N-step
    decode scan — one dispatch, one packed host read. Returns (packed
    [B, steps+3] — see :func:`_pack_ragged` — last_logits [B, V], cache,
    state); ``last_logits`` stays on device unless the engine retains it
    for the chunk-prefix cache."""
    logits_c, cache = llama.decode_chunk.__wrapped__(
        cfg, params, chunk, cache, chunk_start
    )
    state, first, last_logits = _fold_finished_prefill(
        state, logits_c, chunk, chunk_start, finish, new_len, budgets,
        stops, temps, topks, topps, rids, rng_root, adapters, params, lora,
    )
    # frozen rows include MID-PREFILL cursor rows whose low positions hold
    # live prompt KV: their scatter must drop out of bounds, never land on
    # position 0 (see decode_block)
    oob = cache.k.shape[2] + 1

    def step(carry, _):
        cache, st = carry
        live = decode_active & ~st.done
        step_len = jnp.where(live, st.seq_len + 1, oob)
        logits, cache = llama.decode_step(
            cfg, params, st.last_token, cache, step_len
        )
        st, out = _block_step(st, decode_active, logits, params, lora)
        return (cache, st), out

    (cache, state), toks = jax.lax.scan(
        step, (cache, state), None, length=steps
    )
    packed = _pack_ragged(
        jnp.transpose(toks), state.done, decode_active, first
    )
    return packed, last_logits, cache, state


@partial(jax.jit, static_argnums=(0, 20), donate_argnums=(2, 3, 4))
def ragged_step_paged(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,       # donated
    v_pool: jnp.ndarray,       # donated
    state: DecodeState,        # donated
    block_tables: jnp.ndarray,  # [B, M] — covers chunk AND block writes
    chunk: jnp.ndarray,        # [B, C]
    chunk_start: jnp.ndarray,  # [B]
    chunk_active: jnp.ndarray,  # [B] bool — rows prefill-chunking now
    kv_capacity: jnp.ndarray,  # [B] tokens covered by owned pages
    finish: jnp.ndarray,
    new_len: jnp.ndarray,
    budgets: jnp.ndarray,
    stops: jnp.ndarray,
    temps: jnp.ndarray,
    topks: jnp.ndarray,
    topps: jnp.ndarray,
    rids: jnp.ndarray,
    rng_root: jax.Array,
    decode_active: jnp.ndarray,
    steps: int,
    adapters: jnp.ndarray | None = None,  # [B] LoRA slots for chunk rows
    lora: tuple | None = None,  # (a_table, b_table) — never donated
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, DecodeState]:
    """Paged twin of :func:`ragged_step`: chunk writes route through the
    block tables (inactive rows and beyond-capacity positions divert to
    the trash page), decode appends likewise."""
    logits_c, k_pool, v_pool = llama.decode_chunk_paged.__wrapped__(
        cfg, params, chunk, k_pool, v_pool, block_tables, chunk_start,
        chunk_active, kv_capacity,
    )
    state, first, last_logits = _fold_finished_prefill(
        state, logits_c, chunk, chunk_start, finish, new_len, budgets,
        stops, temps, topks, topps, rids, rng_root, adapters, params, lora,
    )

    def step(carry, _):
        kp, vp, st = carry
        live = decode_active & ~st.done
        step_len = jnp.where(live, st.seq_len + 1, 1)
        logits, kp, vp = llama.decode_step_paged(
            cfg, params, st.last_token, kp, vp, block_tables, step_len, live
        )
        st, out = _block_step(st, decode_active, logits, params, lora)
        return (kp, vp, st), out

    (k_pool, v_pool, state), toks = jax.lax.scan(
        step, (k_pool, v_pool, state), None, length=steps
    )
    packed = _pack_ragged(
        jnp.transpose(toks), state.done, decode_active, first
    )
    return packed, last_logits, k_pool, v_pool, state


@partial(jax.jit, static_argnums=(0, 22), donate_argnums=(2, 3, 4, 5, 6))
def ragged_step_paged_q(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,       # int8, donated
    v_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,      # f32 scales, donated
    vs_pool: jnp.ndarray,
    state: DecodeState,        # donated
    block_tables: jnp.ndarray,
    chunk: jnp.ndarray,
    chunk_start: jnp.ndarray,
    chunk_active: jnp.ndarray,
    kv_capacity: jnp.ndarray,
    finish: jnp.ndarray,
    new_len: jnp.ndarray,
    budgets: jnp.ndarray,
    stops: jnp.ndarray,
    temps: jnp.ndarray,
    topks: jnp.ndarray,
    topps: jnp.ndarray,
    rids: jnp.ndarray,
    rng_root: jax.Array,
    decode_active: jnp.ndarray,
    steps: int,
    adapters: jnp.ndarray | None = None,  # [B] LoRA slots for chunk rows
    lora: tuple | None = None,  # (a_table, b_table) — never donated
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray, DecodeState]:
    """int8 twin of :func:`ragged_step_paged`."""
    logits_c, k_pool, v_pool, ks_pool, vs_pool = (
        llama.decode_chunk_paged_q.__wrapped__(
            cfg, params, chunk, k_pool, v_pool, ks_pool, vs_pool,
            block_tables, chunk_start, chunk_active, kv_capacity,
        )
    )
    state, first, last_logits = _fold_finished_prefill(
        state, logits_c, chunk, chunk_start, finish, new_len, budgets,
        stops, temps, topks, topps, rids, rng_root, adapters, params, lora,
    )

    def step(carry, _):
        kp, vp, ksp, vsp, st = carry
        live = decode_active & ~st.done
        step_len = jnp.where(live, st.seq_len + 1, 1)
        logits, kp, vp, ksp, vsp = llama.decode_step_paged_q(
            cfg, params, st.last_token, kp, vp, ksp, vsp, block_tables,
            step_len, live,
        )
        st, out = _block_step(st, decode_active, logits, params, lora)
        return (kp, vp, ksp, vsp, st), out

    (k_pool, v_pool, ks_pool, vs_pool, state), toks = jax.lax.scan(
        step, (k_pool, v_pool, ks_pool, vs_pool, state), None, length=steps
    )
    packed = _pack_ragged(
        jnp.transpose(toks), state.done, decode_active, first
    )
    return packed, last_logits, k_pool, v_pool, ks_pool, vs_pool, state


@partial(jax.jit, donate_argnums=(0, 1))
def insert_chunk(
    k_cache: jnp.ndarray,  # [L, B, S_max, Hkv, Dh] donated
    v_cache: jnp.ndarray,
    k_slab: jnp.ndarray,  # [L, C, Hkv, Dh] cached chunk-prefix slab
    v_slab: jnp.ndarray,
    slot: jnp.ndarray,  # scalar int32
    start: jnp.ndarray,  # scalar int32 — token offset of the slab
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter a cached chunk-prefix slab into slot row
    [.., slot, start:start+C] — :func:`insert_slot`'s offset twin, used
    when a chunked admission skips already-cached chunk prefixes."""
    k_cache = jax.lax.dynamic_update_slice(
        k_cache, k_slab[:, None], (0, slot, start, 0, 0)
    )
    v_cache = jax.lax.dynamic_update_slice(
        v_cache, v_slab[:, None], (0, slot, start, 0, 0)
    )
    return k_cache, v_cache


# ----------------------------------------------------- speculative decoding
def _accept_and_bonus(
    chunk: jnp.ndarray,  # [B, T] (pos 0 = last committed; 1.. = drafts, -1 pad)
    logits: jnp.ndarray,  # [B, T, V] from a chunk verify forward
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jax.Array]:
    """Greedy draft acceptance + per-row bonus sampling, fused device-side.
    The verify_and_sample* wrappers pack (tokens, n_accept) into ONE
    [B, T+1] int32 array so the engine's spec path pays a single host
    sync per chunk (tokens in columns [0, T), n_accept in column T).

    Position i's logits predict the token after chunk token i, so draft
    chunk[:, i+1] is accepted iff argmax(logits[:, i]) equals it AND every
    earlier draft was accepted (cumulative product). -1 padding never
    matches, so per-row draft counts need no separate length input. The
    bonus token samples from logits at the first rejected position with
    the row's own sampling params — rows the engine didn't draft for
    (temperature > 0) therefore take exactly a normal sampled step.
    Returns (tokens [B, T] — accepted drafts then bonus, -1 beyond —
    n_accept [B], rng)."""
    B, T = chunk.shape
    greedy = jnp.argmax(logits, axis=-1)  # [B, T]
    drafts = chunk[:, 1:]  # [B, T-1]
    match = (greedy[:, :-1] == drafts) & (drafts >= 0)
    n_accept = jnp.sum(jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    bonus_logits = jnp.take_along_axis(
        logits, n_accept[:, None, None], axis=1
    )[:, 0]  # [B, V]
    rng, key = jax.random.split(rng)
    bonus = sample_logits(
        bonus_logits, key, temperature=temperature, top_k=top_k, top_p=top_p
    )
    idx = jnp.arange(T)[None, :]
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((B, 1), drafts.dtype)], axis=1
    )
    out = jnp.where(
        idx < n_accept[:, None], drafts_pad,
        jnp.where(idx == n_accept[:, None], bonus[:, None], -1),
    )
    return out, n_accept, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2,))
def verify_and_sample(
    cfg: llama.LlamaConfig,
    params: dict,
    cache: llama.KVCache,  # donated (bf16 or int8 dense)
    chunk: jnp.ndarray,  # [B, T]
    start_len: jnp.ndarray,  # [B] committed length before the chunk
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, llama.KVCache, jax.Array]:
    """Speculative engine step, dense cache: chunk-verify forward + draft
    acceptance + bonus sampling in ONE dispatch. Returns
    (tokens [B, T], n_accept [B], cache, rng)."""
    logits, cache = llama.decode_chunk.__wrapped__(
        cfg, params, chunk, cache, start_len
    )
    out, n_accept, rng = _accept_and_bonus(
        chunk, logits, temperature, top_k, top_p, rng
    )
    packed = jnp.concatenate(
        [out.astype(jnp.int32), n_accept[:, None].astype(jnp.int32)], axis=1
    )
    return packed, cache, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2, 3))
def verify_and_sample_paged(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # donated
    v_pool: jnp.ndarray,  # donated
    block_tables: jnp.ndarray,
    chunk: jnp.ndarray,
    start_len: jnp.ndarray,
    active: jnp.ndarray,
    kv_capacity: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jax.Array]:
    """Paged twin of :func:`verify_and_sample`."""
    logits, k_pool, v_pool = llama.decode_chunk_paged.__wrapped__(
        cfg, params, chunk, k_pool, v_pool, block_tables, start_len,
        active, kv_capacity,
    )
    out, n_accept, rng = _accept_and_bonus(
        chunk, logits, temperature, top_k, top_p, rng
    )
    packed = jnp.concatenate(
        [out.astype(jnp.int32), n_accept[:, None].astype(jnp.int32)], axis=1
    )
    return packed, k_pool, v_pool, rng


@partial(jax.jit, static_argnums=0, donate_argnums=(2, 3, 4, 5))
def verify_and_sample_paged_q(
    cfg: llama.LlamaConfig,
    params: dict,
    k_pool: jnp.ndarray,  # int8, donated
    v_pool: jnp.ndarray,
    ks_pool: jnp.ndarray,  # f32 scales, donated
    vs_pool: jnp.ndarray,
    block_tables: jnp.ndarray,
    chunk: jnp.ndarray,
    start_len: jnp.ndarray,
    active: jnp.ndarray,
    kv_capacity: jnp.ndarray,
    temperature: jnp.ndarray,
    top_k: jnp.ndarray,
    top_p: jnp.ndarray,
    rng: jax.Array,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray,
           jnp.ndarray, jax.Array]:
    """int8-paged twin of :func:`verify_and_sample`."""
    logits, k_pool, v_pool, ks_pool, vs_pool = (
        llama.decode_chunk_paged_q.__wrapped__(
            cfg, params, chunk, k_pool, v_pool, ks_pool, vs_pool,
            block_tables, start_len, active, kv_capacity,
        )
    )
    out, n_accept, rng = _accept_and_bonus(
        chunk, logits, temperature, top_k, top_p, rng
    )
    packed = jnp.concatenate(
        [out.astype(jnp.int32), n_accept[:, None].astype(jnp.int32)], axis=1
    )
    return packed, k_pool, v_pool, ks_pool, vs_pool, rng


def pad_bucket(length: int, buckets: tuple[int, ...]) -> int:
    """Smallest bucket ≥ length (prompt padding, limits recompiles)."""
    for b in buckets:
        if length <= b:
            return b
    return buckets[-1]

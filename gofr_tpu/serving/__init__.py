"""The serving engine: continuous batching between transports and the TPU.

SURVEY §7 phase 4 — the TPU-native replacement for the reference's
per-request goroutine model (§3.2): requests share compiled batch steps, so
the unit of concurrency is the *slot*, not the thread. Components:

- engine.py: the ServingEngine — admission queue, slot allocation, prefill/
  decode interleave, per-token streaming, cancellation, metrics.
- stepplan.py: the continuous-batching step planner — per-iteration token
  budgets (decode reserved first), chunk cursors for long prompts
  (docs/performance.md "Continuous batching").
- batch.py: jitted fixed-shape device functions (slot prefill insert,
  batched decode+sample step, the unified ragged prefill-chunk + decode
  dispatch).
- tokenizer.py: tokenizer boundary (pluggable; byte-level default so the
  stack runs with zero external assets).
- handlers.py: ready-made HTTP handlers (/generate JSON + SSE stream,
  /embed) that plug the engine into the App router.
- router.py / membership.py: the multi-replica router tier — pubsub
  heartbeat membership, prefix-affinity routing with failover, hedged
  prefill admission (docs/robustness.md "The router plane").
- kv_spill.py / prefix_index.py: the cluster-wide KV reuse tiers —
  host-RAM spill pool under the device prefix cache, heartbeat-gossiped
  distributed prefix index, warm KV page migration between replicas
  (docs/performance.md "KV reuse tiers").
- remote.py / autoscaler.py: the disaggregation plane — the remote
  token-stream transport (SSE over /generate/stream + the cancel wire)
  and the headroom-driven per-role replica autoscaler with its
  simulated pool driver (docs/robustness.md "The disaggregation
  plane").
- timeline.py / device_telemetry.py: the observability layer — per-request
  lifecycle timelines behind /requestz, and the TPU HBM / duty-cycle
  poller feeding health, metrics and membership heartbeats
  (docs/observability.md).
- lora.py / tenancy.py: the multi-tenant plane — the LoRA adapter
  registry (paged adapter weights, heterogeneous-adapter batched
  decode) and per-tenant SLO classes (priority/deadline classes,
  token-rate budgets, the preemption ladder — docs/serving.md
  "Multi-tenancy").
"""

from gofr_tpu.serving.device_telemetry import DeviceTelemetry
from gofr_tpu.serving.engine import EngineConfig, GenerationResult, ServingEngine
from gofr_tpu.serving.membership import (
    Heartbeat,
    MembershipTable,
    ReplicaAnnouncer,
)
from gofr_tpu.serving.router import (
    HTTPReplica,
    LocalReplica,
    Router,
    RouterConfig,
)
from gofr_tpu.serving.kv_spill import HostSpillTier, TieredPrefixCache
from gofr_tpu.serving.prefix_index import (
    KVMigrator,
    PrefixIndex,
    local_engine_fetcher,
    local_engine_store,
)
from gofr_tpu.serving.autoscaler import (
    Autoscaler,
    AutoscalerConfig,
    SimulatedPoolDriver,
)
from gofr_tpu.serving.supervisor import EngineSupervisor
from gofr_tpu.serving.timeline import (RequestTimeline, TimelineExporter,
                                       TimelineRecorder)
from gofr_tpu.serving.tokenizer import ByteTokenizer, Tokenizer
from gofr_tpu.serving.lora import AdapterRegistry, LoraAdapter
from gofr_tpu.serving.tenancy import TenantPolicy, TenantRegistry

__all__ = [
    "ServingEngine",
    "EngineSupervisor",
    "EngineConfig",
    "GenerationResult",
    "Tokenizer",
    "ByteTokenizer",
    "Router",
    "RouterConfig",
    "LocalReplica",
    "HTTPReplica",
    "MembershipTable",
    "ReplicaAnnouncer",
    "Heartbeat",
    "TimelineRecorder",
    "TimelineExporter",
    "RequestTimeline",
    "DeviceTelemetry",
    "TieredPrefixCache",
    "HostSpillTier",
    "PrefixIndex",
    "KVMigrator",
    "local_engine_fetcher",
    "local_engine_store",
    "Autoscaler",
    "AutoscalerConfig",
    "SimulatedPoolDriver",
    "AdapterRegistry",
    "LoraAdapter",
    "TenantRegistry",
    "TenantPolicy",
]

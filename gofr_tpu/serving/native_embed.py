"""BERT /embed through the native PJRT runtime (SURVEY §2.9 native row).

The r4 verdict called the PJRT binding "a validation rig, not a runtime":
this module promotes it onto a real serving path. The BERT embedder is
lowered once to StableHLO, compiled by the NATIVE C runtime
(native/pjrt/pjrt_dl.cc → PJRT_Client_Compile on the loaded plugin), and
every request executes through PJRT_LoadedExecutable_Execute with no JAX
in the loop — weights live inside the compiled module as constants.

Enabled by ``TPU_NATIVE_PJRT=1`` (+ optional ``TPU_PJRT_PLUGIN`` path).
CI runs against the in-repo stub plugin, whose execute is the
deterministic ``y = 2x`` — that proves the full buffer→compile→execute→
buffer path without hardware; under a real libtpu plugin the same MLIR
yields real embeddings.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class NativePjrtEmbedder:
    """Owns a native PJRT client + compiled embed executable."""

    def __init__(
        self,
        cfg: Any,
        params: Any,
        *,
        plugin_path: str | None = None,
        seq_len: int | None = None,
    ) -> None:
        import jax
        import jax.numpy as jnp

        from gofr_tpu.models import bert as bert_model
        from gofr_tpu.native.pjrt import PjrtPlugin

        self.cfg = cfg
        # full model sequence budget by default: the native path must
        # embed exactly what the JAX path would (same truncation point),
        # or identical requests return different vectors per backend
        self.seq_len = int(seq_len or cfg.max_seq_len)

        def embed_one(tokens_f32: Any) -> Any:
            # f32 in/out is the binding's buffer contract; -1 marks padding
            toks = tokens_f32.astype(jnp.int32)[None, :]
            lens = jnp.sum((toks >= 0).astype(jnp.int32), axis=1)
            emb = bert_model.embed(
                cfg, params, jnp.maximum(toks, 0), jnp.maximum(lens, 1)
            )
            return emb[0].astype(jnp.float32)

        lowered = jax.jit(embed_one).lower(
            jax.ShapeDtypeStruct((self.seq_len,), jnp.float32)
        )
        module = lowered.compiler_ir(dialect="stablehlo")
        try:
            # MLIR bytecode keeps the weight constants binary (4 B/f32);
            # the textual form hex-prints every tensor — multi-GB strings
            # at bert-base scale
            mlir_bytes = module.operation.get_asm(binary=True)
        except Exception:
            mlir_bytes = str(module).encode()
        self.plugin = PjrtPlugin.load(plugin_path)
        self.client = self.plugin.create_client()
        self.executable = self.client.compile(mlir_bytes, "mlir")
        self.platform = self.client.platform_name

    def embed_tokens(self, token_ids: list[int]) -> list[float]:
        """One sequence → one embedding vector, through the native
        executable. Pads/truncates to the compiled static shape."""
        row = list(token_ids[: self.seq_len])
        row += [-1] * (self.seq_len - len(row))
        return self.executable.execute_f32(
            [float(t) for t in row], out_cap=max(self.cfg.d_model * 4, 1 << 12)
        )

    def embed_texts(self, tokenizer: Any, texts: list[str]) -> tuple[np.ndarray, int]:
        """Returns (embeddings [N, D], total tokens EMBEDDED) — the count
        reflects the compiled truncation point so usage never claims
        tokens the executable didn't see."""
        rows = []
        n_tokens = 0
        for t in texts:
            ids = tokenizer.encode(t)[: self.seq_len]
            n_tokens += len(ids)
            rows.append(self.embed_tokens(ids))
        return np.asarray(rows, np.float32), n_tokens

    def close(self) -> None:
        try:
            self.executable.destroy()
        finally:
            self.client.close()


def maybe_native_embedder(cfg: Any, params: Any, config: Any,
                          logger: Any = None) -> NativePjrtEmbedder | None:
    """Build the native path when TPU_NATIVE_PJRT=1; fall back to the JAX
    path (returning None) on any failure — serving availability beats the
    native fast path."""
    if config is None or config.get_or_default("TPU_NATIVE_PJRT", "0") != "1":
        return None
    plugin_path = config.get("TPU_PJRT_PLUGIN") or None
    try:
        emb = NativePjrtEmbedder(cfg, params, plugin_path=plugin_path)
        if logger:
            logger.info(
                f"native PJRT embed path active (platform={emb.platform})"
            )
        return emb
    except Exception as exc:  # noqa: BLE001 - degraded, not down
        if logger:
            logger.error(f"native PJRT embed path unavailable: {exc}")
        return None

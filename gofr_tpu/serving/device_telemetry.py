"""TPU device-state telemetry: HBM occupancy + engine duty cycle.

The serving data plane is only as observable as its device is
(ROADMAP north star: "TPU device/HBM state surfaced through the existing
health/metrics/tracing middleware"). This poller samples, on its own
daemon thread and NEVER on the engine thread:

- **per-device HBM** via ``device.memory_stats()`` (PJRT exposes
  ``bytes_in_use`` / ``bytes_limit`` on TPU; backends without stats —
  CPU — simply report no devices), exported as ``app_tpu_hbm_bytes``
  (labels ``device``, ``kind=used|limit``) and ``app_tpu_hbm_util``;
- **engine duty cycle** from the loop thread's cumulative busy counter
  (``ServingEngine.busy_seconds()``, stamped beside the heartbeat):
  Δbusy/Δwall over the poll interval, exported as
  ``app_engine_duty_cycle``.

The sample is embedded in ``engine.health_check()`` (``details.device``)
and the membership announcer reads :meth:`hbm_headroom` into the
heartbeat's ``hbm_free_frac`` — so the router's spill policy reacts to
real HBM pressure (serving/router.py ``spill_hbm_frac``).

Reading ``memory_stats()`` is a host-side PJRT query — allocator
counters, not a device computation: it forces no sync with in-flight
dispatches, so polling cannot perturb the CPU-free decode loop.
"""

from __future__ import annotations

import threading
import time
from typing import Any


class DeviceTelemetry:
    """Background device-state poller. ``start()`` spawns the daemon
    thread; ``sample()`` is also callable inline (tests, one-shot
    health probes)."""

    def __init__(
        self,
        engine: Any = None,
        *,
        metrics: Any = None,
        logger: Any = None,
        interval_s: float = 5.0,
    ) -> None:
        self.engine = engine
        self._metrics = metrics
        self._logger = logger
        self.interval_s = max(0.1, float(interval_s))
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._mu = threading.Lock()
        self._last: dict[str, Any] = {}
        # duty-cycle window: (busy_seconds, monotonic) at the last poll
        self._duty_mark: tuple[float, float] | None = None
        if engine is not None:
            # health_check embeds last_sample(); the announcer finds the
            # poller for its heartbeat headroom through this backref
            engine.device_telemetry = self

    # -- sampling --------------------------------------------------------------
    @staticmethod
    def _device_stats() -> list[dict[str, Any]]:
        try:
            import jax

            devices = jax.local_devices()
        except Exception:
            return []
        out: list[dict[str, Any]] = []
        for dev in devices:
            entry: dict[str, Any] = {
                "id": int(getattr(dev, "id", len(out))),
                "platform": str(getattr(dev, "platform", "unknown")),
            }
            try:
                stats = dev.memory_stats() or {}
            except Exception:
                stats = {}
            used = stats.get("bytes_in_use")
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
            if used is not None:
                entry["hbm_used_bytes"] = int(used)
            if limit:
                entry["hbm_limit_bytes"] = int(limit)
                if used is not None:
                    entry["hbm_util"] = round(int(used) / int(limit), 4)
            out.append(entry)
        return out

    def _duty_cycle(self, now: float) -> float | None:
        engine = self.engine
        if engine is None or not hasattr(engine, "busy_seconds"):
            return None
        busy = float(engine.busy_seconds())
        mark = self._duty_mark
        self._duty_mark = (busy, now)
        if mark is None:
            return None  # first poll: no window yet
        busy0, t0 = mark
        wall = now - t0
        if wall <= 0:
            return None
        return max(0.0, min(1.0, (busy - busy0) / wall))

    def sample(self) -> dict[str, Any]:
        """Take one sample, export the gauges, cache it for health."""
        now = time.monotonic()
        devices = self._device_stats()
        duty = self._duty_cycle(now)
        out: dict[str, Any] = {"devices": devices, "sampled_unix": time.time()}
        if duty is not None:
            out["engine_duty_cycle"] = round(duty, 4)
        hbm = self._headroom_of(devices)
        if hbm is not None:
            out["hbm_free_frac"] = round(hbm, 4)
        if self._metrics is not None:
            for dev in devices:
                dev_label = str(dev["id"])
                used = dev.get("hbm_used_bytes")
                limit = dev.get("hbm_limit_bytes")
                if used is not None:
                    self._metrics.set_gauge(
                        "app_tpu_hbm_bytes", used,
                        device=dev_label, kind="used",
                    )
                    self._metrics.set_gauge(
                        "app_tpu_hbm_used_bytes", used, device=dev_label,
                    )
                if limit is not None:
                    self._metrics.set_gauge(
                        "app_tpu_hbm_bytes", limit,
                        device=dev_label, kind="limit",
                    )
                    self._metrics.set_gauge(
                        "app_tpu_hbm_limit_bytes", limit, device=dev_label,
                    )
                if dev.get("hbm_util") is not None:
                    self._metrics.set_gauge(
                        "app_tpu_hbm_util", dev["hbm_util"], device=dev_label,
                    )
            if duty is not None:
                # ONLY app_engine_duty_cycle: app_tpu_duty_cycle belongs
                # to TPUClient's execute-duty window (datasource/tpu) —
                # two writers with different semantics would make the
                # unlabeled series flap between meanings
                self._metrics.set_gauge("app_engine_duty_cycle", duty)
        with self._mu:
            self._last = out
        return out

    @staticmethod
    def _headroom_of(devices: list[dict[str, Any]]) -> float | None:
        """The tightest device's free-HBM fraction — what the membership
        heartbeat publishes as ``hbm_free_frac``."""
        fracs = [
            1.0 - dev["hbm_util"]
            for dev in devices
            if dev.get("hbm_util") is not None
        ]
        return min(fracs) if fracs else None

    # -- consumers -------------------------------------------------------------
    def last_sample(self) -> dict[str, Any]:
        with self._mu:
            return dict(self._last)

    def hbm_headroom(self) -> float | None:
        """Free-HBM fraction of the tightest local device, from the last
        poll (never samples inline — the announcer calls this per beat)."""
        with self._mu:
            return self._last.get("hbm_free_frac")

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self.sample()  # prime: health/heartbeats see data before interval 1
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="device-telemetry",
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample()
            except Exception as exc:
                if self._logger is not None:
                    self._logger.debug(f"device telemetry poll failed: {exc}")

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
        self._thread = None

"""Async ASR worker: Pub/Sub-fed Whisper transcription.

SURVEY §3.4 / BASELINE.json configs[3]: the subscriber loop is the async
inference blueprint — jobs arrive on a broker topic, the handler binds the
audio payload, runs the jitted transcription, and publishes the result to a
reply topic with commit-on-success (at-least-once).

Job message (JSON): ``{"id": ..., "audio": [f32 samples] | "audio_b64":
base64 f32le, "sample_rate": 16000, "reply_topic": "asr-results"}``.
"""

from __future__ import annotations

import base64
import json
from typing import Any

import jax.numpy as jnp
import numpy as np

from gofr_tpu.models import whisper
from gofr_tpu.ops.audio import log_mel_spectrogram


class ASRWorker:
    def __init__(
        self,
        cfg: whisper.WhisperConfig,
        params: dict,
        tokenizer: Any = None,
        reply_topic: str = "asr-results",
        n_fft: int = 400,
        hop: int = 160,
    ) -> None:
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.reply_topic = reply_topic
        self.n_fft = n_fft
        self.hop = hop

    def decode_audio(self, job: dict) -> np.ndarray:
        if "audio_b64" in job:
            raw = base64.b64decode(job["audio_b64"])
            return np.frombuffer(raw, np.float32)
        return np.asarray(job.get("audio", []), np.float32)

    def transcribe_job(self, job: dict) -> dict:
        audio = self.decode_audio(job)
        if audio.size == 0:
            return {"id": job.get("id"), "error": "empty audio"}
        mel = log_mel_spectrogram(
            jnp.asarray(audio[None, :]),
            n_fft=self.n_fft, hop=self.hop, n_mels=self.cfg.n_mels,
        )
        ids = whisper.transcribe(self.cfg, self.params, mel, job.get("max_tokens"))[0]
        text = self.tokenizer.decode(ids) if self.tokenizer is not None else None
        return {"id": job.get("id"), "token_ids": ids, "text": text}

    async def handler(self, ctx: Any) -> Any:
        """The subscription handler: ``app.subscribe("asr-jobs",
        worker.handler)``. Transcription runs in the executor so the jitted
        decode (and its first compile) never stalls the event loop; the
        result is published to the job's reply topic."""
        import asyncio

        job = ctx.bind(dict)
        loop = asyncio.get_running_loop()
        result = await loop.run_in_executor(None, self.transcribe_job, job)
        publisher = ctx.get_publisher()
        if publisher is not None:
            topic = job.get("reply_topic", self.reply_topic)
            publisher.publish(topic, json.dumps(result).encode())
        return result

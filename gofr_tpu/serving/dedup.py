"""Exactly-once client retries: idempotency-keyed dedup + resumable streams.

HA-plane addition (docs/robustness.md "The HA plane"). Two pieces, both
replica-side — the engine, not router memory, is the authority, which is
what lets two routers share one replica fleet without double-serving:

``ReplayStream``
    A bounded per-request ring of emitted frames, fed from the engine's
    single detok worker through ``wrap()`` so every frame carries a dense
    sequence number (tokens ``1..N``, terminal ``N+1``) in emission
    order. ``attach(last_seq, sub)`` replays the suffix the caller has
    not acked and subscribes it for the live continuation under one
    lock, so a re-attaching client can neither miss nor double-receive a
    frame. The ring is bounded (``TPU_STREAM_REPLAY_TOKENS``); a caller
    whose ``last_seq`` fell behind the window gets ``ReplayGap`` — a
    token-identical resume is impossible and the engine reports a typed
    error instead of silently re-generating (the PR 7 rule: a request
    that streamed tokens is NEVER re-run). A submit-path duplicate that
    hits the gap instead ``subscribe()``s live (truncated stream, full
    result via the shared future) — a keyed retry must dedup safely,
    never hard-error. ``subscribers`` counts every live attachment
    (owner + duplicates + resumes); the orphan-grace reaper cancels
    only when it reaches zero.

``DedupRegistry``
    A bounded, thread-safe ``Idempotency-Key -> entry`` map. Live
    entries are bounded by in-flight requests; terminal entries are an
    LRU capped by ``TPU_IDEM_CAPACITY``. Only *successful* terminals are
    retained for replay — an exception terminal forgets the key so a
    genuine client retry re-runs cleanly. ``claim()`` is the atomic
    check-and-register: exactly one concurrent submit per key becomes
    the owner and dispatches; every other becomes a duplicate and
    attaches to the owner's future, which is how ``terminal_marks == 1``
    holds structurally across duplicates (duplicates never create a
    ``_Request``, never touch the scheduler, never reach
    ``_try_resolve``).
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable

# (seq, token_id, piece, done) — the resumable-wire frame shape.
FrameSub = Callable[[int, int, str, bool], None]

DEFAULT_REPLAY_TOKENS = 512
DEFAULT_KEY_CAPACITY = 1024


class ReplayGap(Exception):
    """The frames between the caller's ``last_seq`` and the ring's oldest
    retained frame were evicted by the bound: the acked-but-unseen suffix
    cannot be replayed token-identically."""


class ReplayStream:
    """Bounded, seq-numbered ring of a request's emitted frames.

    Fed from exactly one thread (the engine's single-worker detok
    executor preserves per-request frame order); read from any thread
    via ``attach``. Terminal frames are idempotent: the engine can fire
    the done frame from more than one settlement path, but only the
    first consumes a sequence number.
    """

    def __init__(self, capacity: int = DEFAULT_REPLAY_TOKENS) -> None:
        self._mu = threading.Lock()
        self._frames: collections.deque[tuple[int, int, str]] = collections.deque(
            maxlen=max(int(capacity), 1)
        )
        self._next_seq = 1
        self._done = False
        self._done_seq: int | None = None
        self._subs: list[FrameSub] = []
        self.attaches = 0  # re-attach generation counter (orphan-grace reaper reads it)
        # live attached connections: the owner plus every duplicate/resume
        # attachment. Incremented on wrap/attach/subscribe, decremented by
        # ``release()`` when a transport reports its client gone — the
        # orphan-grace reaper must not cancel while ANY of them remains
        # (one client's disconnect must never kill another client's
        # in-flight generation). Drift can only be upward (an attachment
        # that completes normally never releases), which errs toward
        # not canceling.
        self.subscribers = 0
        # the authoritative record of what the stream actually emitted:
        # every token frame's piece, in seq order (seq i+1 -> pieces[i]).
        # Bounded by the request's own max_new_tokens — the same order of
        # memory as the final text the terminal LRU already retains — so
        # a terminal replay is TEXT-identical to the original stream, not
        # merely token-identical.
        self.pieces: list[str] = []

    def wrap(self, cb: Callable[[int, str, bool], None] | None) -> Callable[[int, str, bool], None]:
        """Return a 3-arg ``stream_cb`` that stamps, stores, and fans out.

        Installed as the request's ``stream_cb`` so ALL engine emission
        paths (detok token frames and every done-frame settlement path)
        flow through the ring; the original client callback, when given,
        still sees the plain ``(token_id, piece, done)`` wire. The owner
        counts as one live subscriber from here (streaming or awaiting
        the future) until its transport ``release()``s it.
        """
        with self._mu:
            self.subscribers += 1

        def fanout(token_id: int, piece: str, done: bool) -> None:
            with self._mu:
                if done:
                    if self._done:  # second settlement path; frame already recorded
                        return
                    self._done = True
                    self._done_seq = self._next_seq
                    seq = self._next_seq
                else:
                    seq = self._next_seq
                    self._frames.append((seq, token_id, piece))
                    self.pieces.append(piece)
                self._next_seq += 1
                subs = list(self._subs)
            for sub in subs:
                try:
                    sub(seq, token_id, piece, done)
                except Exception:  # noqa: BLE001 - a dead subscriber must not hurt the stream
                    pass
            if cb is not None:
                cb(token_id, piece, done)

        return fanout

    def attach(self, last_seq: int, sub: FrameSub) -> None:
        """Replay frames with ``seq > last_seq``, then subscribe live.

        Replay and subscription happen under the ring lock, so no frame
        emitted concurrently can be missed or delivered twice. Raises
        ``ReplayGap`` when the suffix was evicted (or ``last_seq`` claims
        frames this stream never emitted).
        """
        last_seq = int(last_seq)
        with self._mu:
            if last_seq >= self._next_seq:
                raise ReplayGap(
                    f"last_seq {last_seq} is ahead of the stream (next seq {self._next_seq})"
                )
            oldest = self._frames[0][0] if self._frames else self._next_seq
            if last_seq + 1 < oldest and not (
                self._done and self._done_seq is not None and self._done_seq <= last_seq + 1
            ):
                raise ReplayGap(
                    f"frames {last_seq + 1}..{oldest - 1} were evicted from the replay window"
                )
            self.attaches += 1
            self.subscribers += 1
            for seq, token_id, piece in self._frames:
                if seq > last_seq:
                    sub(seq, token_id, piece, False)
            if self._done:
                if self._done_seq is not None and self._done_seq > last_seq:
                    sub(self._done_seq, -1, "", True)
            else:
                self._subs.append(sub)

    def subscribe(self, sub: FrameSub) -> int:
        """Attach live with NO replay: the subscriber accepts a truncated
        stream starting at the next emitted frame.

        The submit-path fallback for a duplicate whose suffix fell out of
        the bounded window (a token-identical replay is impossible, but
        the keyed-submit contract is "dedup safely", never a hard error):
        the caller's future still resolves with the FULL result; only the
        stream is truncated. Returns the seq BEFORE the first frame the
        subscriber will receive, so transports can stamp true ``id:``
        lines. A finished stream delivers just its terminal frame.
        """
        with self._mu:
            self.attaches += 1
            self.subscribers += 1
            if self._done:
                done_seq = self._done_seq if self._done_seq is not None else self._next_seq
                sub(done_seq, -1, "", True)
                return done_seq - 1
            self._subs.append(sub)
            return self._next_seq - 1

    def release(self) -> int:
        """One attached transport's client is gone (disconnect → orphan):
        drop its live-subscriber count. Returns the remaining count the
        orphan-grace reaper gates on. Floored at zero — an unbalanced
        release must not go negative and steal another client's slot."""
        with self._mu:
            self.subscribers = max(0, self.subscribers - 1)
            return self.subscribers

    def detach(self, sub: FrameSub) -> None:
        with self._mu:
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    @property
    def last_seq(self) -> int:
        with self._mu:
            return self._next_seq - 1

    @property
    def done(self) -> bool:
        with self._mu:
            return self._done


class DedupEntry:
    """One idempotency key's state: live (owner dispatched, duplicates
    attach to ``future``/``replay``) or terminal (``result`` replayable).

    ``ready`` closes the claim-to-publish window: a duplicate that wins
    the race between the owner's claim and its admission completing
    waits on ``ready`` instead of spinning or double-dispatching.
    """

    __slots__ = ("key", "rid", "future", "replay", "result", "terminal", "ready")

    def __init__(self, key: str) -> None:
        self.key = key
        self.rid: int | None = None
        self.future: Any = None
        self.replay: ReplayStream | None = None
        self.result: Any = None
        self.terminal = False
        self.ready = threading.Event()

    def publish(self, rid: int, future: Any, replay: ReplayStream) -> None:
        self.rid = rid
        self.future = future
        self.replay = replay
        self.ready.set()


class DedupRegistry:
    """Bounded key -> entry map; the replica-side exactly-once authority."""

    def __init__(self, capacity: int = DEFAULT_KEY_CAPACITY) -> None:
        self._mu = threading.Lock()
        self._live: dict[str, DedupEntry] = {}
        self._terminal: collections.OrderedDict[str, DedupEntry] = collections.OrderedDict()
        self.capacity = max(int(capacity), 1)
        self.hits_live = 0
        self.hits_terminal = 0
        self.evicted = 0

    def claim(self, key: str) -> tuple[bool, DedupEntry]:
        """Atomic check-and-register. ``(True, entry)``: caller is the
        owner and must ``publish`` (or the engine's terminal path must
        ``forget``) the entry. ``(False, entry)``: duplicate — attach."""
        with self._mu:
            entry = self._live.get(key)
            if entry is None:
                entry = self._terminal.get(key)
                if entry is not None:
                    self._terminal.move_to_end(key)
            if entry is not None:
                if entry.terminal:
                    self.hits_terminal += 1
                else:
                    self.hits_live += 1
                return False, entry
            entry = DedupEntry(key)
            self._live[key] = entry
            return True, entry

    def lookup(self, key: str) -> DedupEntry | None:
        """Read-only fast path (no claim): the pre-admission duplicate
        check, and the resume wire's registry crossing."""
        with self._mu:
            entry = self._live.get(key)
            if entry is not None:
                self.hits_live += 1
                return entry
            entry = self._terminal.get(key)
            if entry is not None:
                self._terminal.move_to_end(key)
                self.hits_terminal += 1
            return entry

    def settle(self, key: str, result: Any) -> None:
        """Record a *successful* terminal for replay (LRU-bounded)."""
        with self._mu:
            entry = self._live.pop(key, None)
            if entry is None:
                return
            entry.result = result
            entry.terminal = True
            entry.ready.set()
            self._terminal[key] = entry
            self._terminal.move_to_end(key)
            while len(self._terminal) > self.capacity:
                self._terminal.popitem(last=False)
                self.evicted += 1

    def forget(self, key: str) -> None:
        """Drop a key entirely (exception terminal, failed admission):
        the next submit with this key re-runs as a fresh request."""
        with self._mu:
            entry = self._live.pop(key, None)
            self._terminal.pop(key, None)
        if entry is not None:
            entry.ready.set()  # wake waiting duplicates; they see a dead entry

    def stats(self) -> dict[str, int]:
        with self._mu:
            return {
                "live": len(self._live),
                "terminal": len(self._terminal),
                "hits_live": self.hits_live,
                "hits_terminal": self.hits_terminal,
                "evicted": self.evicted,
            }

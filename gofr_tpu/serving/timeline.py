"""Request-lifecycle timelines + the ``/requestz`` flight recorder.

Every request the engine accepts gets a :class:`RequestTimeline`: a set of
monotonic phase stamps (submitted → admitted → prefill start/end → first
token → per-decode-block syncs → detok → terminal) recorded at points the
engine thread **already touches** — the heartbeat stamps, the
``_block_sync`` consume, the detok executor. The hard constraint
(docs/observability.md): instrumentation reads only host-side data that is
already materialized at the existing sync points. Zero new device syncs —
the PR 6 sync-count test pins it.

The :class:`TimelineRecorder` keeps every in-flight timeline plus a
bounded ring of the last-N completed ones, and serves them as JSON at
``/requestz`` / ``/requestz/<request_id>`` (serving/handlers.py). That is
the answer to "where did this request's 200 ms go": per-phase offsets,
decode-block cadence, and the trace id that links the timeline to its
span tree and structured log records.

Thread model: the recorder's own mutex guards only membership (the
in-flight dict and the completed ring) and is never held across a call
out. Per-timeline mutation is single-writer-per-phase (the engine thread,
the submitting thread, the detok executor each own distinct stamps) and
uses GIL-atomic list/dict/attribute operations, so the hot path pays one
``time.monotonic()`` and a dict write per stamp; ``/requestz`` readers
get racy-but-consistent-enough snapshots of a live request by design.
"""

from __future__ import annotations

import collections
import json
import threading
import time
from typing import Any

# canonical phase names, in lifecycle order (decode-block syncs are
# aggregated as counters, not individual stamps — a 1024-token request
# would otherwise grow 256 entries)
PHASES = (
    "submitted",
    "admitted",
    "prefill_start",
    "prefill_end",
    "first_token",
    "detok_done",
)


class RequestTimeline:
    """One request's lifecycle record. Stamps are monotonic seconds; the
    JSON view renders them as millisecond offsets from ``submitted``."""

    __slots__ = (
        "request_id", "trace_id", "created_unix", "prompt_tokens",
        "phases", "decode_blocks", "decode_tokens", "last_block_at",
        "prefill_chunks", "prefix_tier", "finish_reason", "terminal_at",
        "terminal_marks", "spans", "tenant", "_t0",
    )

    def __init__(self, request_id: int, prompt_tokens: int = 0,
                 trace_id: str | None = None) -> None:
        self.request_id = request_id
        self.trace_id = trace_id
        self.created_unix = time.time()  # wall clock, display only
        self._t0 = time.monotonic()
        self.prompt_tokens = prompt_tokens
        self.phases: dict[str, float] = {}
        self.decode_blocks = 0
        self.decode_tokens = 0
        self.last_block_at: float | None = None
        # chunked-prefill record (continuous batching): one entry per
        # committed prefill chunk — {index, tokens, prefix_hit, ms}. A
        # monolithic (single-bucket) prefill leaves this empty; the
        # prefill_start→prefill_end stamps cover it either way.
        self.prefill_chunks: list[dict[str, Any]] = []
        # warmest KV source that served this request's cached prefix:
        # device | host | remote | miss (None until admission walks the
        # cache; docs/performance.md "KV reuse tiers"). First stamp wins
        # — a requeued admission keeps its original attribution.
        self.prefix_tier: str | None = None
        # multi-tenant plane (docs/serving.md "Multi-tenancy"): the
        # request's tenant label — per-tenant SLO attainment is directly
        # scrapeable off /requestz (preempted:<n> phase stamps mark each
        # preemption of the row)
        self.tenant: str | None = None
        self.finish_reason: str | None = None
        self.terminal_at: float | None = None
        # how many times a terminal state was recorded for this request —
        # the chaos tier asserts EXACTLY one (a second mark means two
        # settlement paths both thought they won)
        self.terminal_marks = 0
        # phase -> Span, registered by the engine when a tracer is wired;
        # all still-open spans are force-ended at the terminal mark so a
        # fault path can never leak one (Span.end is idempotent)
        self.spans: dict[str, Any] = {}

    # -- stamping (hot path: one monotonic read + a dict write) --------------
    def stamp(self, phase: str, t: float | None = None) -> None:
        """Record a phase stamp; the FIRST stamp for a phase wins (a
        requeued admission keeps its original queue-wait truth)."""
        self.phases.setdefault(phase, time.monotonic() if t is None else t)

    def block(self, n_tokens: int, t: float | None = None) -> None:
        """One consumed decode block: committed token count for this row
        at the block's single host sync."""
        self.decode_blocks += 1
        self.decode_tokens += int(n_tokens)
        self.last_block_at = time.monotonic() if t is None else t

    def chunk(self, index: int, n_tokens: int, prefix_hit: bool = False,
              start: int = 0) -> None:
        """One committed prefill chunk (or a skipped cached prefix),
        stamped at the ragged block's single host sync — same zero-new-
        device-syncs rule as :meth:`block`. ``start`` is the chunk's
        token offset in the prompt: the chaos tier audits that committed
        spans are contiguous and never overlap (a requeued request
        restarts at 0 — double-prefilling committed KV is the bug class
        the audit pins)."""
        self.prefill_chunks.append({
            "index": int(index),
            "start": int(start),
            "tokens": int(n_tokens),
            "prefix_hit": bool(prefix_hit),
            "ms": round((time.monotonic() - self._t0) * 1e3, 3),
        })

    # -- span registry -------------------------------------------------------
    def open_span(self, phase: str, span: Any) -> Any:
        if span is not None:
            displaced = self.spans.get(phase)
            if displaced is not None and displaced is not span:
                # re-opening a phase (a requeued request re-prefilling
                # after a warm restart): the displaced span would lose
                # its only closing handle — end it now (idempotent)
                try:
                    displaced.end()
                except Exception:
                    pass
            self.spans[phase] = span
            if self.trace_id is None:
                self.trace_id = span.trace_id
        return span

    def end_span(self, phase: str) -> None:
        span = self.spans.get(phase)
        if span is not None:
            span.end()

    def close_spans(self) -> None:
        for span in list(self.spans.values()):
            try:
                span.end()
            except Exception:
                pass  # a torn span must not block terminal settlement

    # -- terminal ------------------------------------------------------------
    @property
    def terminal(self) -> bool:
        return self.finish_reason is not None

    def mark_terminal(self, reason: str, t: float | None = None) -> bool:
        """Record the terminal phase. Returns True for the FIRST mark;
        later marks only bump ``terminal_marks`` (the exactly-once audit
        counter) without overwriting the recorded reason."""
        self.terminal_marks += 1
        if self.finish_reason is not None:
            return False
        self.finish_reason = reason
        self.terminal_at = time.monotonic() if t is None else t
        self.stamp("terminal", self.terminal_at)
        self.close_spans()
        return True

    # -- derived (bench + histograms read these) -----------------------------
    def phase_delta(self, a: str, b: str) -> float | None:
        """Seconds from phase ``a`` to phase ``b``; None when either is
        missing."""
        ta, tb = self.phases.get(a), self.phases.get(b)
        if ta is None or tb is None:
            return None
        return tb - ta

    def queue_wait_s(self) -> float | None:
        return self.phase_delta("submitted", "admitted")

    def ttft_s(self) -> float | None:
        return self.phase_delta("submitted", "first_token")

    def e2e_s(self) -> float | None:
        return self.phase_delta("submitted", "terminal")

    # -- JSON view -----------------------------------------------------------
    def _ms(self, t: float) -> float:
        return round((t - self._t0) * 1e3, 3)

    def to_dict(self) -> dict[str, Any]:
        # snapshot first: an in-flight timeline is being stamped by the
        # engine thread while /requestz serializes it — iterating the
        # live dict would raise "changed size during iteration"
        phases = {
            p: self._ms(t)
            for p, t in sorted(list(self.phases.items()), key=lambda kv: kv[1])
        }
        out: dict[str, Any] = {
            "request_id": self.request_id,
            "trace_id": self.trace_id,
            "created_unix": round(self.created_unix, 6),
            "prompt_tokens": self.prompt_tokens,
            "terminal": self.terminal,
            "finish_reason": self.finish_reason,
            "terminal_marks": self.terminal_marks,
            "phases_ms": phases,
            "decode": {
                "blocks": self.decode_blocks,
                "tokens": self.decode_tokens,
                "last_block_ms": (
                    self._ms(self.last_block_at)
                    if self.last_block_at is not None else None
                ),
            },
        }
        if self.prefill_chunks:
            # snapshot (list() of the live list): the engine thread may
            # append a chunk while /requestz serializes an in-flight row
            out["prefill_chunks"] = list(self.prefill_chunks)
        if self.prefix_tier is not None:
            out["prefix_tier"] = self.prefix_tier
        if self.tenant is not None:
            out["tenant"] = self.tenant
        for key, value in (
            ("queue_wait_ms", self.queue_wait_s()),
            ("ttft_ms", self.ttft_s()),
            ("e2e_ms", self.e2e_s()),
        ):
            out[key] = round(value * 1e3, 3) if value is not None else None
        if not self.terminal:
            out["age_ms"] = self._ms(time.monotonic())
        return out


class TimelineExporter:
    """Streaming JSONL sink for completed timelines: one ``to_dict()``
    line per terminal settlement, written as requests finish. The bounded
    ``/requestz`` ring keeps the last 256 — a production-load run settles
    millions, and the goodput scorer (gofr_tpu/loadlab/scorer.py) and the
    capacity planner both need every one of them. Writes happen on the
    settling thread (usually the detok executor) under the exporter's own
    lock, NEVER under the recorder mutex — a slow disk must not stall
    ``/requestz`` readers or the engine's settlement path."""

    def __init__(self, path: str, *, append: bool = False) -> None:
        self.path = path
        self._mu = threading.Lock()
        self._fh = open(path, "a" if append else "w", encoding="utf-8")
        self._lines = 0

    def write(self, tl: "RequestTimeline") -> None:
        line = json.dumps(tl.to_dict(), sort_keys=True)
        with self._mu:
            if self._fh.closed:
                return  # settled after close(): the ring still has it
            self._fh.write(line + "\n")
            self._lines += 1

    @property
    def lines(self) -> int:
        with self._mu:
            return self._lines

    def flush(self) -> None:
        with self._mu:
            if not self._fh.closed:
                self._fh.flush()

    def close(self) -> None:
        with self._mu:
            if not self._fh.closed:
                self._fh.close()

    def __enter__(self) -> "TimelineExporter":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


class TimelineRecorder:
    """The flight recorder: all in-flight timelines plus a bounded ring
    of the last ``capacity`` completed ones."""

    # bounded prefix-reuse observation map (the spill tier's demotion
    # scorer reads it): far larger than any prefix cache so a hot key's
    # count survives its slabs moving between tiers
    REUSE_KEYS = 4096

    def __init__(self, capacity: int = 256) -> None:
        self._mu = threading.Lock()
        self._inflight: dict[int, RequestTimeline] = {}
        self._done: collections.deque[RequestTimeline] = collections.deque(
            maxlen=max(1, int(capacity))
        )
        # prefix-cache key -> observed reuse count, LRU-bounded. Fed by
        # the engine's admission-time cache hits; consumed by the spill
        # tier's demotion policy (serving/kv_spill.py) — a prefix the
        # timelines show being reused must outlive a one-shot prefix
        # under host-RAM byte pressure, whatever the raw LRU order says.
        self._reuse: "collections.OrderedDict[Any, int]" = (
            collections.OrderedDict()
        )
        self._exporter: TimelineExporter | None = None

    def observe_prefix_reuse(self, key: Any) -> None:
        """Record one admission-time hit on a prefix-cache key (engine
        thread; one dict write under the leaf lock)."""
        with self._mu:
            self._reuse[key] = self._reuse.get(key, 0) + 1
            self._reuse.move_to_end(key)
            while len(self._reuse) > self.REUSE_KEYS:
                self._reuse.popitem(last=False)

    def reuse_count(self, key: Any) -> int:
        """Observed reuse score for a prefix-cache key (0 = never seen
        re-used) — the spill tier's demotion ordering signal."""
        with self._mu:
            return self._reuse.get(key, 0)

    def begin(self, request_id: int, prompt_tokens: int = 0,
              trace_id: str | None = None) -> RequestTimeline:
        tl = RequestTimeline(request_id, prompt_tokens, trace_id)
        tl.stamp("submitted", tl._t0)
        with self._mu:
            self._inflight[request_id] = tl
        return tl

    def export_jsonl(self, path: str, *, append: bool = False) -> TimelineExporter:
        """Stream every subsequently-completed timeline to ``path`` as
        JSONL (one ``to_dict()`` object per line). Returns the exporter;
        the caller owns its lifetime (``close()`` or context-manage it —
        a closed exporter silently stops receiving, it never unhooks
        itself mid-settlement). One exporter at a time: re-calling
        replaces the hook, the displaced exporter is closed."""
        exporter = TimelineExporter(path, append=append)
        with self._mu:
            displaced, self._exporter = self._exporter, exporter
        if displaced is not None:
            displaced.close()
        return exporter

    def finish(self, tl: RequestTimeline, reason: str) -> bool:
        """Terminal settlement for one timeline. Exactly the future-
        settlement winner calls this with effect; a second call (two
        paths racing) is counted on the timeline, never double-ringed."""
        if not tl.mark_terminal(reason):
            return False
        with self._mu:
            self._inflight.pop(tl.request_id, None)
            self._done.append(tl)
            exporter = self._exporter
        if exporter is not None:
            # outside the recorder mutex: a slow disk stalls only the
            # settling thread, never /requestz readers
            try:
                exporter.write(tl)
            except Exception:
                pass  # export is observability, never a settlement gate
        return True

    def get(self, request_id: int) -> RequestTimeline | None:
        with self._mu:
            tl = self._inflight.get(request_id)
            if tl is not None:
                return tl
            for done in reversed(self._done):
                if done.request_id == request_id:
                    return done
        return None

    def all(self) -> list[RequestTimeline]:
        with self._mu:
            return list(self._inflight.values()) + list(self._done)

    def in_flight(self) -> list[RequestTimeline]:
        with self._mu:
            return list(self._inflight.values())

    def completed(self) -> list[RequestTimeline]:
        with self._mu:
            return list(self._done)

    def latency_summary(self) -> dict[str, Any]:
        """Median phase latencies over the completed ring — the compact
        health-check view of the same numbers the histograms export."""
        with self._mu:
            done = list(self._done)
            inflight = len(self._inflight)
        out: dict[str, Any] = {
            "in_flight": inflight, "completed": len(done),
        }
        for key, read in (
            ("ttft_ms_p50", RequestTimeline.ttft_s),
            ("queue_wait_ms_p50", RequestTimeline.queue_wait_s),
            ("e2e_ms_p50", RequestTimeline.e2e_s),
        ):
            values = sorted(
                v for v in (read(tl) for tl in done) if v is not None
            )
            if values:
                out[key] = round(values[len(values) // 2] * 1e3, 3)
        return out

    def snapshot(self, limit: int = 64) -> dict[str, Any]:
        """The ``/requestz`` view: every in-flight timeline (oldest
        first) and the newest ``limit`` completed ones."""
        limit = max(0, int(limit))
        with self._mu:
            inflight = list(self._inflight.values())
            # [-0:] would be the WHOLE list — an explicit zero guard
            done = list(self._done)[-limit:] if limit else []
        return {
            "in_flight": [tl.to_dict() for tl in inflight],
            "completed": [tl.to_dict() for tl in reversed(done)],
            "in_flight_count": len(inflight),
            "completed_count": len(done),
        }

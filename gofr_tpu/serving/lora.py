"""LoRA adapter multiplexing: paged adapter weights for multi-tenant decode.

One base model, many tenants: each request may name a LoRA adapter
(AIBrix adapter management, arXiv:2504.03648) and the engine serves rows
with DIFFERENT adapters in the same fused decode block — the kernels in
``serving/batch.py`` gather a per-row adapter index out of a fixed device
table and apply the grouped low-rank delta inside the dispatch, so
heterogeneous-adapter batching costs no extra dispatches and no extra
host syncs (the PR 6 one-sync-per-block contract is untouched).

Storage is tiered like the KV plane (serving/kv_spill.py):

- **host pool** — every registered :class:`LoraAdapter` lives as host
  numpy arrays in the :class:`AdapterRegistry`, unbounded by device HBM;
- **device table** — a fixed ``[max_active, ...]`` pair of stacked delta
  factors (``a_table [n, D, r]`` / ``b_table [n, r, V]``); slot 0 is the
  base model (all-zero delta) and never evicts. Active adapters are
  pinned by the rows decoding with them; unpinned slots recycle LRU.

Uploads run on a single-worker ``lora-upload`` executor (the spill tier's
sibling): ``prefetch`` at submit time schedules the host→device copy off
the engine thread under the ``lora.upload`` chaos point, and the
admission-time :meth:`acquire` normally finds the adapter already
resident. An upload fault is transient by construction — acquire raises
:class:`AdapterBusy` and the engine requeues the request exactly like
KV-pool pressure.

Delta math: the adapter is a low-rank token→logits bypass — for a row
about to sample from ``logits`` produced by forwarding input token ``t``,
the delta is ``emb[t] @ A_i @ B_i`` with ``A_i [D, r]``, ``B_i [r, V]``.
Applied identically at every sampling site (prefill first token, each
block step, ragged fold), so a heterogeneous batch is token-identical to
sequential per-adapter runs. The full per-projection (q/v) LoRA belongs
with a hardware round — it changes the KV contents and lands together
with the flat-packed Pallas prefill kernel (ROADMAP).

Lock discipline: the registry mutex is LEAF-ONLY (never held across a
device op or a call out); table swaps are reference assignments under it.
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import threading
from typing import Any

import numpy as np

from gofr_tpu import chaos

__all__ = [
    "LoraAdapter", "AdapterRegistry", "AdapterBusy", "UnknownAdapter",
    "make_adapter",
]


class UnknownAdapter(KeyError):
    """The request named an adapter the registry has never seen — a
    CLIENT error (400/INVALID_ARGUMENT at the transports), never a
    retriable condition."""


class AdapterBusy(RuntimeError):
    """Transient: no device table slot can be recycled right now (every
    slot is pinned by an active row) or the async upload faulted — the
    engine requeues the request like KV-pool pressure."""

    retriable = True


@dataclasses.dataclass
class LoraAdapter:
    """One registered adapter: host-resident low-rank factors.

    ``a`` is ``[d_model, rank]``, ``b`` is ``[rank, vocab]`` — the
    token→logits bypass factors (see the module docstring). ``scale``
    multiplies the delta (the usual alpha/rank knob, folded into ``b``
    at registration so the device table stays two tensors)."""

    adapter_id: str
    a: np.ndarray
    b: np.ndarray

    @property
    def rank(self) -> int:
        return int(self.a.shape[1])


def make_adapter(cfg: Any, adapter_id: str, *, rank: int = 4,
                 seed: int = 0, scale: float = 1.0) -> LoraAdapter:
    """Random-init adapter for tests/benches: factors sized to the model
    config, scaled so the delta measurably shifts the argmax without
    drowning the base logits."""
    rng = np.random.default_rng(seed)
    d, v = int(cfg.d_model), int(cfg.vocab_size)
    a = rng.standard_normal((d, rank)).astype(np.float32) / np.sqrt(d)
    b = rng.standard_normal((rank, v)).astype(np.float32) * (scale / np.sqrt(rank))
    return LoraAdapter(adapter_id, a, b)


class AdapterRegistry:
    """Host pool of registered adapters + the fixed device table of the
    active ones. Engine-facing surface:

    - ``prefetch(adapter_id)`` — submit-time: schedule the async upload
      (off the engine thread) so admission finds the adapter resident;
    - ``acquire(adapter_id) -> int`` — admission-time (engine thread):
      pin and return the adapter's device slot index; raises
      :class:`AdapterBusy` (transient → requeue) or
      :class:`UnknownAdapter` (client error);
    - ``release(idx)`` — retire-time: unpin;
    - ``tables() -> (a_table, b_table)`` — the current device table refs
      for a dispatch. Tables are NEVER donated and every upload swap
      builds a new array (functional ``.at[].set``), so an in-flight
      block keeps reading the table it was dispatched with.
    """

    def __init__(self, *, max_active: int = 8, metrics: Any = None,
                 logger: Any = None) -> None:
        if max_active < 2:
            raise ValueError("TPU_LORA_MAX_ACTIVE must be >= 2 (slot 0 is base)")
        self.max_active = int(max_active)
        self._metrics = metrics
        self._logger = logger
        self._mu = threading.Lock()
        self._adapters: dict[str, LoraAdapter] = {}
        # device residency: adapter_id -> slot, slot -> adapter_id
        self._slot_of: dict[str, int] = {}
        self._id_of: dict[int, str] = {}
        self._pins: dict[int, int] = {}
        self._lru: list[int] = []  # unpinned resident slots, oldest first
        self._uploads: dict[str, concurrent.futures.Future] = {}
        # adapter_id -> slot claimed by a queued upload, so two
        # prefetches can never claim the same slot
        self._upload_slot: dict[str, int] = {}
        self._a_table: Any = None  # jnp [max_active, D, r_max]
        self._b_table: Any = None  # jnp [max_active, r_max, V]
        self._rank_max = 0
        self._exec = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="lora-upload"
        )
        self.upload_faults_total = 0

    @classmethod
    def from_config(cls, config: Any, **kw: Any) -> "AdapterRegistry":
        return cls(
            max_active=int(config.get_or_default("TPU_LORA_MAX_ACTIVE", "8")),
            **kw,
        )

    # -- host pool -------------------------------------------------------------
    def register(self, adapter: LoraAdapter) -> None:
        """File an adapter in the host pool (host numpy only — no device
        work until a request names it). Re-registering an id replaces the
        weights; its device copy, if any, is dropped so the next acquire
        uploads the new factors."""
        a = np.asarray(adapter.a, np.float32)
        b = np.asarray(adapter.b, np.float32)
        if a.ndim != 2 or b.ndim != 2 or a.shape[1] != b.shape[0]:
            raise ValueError(
                f"adapter {adapter.adapter_id!r}: a must be [D, r] and "
                f"b [r, V] with matching rank (got {a.shape} / {b.shape})"
            )
        with self._mu:
            # model-dimension mismatches are PERMANENT: reject at the
            # registration door — discovered at upload time they would
            # masquerade as transient AdapterBusy and spin the request's
            # requeue loop forever
            ref = (
                (self._a_table.shape[1], self._b_table.shape[2])
                if self._a_table is not None else next(
                    ((p.a.shape[0], p.b.shape[1])
                     for p in self._adapters.values()), None,
                )
            )
            if ref is not None and (a.shape[0], b.shape[1]) != ref:
                raise ValueError(
                    f"adapter {adapter.adapter_id!r}: dims "
                    f"(D={a.shape[0]}, V={b.shape[1]}) do not match the "
                    f"registry's model (D={ref[0]}, V={ref[1]})"
                )
            self._adapters[adapter.adapter_id] = LoraAdapter(
                adapter.adapter_id, a, b
            )
            slot = self._slot_of.pop(adapter.adapter_id, None)
            if slot is not None:
                self._id_of.pop(slot, None)
                if slot in self._lru:
                    self._lru.remove(slot)
            self._uploads.pop(adapter.adapter_id, None)

    def deregister(self, adapter_id: str) -> None:
        with self._mu:
            self._adapters.pop(adapter_id, None)
            slot = self._slot_of.pop(adapter_id, None)
            if slot is not None:
                self._id_of.pop(slot, None)
                if slot in self._lru:
                    self._lru.remove(slot)
            self._uploads.pop(adapter_id, None)

    def known(self, adapter_id: str) -> bool:
        with self._mu:
            return adapter_id in self._adapters

    def ids(self) -> list[str]:
        with self._mu:
            return list(self._adapters)

    # -- device table ----------------------------------------------------------
    def _ensure_tables_locked(self, adapter: LoraAdapter) -> None:
        """Allocate (or grow, on a larger-rank registration) the device
        tables. Called under the mutex; the jnp work is pure functional
        array construction — a swap never mutates what a dispatch holds."""
        import jax.numpy as jnp

        d, r = adapter.a.shape
        v = adapter.b.shape[1]
        if self._a_table is None:
            self._rank_max = r
            self._a_table = jnp.zeros((self.max_active, d, r), jnp.float32)
            self._b_table = jnp.zeros((self.max_active, r, v), jnp.float32)
        elif r > self._rank_max:
            pad_r = r - self._rank_max
            self._a_table = jnp.pad(self._a_table, ((0, 0), (0, 0), (0, pad_r)))
            self._b_table = jnp.pad(self._b_table, ((0, 0), (0, pad_r), (0, 0)))
            self._rank_max = r

    def _upload(self, adapter_id: str, slot: int) -> None:
        """The lora-upload worker: materialize one adapter into its table
        slot. Runs OFF the engine thread (the kv-spill pattern); the
        ``lora.upload`` chaos point makes a torn upload a first-class
        fault — acquire sees the future's exception and the request
        requeues, never decodes with a half-written delta."""
        import jax.numpy as jnp

        chaos.maybe_fail("lora.upload")
        with self._mu:
            adapter = self._adapters.get(adapter_id)
            if adapter is None:  # deregistered while queued
                raise UnknownAdapter(adapter_id)
            self._ensure_tables_locked(adapter)
            a_tab, b_tab, r_max = self._a_table, self._b_table, self._rank_max
        r = adapter.rank
        a = np.zeros(a_tab.shape[1:], np.float32)
        a[:, :r] = adapter.a
        b = np.zeros(b_tab.shape[1:], np.float32)
        b[:r, :] = adapter.b
        # functional update: the OLD table stays alive for any in-flight
        # dispatch; the swap below is a reference assignment under the mutex
        new_a = a_tab.at[slot].set(jnp.asarray(a))
        new_b = b_tab.at[slot].set(jnp.asarray(b))
        with self._mu:
            # a concurrent larger-rank registration may have grown the
            # tables while this upload computed: losing that race retries
            if self._a_table is a_tab and self._rank_max == r_max:
                self._a_table, self._b_table = new_a, new_b
                self._id_of[slot] = adapter_id
                self._slot_of[adapter_id] = slot
                if self._pins.get(slot, 0) == 0 and slot not in self._lru:
                    # resident-but-unpinned from birth (a prefetch whose
                    # request was shed/canceled before admission): the
                    # slot must be LRU-recyclable or it would leak —
                    # enough never-acquired uploads would wedge the table
                    self._lru.append(slot)
                resident = len(self._slot_of)
            else:
                raise AdapterBusy(f"adapter {adapter_id}: table grew mid-upload")
        if self._metrics is not None:
            self._metrics.set_gauge(
                "app_lora_adapter_residency", float(resident)
            )

    def _claim_slot_locked(self, adapter_id: str) -> int:
        """Pick the device slot this adapter will occupy: a never-used
        slot first, else the LRU unpinned resident (its adapter demotes
        to host-only). Raises AdapterBusy when every slot is pinned."""
        used = set(self._id_of) | set(self._upload_slot.values())
        for slot in range(1, self.max_active):
            if slot not in used:
                return slot
        while self._lru:
            slot = self._lru.pop(0)
            if self._pins.get(slot, 0) == 0 and slot in self._id_of:
                evicted = self._id_of.pop(slot)
                self._slot_of.pop(evicted, None)
                return slot
        raise AdapterBusy(
            f"adapter {adapter_id}: all {self.max_active - 1} device slots "
            "pinned by active rows"
        )

    def prefetch(self, adapter_id: str) -> None:
        """Submit-time hint (caller thread, never the engine thread):
        start the async upload so admission finds the adapter resident.
        Unknown ids raise so the transport can 400 before queueing."""
        with self._mu:
            if adapter_id not in self._adapters:
                raise UnknownAdapter(adapter_id)
            if adapter_id in self._slot_of or adapter_id in self._uploads:
                return
            try:
                slot = self._claim_slot_locked(adapter_id)
            except AdapterBusy:
                return  # admission-time acquire retries with pins drained
            self._upload_slot[adapter_id] = slot
            fut = self._exec.submit(self._upload, adapter_id, slot)
            self._uploads[adapter_id] = fut
            fut.add_done_callback(
                lambda f, aid=adapter_id: self._upload_done(aid, f)
            )

    def _upload_done(self, adapter_id: str, fut: Any) -> None:
        with self._mu:
            self._uploads.pop(adapter_id, None)
            self._upload_slot.pop(adapter_id, None)
        exc = fut.exception()
        if exc is not None:
            self.upload_faults_total += 1
            if self._logger is not None:
                self._logger.warn(
                    f"lora adapter {adapter_id} upload failed: {exc}"
                )

    def acquire(self, adapter_id: str | None, timeout: float = 5.0) -> int:
        """Admission-time pin (engine thread): returns the adapter's
        device slot index (0 for no adapter). Waits BOUNDED for an
        in-flight upload (a typical upload lands in milliseconds; the
        tight bound keeps a pathological backlog from stalling the
        decode loop — past it the request requeues and retries); a
        missed/faulted upload re-schedules once and raises
        :class:`AdapterBusy` (transient — the engine requeues the
        request) if the adapter still is not resident."""
        if not adapter_id:
            return 0
        with self._mu:
            if adapter_id not in self._adapters:
                raise UnknownAdapter(adapter_id)
            slot = self._slot_of.get(adapter_id)
            if slot is not None:
                self._pin_locked(slot)
                return slot
            fut = self._uploads.get(adapter_id)
        if fut is None:
            self.prefetch(adapter_id)
            with self._mu:
                fut = self._uploads.get(adapter_id)
            if fut is None:
                # prefetch could not claim a slot (all pinned) — transient
                raise AdapterBusy(adapter_id)
        try:
            fut.result(timeout=timeout)
        except AdapterBusy:
            raise
        except UnknownAdapter:
            raise
        except Exception as exc:  # chaos fault / transport-ish upload error
            raise AdapterBusy(f"adapter {adapter_id} upload failed") from exc
        with self._mu:
            slot = self._slot_of.get(adapter_id)
            if slot is None:
                raise AdapterBusy(adapter_id)
            self._pin_locked(slot)
            return slot

    def _pin_locked(self, slot: int) -> None:
        self._pins[slot] = self._pins.get(slot, 0) + 1
        if slot in self._lru:
            self._lru.remove(slot)

    def release(self, slot: int) -> None:
        """Unpin one row's claim on a device slot; a slot whose pins
        drain to zero becomes LRU-recyclable (weights stay resident until
        a new adapter needs the slot — a follow-up request hits warm)."""
        if slot <= 0:
            return
        with self._mu:
            n = self._pins.get(slot, 0) - 1
            if n <= 0:
                self._pins.pop(slot, None)
                if slot in self._id_of and slot not in self._lru:
                    self._lru.append(slot)
            else:
                self._pins[slot] = n

    def tables(self) -> tuple[Any, Any] | None:
        """Current device tables for a dispatch (never donated), or None
        when no adapter has ever been uploaded — the None path keeps the
        base-only engine byte-identical to the pre-LoRA executables."""
        with self._mu:
            if self._a_table is None:
                return None
            return self._a_table, self._b_table

    def slot_factors(self, slot: int) -> tuple[Any, Any] | None:
        """One slot's (a, b) factor pair out of the device tables — the
        host-path first-token sampling uses it for the single-row delta."""
        tabs = self.tables()
        if tabs is None or slot <= 0:
            return None
        return tabs[0][slot], tabs[1][slot]

    def residency(self) -> dict[str, Any]:
        with self._mu:
            return {
                "registered": len(self._adapters),
                "resident": len(self._slot_of),
                "max_active": self.max_active - 1,
                "pinned_slots": sum(1 for n in self._pins.values() if n > 0),
                "uploads_in_flight": len(self._uploads),
                "upload_faults_total": self.upload_faults_total,
            }

    def flush(self, timeout: float = 5.0) -> None:
        """Settle queued uploads (tests, drain)."""
        with self._mu:
            futs = list(self._uploads.values())
        for fut in futs:
            try:
                fut.result(timeout=timeout)
            except Exception:
                pass

    def close(self) -> None:
        self._exec.shutdown(wait=False)

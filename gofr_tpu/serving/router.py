"""The multi-replica router tier: prefix-affinity routing with failover.

One engine process serves one accelerator; millions of users need N of
them (ROADMAP item 3). This router fronts N replicas and makes the tier
survive the death, drain, or wedge of any individual replica with no
lost requests and bounded failover latency (AIBrix, arXiv:2504.03648 —
cache-aware routing + health-driven replica management is what turns a
fast engine into a serving *system*).

Four planes, each reusing a robustness layer built in PRs 3-5:

- **Membership** (serving/membership.py): replicas heartbeat over the
  pubsub layer; missed beats → SUSPECT → DOWN, breaker-open → DOWN now.
  DRAINING/WEDGED replicas receive zero new routes.
- **Prefix affinity**: requests hash by prompt prefix onto a consistent
  ring (vnodes per replica), so repeated prefixes land on the replica
  whose ``prefix_cache.py`` already holds their prefill — falling back
  to least-estimated-wait when the affine replica is unhealthy or its
  shed queue-wait EWMA exceeds the spill bound. Affinity is a hint, not
  an invariant: spilling costs one prefill, routing to a dead replica
  costs the request.
- **Failover & hedging**: a replica failing a request *before its first
  token* (503/UNAVAILABLE + Retry-After — the PR 5 warm-restart
  contract) re-routes to the next candidate with the original absolute
  deadline preserved. A request that already streamed tokens is NEVER
  silently re-run (the stream is not idempotent): the client gets the
  typed retriable error and decides. Optionally the *prefill admission*
  is hedged on a second replica after a p99-based delay; first token
  wins, the loser is canceled before it streams.
- **Observability**: per-replica ``app_router_replica_state``,
  ``app_router_failovers_total``, ``app_router_hedges_total``, aggregate
  queue-wait, and the ``/routerz`` view (serving/handlers.py).

The invariant the chaos tier (tests/test_router_chaos.py) enforces:
*every accepted request reaches exactly one terminal state on exactly
one replica, within its deadline or with a retriable error.*
"""

from __future__ import annotations

import concurrent.futures
import dataclasses
import hashlib
import threading
import time
from typing import Any, Callable

from gofr_tpu import chaos
from gofr_tpu.chaos.injector import ChaosFault
from gofr_tpu.http.errors import (
    ErrorDeadlineExceeded,
    ErrorEntityNotFound,
    ErrorServiceUnavailable,
    ErrorStaleEpoch,
    ErrorTooManyRequests,
)
from gofr_tpu.metrics.register import Histogram
from gofr_tpu.serving import membership as ms
from gofr_tpu.serving.prefix_index import PrefixIndex, decode_entry
from gofr_tpu.service.options import CircuitBreakerError
from gofr_tpu.tracing.trace import current_span, format_traceparent

# The typed-retriable error set: ONLY these may trigger a failover
# re-route or be swallowed while a better attempt lives. Everything else
# (400s, 413, deadline, programming errors) propagates to the client
# untouched — retrying a non-retriable error duplicates work at best and
# output at worst. gofrlint's ``router-retry-untyped`` rule pins this:
# except clauses in the retry-zone functions must name only this set.
RETRIABLE_ERRORS = (
    ErrorServiceUnavailable,   # 503 + Retry-After: warm restart / drain
    ErrorTooManyRequests,      # 429 shed: another replica may have room
    CircuitBreakerError,       # breaker open: the replica is gone
    ChaosFault,                # injected transient (chaos tier)
    ConnectionError,           # transport reset to a remote replica
    # 409 stale fence: THIS router's view of the replica lagged a warm
    # restart / reclaim / re-register (docs/robustness.md "The HA
    # plane"). The engine wire must hard-reject (a zombie router cannot
    # be allowed through), but for a merely-lagging router the right
    # move is a re-route — each attempt re-stamps fence_epoch from the
    # membership table, so the retry carries the refreshed fence.
    ErrorStaleEpoch,
)


@dataclasses.dataclass
class RouterConfig:
    """Every knob env-tunable, like EngineConfig (docs/robustness.md has
    the full table)."""

    heartbeat_s: float = 1.0
    suspect_after_s: float | None = None   # default: 3 × heartbeat_s
    down_after_s: float | None = None      # default: 10 × heartbeat_s
    # affine replica's queue-wait EWMA above this → spill to the
    # least-loaded healthy replica (0 disables spilling)
    spill_wait_s: float = 1.0
    # prompt units (tokens for id lists, utf-8 bytes for strings) that
    # define the affinity prefix — long enough to separate system
    # prompts, short enough that a trailing user turn doesn't break
    # affinity
    affinity_prefix_tokens: int = 32
    vnodes: int = 64               # ring positions per replica
    max_failovers: int = 3         # re-routes per request
    # hedge the prefill admission on a second replica when the first
    # token hasn't arrived after this many seconds (0 disables hedging)
    hedge_delay_s: float = 0.0
    # with enough TTFT observations, the hedge delay floors at the
    # observed p99 — hedging inside normal latency doubles prefill load
    # for nothing
    hedge_from_p99: bool = True
    # affine replica's reported HBM headroom (membership heartbeat
    # hbm_free_frac, fed by the device-telemetry poller) below this
    # fraction → spill like queue-wait pressure; 0 disables. Replicas
    # that report no HBM signal are never spilled on it.
    spill_hbm_frac: float = 0.05
    heartbeat_topic: str = ms.HEARTBEAT_TOPIC

    def __post_init__(self) -> None:
        if self.suspect_after_s is None:
            self.suspect_after_s = 3.0 * self.heartbeat_s
        if self.down_after_s is None:
            self.down_after_s = 10.0 * self.heartbeat_s

    @classmethod
    def from_config(cls, config: Any) -> "RouterConfig":
        hb = float(config.get_or_default("TPU_ROUTER_HEARTBEAT_S", "1.0"))
        suspect = config.get("TPU_ROUTER_SUSPECT_AFTER_S")
        down = config.get("TPU_ROUTER_DOWN_AFTER_S")
        return cls(
            heartbeat_s=hb,
            suspect_after_s=float(suspect) if suspect else None,
            down_after_s=float(down) if down else None,
            spill_wait_s=float(
                config.get_or_default("TPU_ROUTER_SPILL_WAIT_S", "1.0")
            ),
            affinity_prefix_tokens=int(
                config.get_or_default("TPU_ROUTER_AFFINITY_TOKENS", "32")
            ),
            vnodes=int(config.get_or_default("TPU_ROUTER_VNODES", "64")),
            max_failovers=int(
                config.get_or_default("TPU_ROUTER_MAX_FAILOVERS", "3")
            ),
            hedge_delay_s=float(
                config.get_or_default("TPU_ROUTER_HEDGE_DELAY_S", "0")
            ),
            hedge_from_p99=config.get_or_default(
                "TPU_ROUTER_HEDGE_P99", "true"
            ).lower() in ("1", "true", "yes"),
            spill_hbm_frac=float(
                config.get_or_default("TPU_ROUTER_SPILL_HBM_FRAC", "0.05")
            ),
            heartbeat_topic=config.get_or_default(
                "TPU_ROUTER_HEARTBEAT_TOPIC", ms.HEARTBEAT_TOPIC
            ),
        )


def prefix_affinity_key(prompt: str | list[int], prefix_units: int) -> bytes:
    """The affinity key: a digest of the prompt's leading units (token
    ids for pre-tokenized prompts, utf-8 bytes for strings). Mirrors the
    keying of serving/prefix_cache.py — two requests sharing a system
    prompt share a key, so the ring sends them to the replica whose
    prefix cache already holds that prefill."""
    if isinstance(prompt, str):
        head = prompt.encode("utf-8")[:prefix_units]
    else:
        import numpy as np

        head = np.asarray(list(prompt[:prefix_units]), np.int32).tobytes()
    return hashlib.blake2b(head, digest_size=8).digest()


class _HashRing:
    """Consistent hash over replica ids with ``vnodes`` positions each:
    adding/removing one replica remaps ~1/N of the key space instead of
    all of it (affinity survives membership churn)."""

    def __init__(self, replica_ids: list[str], vnodes: int) -> None:
        points: list[tuple[int, str]] = []
        for rid in replica_ids:
            for v in range(vnodes):
                digest = hashlib.blake2b(
                    f"{rid}#{v}".encode(), digest_size=8
                ).digest()
                points.append((int.from_bytes(digest, "big"), rid))
        points.sort()
        self._points = points

    def lookup(self, key: bytes) -> str | None:
        if not self._points:
            return None
        import bisect

        h = int.from_bytes(
            hashlib.blake2b(key, digest_size=8).digest(), "big"
        )
        idx = bisect.bisect_left(self._points, (h, ""))
        if idx == len(self._points):
            idx = 0
        return self._points[idx][1]


class LocalReplica:
    """An in-process engine replica handle (the chaos tier runs ≥2 of
    these side by side; production wraps one per process). The handle
    contract: ``submit(prompt, **kw) -> Future``, ``cancel(request_id)``,
    ``health_check()``."""

    def __init__(self, replica_id: str, engine: Any,
                 role: str | None = None) -> None:
        self.replica_id = replica_id
        self.engine = engine
        # disaggregation role seed for membership registration; the
        # engine's announcer remains authoritative via heartbeats
        self.role = role or getattr(engine, "role", None) or ms.ROLE_UNIFIED
        # preemptible capability seed, mirrored from the engine config —
        # heartbeats are authoritative after registration
        self.preemptible = bool(getattr(engine, "preemptible", False))

    def submit(self, prompt: str | list[int], **kw: Any) -> Any:
        return self.engine.submit(prompt, **kw)

    def cancel(self, request_id: int) -> None:
        self.engine.cancel(request_id)

    def resume(self, idempotency_key: str, *, last_seq: int = 0,
               stream_cb: Any = None, fence_epoch: int | None = None,
               deadline: float | None = None) -> Any:
        """Re-attach to a keyed stream on this replica (docs/serving.md
        "Resumable streams"); raises 404 when the engine never saw the
        key (the router's resume walk tries the next replica)."""
        del deadline  # in-process attach is immediate; no wire budget
        return self.engine.resume(
            idempotency_key, last_seq=last_seq, stream_cb=stream_cb,
            fence_epoch=fence_epoch,
        )

    def health_check(self) -> dict[str, Any]:
        return self.engine.health_check()


class HTTPReplica:
    """A remote engine replica behind its HTTP surface, through the
    service-client stack (PR 3 retry semantics stay with the ROUTER —
    the client here is breaker-only, because the router's failover IS
    the retry policy; stacking both would retry twice).

    The breaker's open/close transitions feed the membership table
    directly via ``on_state_change`` — the data path detecting a dead
    replica must not wait for the heartbeat timers."""

    def __init__(self, replica_id: str, address: str, *, logger: Any = None,
                 metrics: Any = None, breaker_threshold: int = 3,
                 breaker_interval: float = 5.0,
                 on_breaker_open: Callable[[str], None] | None = None,
                 role: str | None = None) -> None:
        from gofr_tpu.service.client import new_http_service
        from gofr_tpu.service.options import CircuitBreakerConfig

        self.replica_id = replica_id
        self.address = address
        self.role = role or ms.ROLE_UNIFIED  # membership registration seed
        self._svc = new_http_service(
            address, logger, metrics, None,
            CircuitBreakerConfig(breaker_threshold, breaker_interval),
        )
        if on_breaker_open is not None:
            self._svc.on_state_change = (
                lambda open_: on_breaker_open(replica_id) if open_ else None
            )
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix=f"replica-{replica_id}"
        )
        self._rid_mu = threading.Lock()
        self._next_rid = 0
        # local rid -> remote engine rid, learned from the stream's id
        # frame; the cancel wire posts the REMOTE id. None = id frame not
        # seen yet; a cancel arriving first parks in _cancel_early and
        # fires the moment the id lands.
        self._remote_ids: dict[int, int | None] = {}
        self._cancel_early: set[int] = set()

    def _build_payload(
        self, prompt: Any, kw: dict[str, Any]
    ) -> dict[str, Any]:
        payload: dict[str, Any] = {"prompt": prompt}
        if kw.get("max_new_tokens"):
            payload["max_tokens"] = kw["max_new_tokens"]
        for key in ("temperature", "top_k", "top_p"):
            if kw.get(key):
                payload[key] = kw[key]
        # disaggregation plane: ride the wire only when set (an older
        # replica's handler ignores unknown fields either way)
        if kw.get("prefill_only"):
            payload["prefill_only"] = True
        if kw.get("handoff_from"):
            payload["handoff_from"] = kw["handoff_from"]
        # multi-tenant plane: adapter + tenant ride the wire the same way
        if kw.get("adapter_id"):
            payload["adapter_id"] = kw["adapter_id"]
        if kw.get("tenant"):
            payload["tenant"] = kw["tenant"]
        # HA plane (docs/robustness.md "The HA plane"): the exactly-once
        # key and the per-attempt membership fence ride the wire when set
        if kw.get("idempotency_key"):
            payload["idempotency_key"] = kw["idempotency_key"]
        if kw.get("fence_epoch"):
            payload["fence_epoch"] = int(kw["fence_epoch"])
        return payload

    def submit(self, prompt: str | list[int], *, deadline: float | None = None,
               stream_cb: Any = None, trace_ctx: Any = None,
               **kw: Any) -> Any:
        with self._rid_mu:
            self._next_rid += 1
            rid = self._next_rid
        future: Any = concurrent.futures.Future()
        future.request_id = rid
        payload = self._build_payload(prompt, kw)
        headers: dict[str, str] = {}
        if deadline:
            headers["X-Request-Timeout"] = f"{deadline:.3f}"
        ctx_span = trace_ctx if trace_ctx is not None else current_span()
        if ctx_span is not None:
            # W3C propagation over the remote transport: the replica's
            # HTTP middleware continues this trace, so the cross-process
            # span tree stays connected
            headers["traceparent"] = format_traceparent(ctx_span)
        if stream_cb is not None:
            # token-level streaming (serving/remote.py): tokens reach the
            # router's stream claim the moment the replica decodes them —
            # remote TTFT decouples from completion time, and failover/
            # hedging keep their pre-first-token semantics over the wire
            with self._rid_mu:
                # cancelable from this instant: a cancel landing before
                # the id frame parks in _cancel_early (see cancel())
                self._remote_ids[rid] = None
            self._pool.submit(
                self._run_stream, rid, future, payload, headers or None,
                deadline, stream_cb,
            )
        else:
            self._pool.submit(
                self._run_unary, rid, future, payload, headers or None,
                deadline, stream_cb,
            )
        return future

    def _run_unary(self, rid: int, future: Any, payload: dict,
                   headers: dict | None, deadline: float | None,
                   stream_cb: Any) -> None:
        from gofr_tpu.serving.remote import error_from_status

        try:
            resp = self._svc.post(
                "/generate", json=payload, headers=headers,
                timeout=deadline,
            )
            if not resp.ok:
                raise error_from_status(
                    resp.status_code,
                    f"replica {self.replica_id}: HTTP {resp.status_code}",
                    resp.headers,
                )
            body = resp.json()
            data = body.get("data") or body
            if stream_cb is not None:
                stream_cb(0, data.get("text", ""), False)
                stream_cb(0, "", True)
            future.set_result(_RemoteResult(rid, data))
        # gofrlint: disable=router-retry-untyped -- settles the future
        # with the error (no retry happens here); a narrow catch would
        # strand the client future forever on an unexpected failure
        except BaseException as exc:
            if isinstance(exc, OSError) and not isinstance(
                exc, ConnectionError
            ):
                exc = ConnectionError(str(exc))
            future.set_exception(exc)

    def _run_stream(self, rid: int, future: Any, payload: dict,
                    headers: dict | None, deadline: float | None,
                    stream_cb: Any) -> None:
        """One remote streaming generation, on a pool worker (the frame
        reads block by design — never the event loop). Mirrors the
        engine's settlement contract: tokens stream, the done frame
        fires, THEN the future resolves; failures settle the future
        FIRST (the router's claim guard reads that ordering)."""
        from gofr_tpu.serving.remote import run_stream

        state: dict[str, Any] = {
            "ids": [], "pieces": [], "first_ms": 0.0, "t0": time.monotonic(),
        }

        def on_id(remote_id: int) -> None:
            fire = False
            with self._rid_mu:
                self._remote_ids[rid] = remote_id
                if rid in self._cancel_early:
                    self._cancel_early.discard(rid)
                    fire = True
            if fire:
                self._post_cancel(remote_id)

        def on_token(token_id: int, text: str) -> None:
            if not state["ids"]:
                state["first_ms"] = (
                    time.monotonic() - state["t0"]
                ) * 1e3
            state["ids"].append(token_id)
            state["pieces"].append(text)
            stream_cb(token_id, text, False)

        try:
            terminal = run_stream(
                self._svc, payload, headers=headers, timeout=deadline,
                on_id=on_id, on_token=on_token,
            )
            data = dict(terminal)
            usage = dict(data.get("usage") or {})
            # the replica reports prompt/completion; TTFT as OBSERVED
            # through this transport is what the router's hedge floor
            # must key on. The token stream itself is the result body —
            # rebuild it so a streamed remote result carries the same
            # token_ids/text a unary one does.
            usage.setdefault("ttft_ms", round(state["first_ms"], 3))
            data["usage"] = usage
            data.setdefault("token_ids", list(state["ids"]))
            data.setdefault("text", "".join(state["pieces"]))
            stream_cb(-1, "", True)
            future.set_result(_RemoteResult(rid, data))
        # gofrlint: disable=router-retry-untyped -- settles the future
        # with the error (no retry happens here); a narrow catch would
        # strand the client future forever on an unexpected failure
        except BaseException as exc:
            if isinstance(exc, OSError) and not isinstance(
                exc, ConnectionError
            ):
                exc = ConnectionError(str(exc))
            future.set_exception(exc)
            # trailing done frame AFTER the failed settlement, mirroring
            # ServingEngine._settle_future — the router's claim guard
            # refuses terminal frames of already-failed attempts
            try:
                stream_cb(-1, "", True)
            # gofrlint: disable=router-retry-untyped -- no retry here: a
            # client callback failing on the courtesy done frame must not
            # mask the already-settled transport error
            except Exception:
                pass
        finally:
            with self._rid_mu:
                self._remote_ids.pop(rid, None)
                self._cancel_early.discard(rid)

    def _post_cancel(self, remote_id: int) -> None:
        from gofr_tpu.serving.remote import CANCEL_PATH

        try:
            self._svc.post(CANCEL_PATH, json={"id": remote_id}, timeout=2.0)
        except Exception:
            pass  # the replica may be gone; its supervisor reclaims

    def cancel(self, request_id: int) -> None:
        """The remote cancel wire: POST the replica's own request id (from
        the stream's id frame) to ``/generate/cancel`` — the engine
        retires the row at the next block sync, so a canceled hedge twin
        stops burning decode steps within one block instead of running to
        completion. A cancel racing the id frame parks and fires when the
        frame lands; unary submissions have no wire to cancel (bounded by
        their deadline, as before)."""
        with self._rid_mu:
            if request_id not in self._remote_ids:
                return  # unary, already finished, or never streamed
            remote_id = self._remote_ids.get(request_id)
            if remote_id is None:
                self._cancel_early.add(request_id)
                return
        self._post_cancel(remote_id)

    def resume(self, idempotency_key: str, *, last_seq: int = 0,
               stream_cb: Any = None, fence_epoch: int | None = None,
               deadline: float | None = None) -> Any:
        """Re-attach to a keyed stream on this remote replica
        (docs/serving.md "Resumable streams"): the pool worker drives
        ``resume_stream`` — ``Idempotency-Key`` + ``Last-Event-ID``
        headers, suffix frames replayed token-identically, then the live
        continuation. ``stream_cb`` is the 4-arg resumable wire
        ``(seq, token_id, piece, done)``. The future resolves to a
        GenerationResult-shaped view of the terminal whose ``token_ids``
        hold the REPLAYED SUFFIX (the client already holds the acked
        prefix). The head errors (404 unknown key, 409 stale fence, 503)
        raise SYNCHRONOUSLY — the router's resume walk classifies them
        and tries the next replica; only the frame drain runs on the
        pool."""
        from gofr_tpu.serving.remote import open_resume

        with self._rid_mu:
            self._next_rid += 1
            rid = self._next_rid
        resp = open_resume(
            self._svc, idempotency_key, last_seq=int(last_seq),
            fence_epoch=fence_epoch, timeout=deadline,
        )
        future: Any = concurrent.futures.Future()
        future.request_id = rid
        deadline_abs = (
            time.monotonic() + deadline if deadline is not None else None
        )
        self._pool.submit(
            self._run_resume, rid, future, resp, int(last_seq),
            deadline_abs, stream_cb,
        )
        return future

    def _run_resume(self, rid: int, future: Any, resp: Any, last_seq: int,
                    deadline_abs: float | None, stream_cb: Any) -> None:
        from gofr_tpu.serving.remote import drain_resume

        state: dict[str, Any] = {"seq": last_seq, "ids": [], "pieces": []}

        def on_frame(seq: int, token_id: int, text: str) -> None:
            state["seq"] = max(state["seq"], seq)
            state["ids"].append(token_id)
            state["pieces"].append(text)
            if stream_cb is not None:
                stream_cb(seq, token_id, text, False)

        try:
            terminal = drain_resume(
                resp, deadline_abs=deadline_abs, on_frame=on_frame,
            )
            data = dict(terminal)
            data.setdefault("token_ids", list(state["ids"]))
            data.setdefault("text", "".join(state["pieces"]))
            if stream_cb is not None:
                stream_cb(
                    int(terminal.get("seq") or (state["seq"] + 1)),
                    -1, "", True,
                )
            future.set_result(_RemoteResult(rid, data))
        # gofrlint: disable=router-retry-untyped -- settles the future
        # with the error (no retry happens here); a narrow catch would
        # strand the client future forever on an unexpected failure
        except BaseException as exc:
            if isinstance(exc, OSError) and not isinstance(
                exc, ConnectionError
            ):
                exc = ConnectionError(str(exc))
            future.set_exception(exc)

    def fetch_kv(self, keys: list[str],
                 timeout: float = 2.0,
                 fence_epoch: int | None = None) -> dict[str, tuple]:
        """Warm KV page migration, remote half (serving/prefix_index.py):
        fetch serialized prefix-cache slabs from this replica's
        ``/kv/fetch`` surface. Returns {key: (logits, k, v)} as HOST
        numpy arrays — the admitting engine uploads them asynchronously.
        Raises on transport failure; the migrator's fetch contract turns
        any raise into a clean compute miss. ``fence_epoch`` rides the
        payload when set: a fetch stamped against a replica that warm-
        restarted since is rejected at the wire (409) instead of serving
        slabs from a cache generation the caller never observed."""
        payload: dict[str, Any] = {"keys": list(keys)}
        if fence_epoch:
            payload["fence_epoch"] = int(fence_epoch)
        resp = self._svc.post(
            "/kv/fetch", json=payload, timeout=timeout,
        )
        if not resp.ok:
            raise ConnectionError(
                f"replica {self.replica_id}: /kv/fetch HTTP {resp.status_code}"
            )
        body = resp.json()
        data = body.get("data") or body
        out: dict[str, tuple] = {}
        for key, payload in (data.get("entries") or {}).items():
            out[key] = decode_entry(payload)
        return out

    def health_check(self) -> dict[str, Any]:
        return self._svc.health_check()

    def close(self) -> None:
        self._pool.shutdown(wait=False)


class _RemoteResult:
    """GenerationResult-shaped view of a remote /generate response."""

    def __init__(self, rid: int, data: dict[str, Any]) -> None:
        usage = data.get("usage") or {}
        self.request_id = rid
        self.text = data.get("text", "")
        self.token_ids: list[int] = data.get("token_ids") or []
        self.finish_reason = data.get("finish_reason", "stop")
        self.prompt_tokens = usage.get("prompt_tokens", 0)
        self.completion_tokens = usage.get("completion_tokens", 0)
        self.ttft_s = usage.get("ttft_ms", 0.0) / 1000.0
        self.duration_s = usage.get("duration_ms", 0.0) / 1000.0


class _RouterRequest:
    """Per-request routing state: which replicas were tried, which
    attempt owns the client-visible stream, how many tokens crossed."""

    def __init__(self, rid: int, prompt: Any, kw: dict[str, Any],
                 stream_cb: Any, deadline_abs: float | None,
                 trace_ctx: Any = None) -> None:
        self.rid = rid
        self.prompt = prompt
        self.kw = kw
        self.stream_cb = stream_cb
        self.deadline_abs = deadline_abs
        self.trace_ctx = trace_ctx  # parent Span the attempt spans hang off
        self.future: Any = concurrent.futures.Future()
        self.future.request_id = rid
        self.mu = threading.Lock()
        self.tried: list[str] = []
        self.live: dict[str, Any] = {}   # replica_id -> replica future
        self.spans: dict[str, Any] = {}  # replica_id -> open attempt span
        self.winner: str | None = None
        self.first_token_at: float | None = None
        self.submitted_at = time.monotonic()
        self.failovers = 0
        self.hedge_timer: threading.Timer | None = None
        self.canceled = False
        # disaggregation (docs/robustness.md "The disaggregation plane"):
        # the role this request's GENERATION attempts must route to
        # (decode when the tier is role-split, None for unified routing —
        # failover and hedge re-walks read it so a re-route can never
        # land generation work on a prefill-only replica), and the live
        # prefill-phase attempt (replica_id, future, span) so cancel
        # reaches a handoff in flight
        self.phase_role: str | None = None
        self.prefill_attempt: tuple[str, Any, Any] | None = None

    def remaining(self) -> float | None:
        if self.deadline_abs is None:
            return None
        return self.deadline_abs - time.monotonic()


class Router:
    """Fronts N replicas: membership-aware, prefix-affine, failover- and
    hedge-capable submit surface mirroring ``ServingEngine.submit``."""

    def __init__(
        self,
        config: RouterConfig | None = None,
        *,
        broker: Any = None,
        metrics: Any = None,
        logger: Any = None,
        tracer: Any = None,
    ) -> None:
        self.config = config or RouterConfig()
        self.broker = broker
        self._metrics = metrics
        self._logger = logger
        self._tracer = tracer
        # shared TenantRegistry (serving/tenancy.py), wired by the
        # container/stack via use_tenants: lets the candidate walk
        # resolve a request's SLO class, so interactive traffic is
        # steered off preemptible capacity (docs/robustness.md "The
        # reclamation plane"). None = no steering (class unknown).
        self._tenants: Any = None
        self.membership = ms.MembershipTable(
            suspect_after_s=self.config.suspect_after_s or 3.0,
            down_after_s=self.config.down_after_s or 10.0,
        )
        # cluster-wide KV reuse (serving/prefix_index.py): per-replica
        # prefix advertisements ride the heartbeats this router already
        # consumes — the router (and any replica handed this index) can
        # locate the longest cached prefix anywhere in the tier. Purely
        # advisory: a stale entry degrades to a compute miss downstream.
        self.prefix_index = PrefixIndex()
        self._handles: dict[str, Any] = {}
        self._handles_mu = threading.Lock()
        # hash rings cached per candidate-set (sorted id tuple): a
        # disaggregated tier alternates prefill-pool and decode-pool
        # walks every request — a single-slot cache would rebuild the
        # 64-vnode ring twice per request, forever. Bounded (the
        # distinct routable sets of a tier are few); cleared on
        # membership-shape changes.
        self._rings: dict[tuple[str, ...], _HashRing] = {}
        self._requests: dict[int, _RouterRequest] = {}
        self._req_mu = threading.Lock()
        self._next_rid = 0
        self._failover_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=2, thread_name_prefix="router-failover"
        )
        self._stop = threading.Event()
        self._consumer: threading.Thread | None = None
        # TTFT observations land in the SHARED registered
        # app_request_ttft_seconds histogram (label source=router) — one
        # series serves /metrics AND the hedge p99 floor. The private
        # instrument only backs routers wired without a metrics manager
        # (unit tests), through the identical Histogram type.
        self._private_ttft: Histogram | None = None
        # counters mirrored into /routerz (metrics keep the canonical
        # series; these make the health view self-contained). Guarded by
        # _stats_mu: they are bumped from caller threads, the failover
        # pool AND replica settlement threads at once — exactly during
        # the failover storms an operator reads them to understand.
        self._stats_mu = threading.Lock()
        self.routed_total = 0
        self.failovers_total = 0
        self.hedges_total = 0
        self.spills_total = 0
        self.no_replica_total = 0
        self.handoffs_total = 0           # prefill→decode KV handoffs hinted
        self.handoff_degraded_total = 0   # handoffs degraded to re-prefill
        self.last_resort_routes_total = 0  # SUSPECT-only pool routes
        self.routes_by_replica: dict[str, int] = {}
        # HA plane (docs/robustness.md "The HA plane"): idempotency-key →
        # replica-id fast path. Strictly an OPTIMIZATION — the replica-
        # side DedupRegistry is the exactly-once authority, so this map
        # may be stale, evicted, or empty (a freshly promoted standby
        # router starts cold) without any correctness loss: a miss just
        # means the duplicate walks the normal candidate order and the
        # owning replica's registry attaches it. Bounded LRU; guarded by
        # its own lock (touched on every keyed submit's hot path).
        self._idem_mu = threading.Lock()
        self._idem_routes: dict[str, str] = {}

    # -- provider pattern (lets the container own the router) ------------------
    def use_logger(self, logger: Any) -> None:
        self._logger = logger

    def use_metrics(self, metrics: Any) -> None:
        self._metrics = metrics

    def use_tracer(self, tracer: Any) -> None:
        self._tracer = tracer

    def use_tenants(self, registry: Any) -> None:
        self._tenants = registry

    def connect(self) -> None:
        pass

    # -- replica management ----------------------------------------------------
    def add_replica(self, handle: Any, role: str | None = None) -> None:
        """Register a replica handle (LocalReplica / HTTPReplica). The
        replica stays SUSPECT until its first heartbeat lands. ``role``
        (or the handle's own ``role`` attribute) seeds its disaggregation
        role; the replica's heartbeats are authoritative after that."""
        with self._handles_mu:
            self._handles[handle.replica_id] = handle
            self._rings.clear()  # rebuilt lazily against the new set
        self.membership.register(
            handle.replica_id,
            role or getattr(handle, "role", None) or ms.ROLE_UNIFIED,
            preemptible=bool(getattr(handle, "preemptible", False)),
        )

    def remove_replica(self, replica_id: str) -> None:
        with self._handles_mu:
            self._handles.pop(replica_id, None)
            self._rings.clear()
        self.membership.forget(replica_id)
        self.prefix_index.drop_replica(replica_id)

    def mark_replica_down(self, replica_id: str,
                          reason: str = "breaker-open") -> None:
        """The breaker's fast path into membership. Also retracts the
        replica's prefix advertisements: a dead replica's entries would
        otherwise keep sending migrators into its transport timeout (a
        fresh UP beat re-advertises along with clearing the mark)."""
        self.membership.mark_down(replica_id, reason)
        self.prefix_index.drop_replica(replica_id)
        self._export_states()

    def _ring_for(self, ids: list[str]) -> _HashRing:
        key = tuple(sorted(ids))
        with self._handles_mu:
            ring = self._rings.get(key)
            if ring is None:
                if len(self._rings) >= 16:  # candidate-set churn bound
                    self._rings.clear()
                ring = _HashRing(list(key), self.config.vnodes)
                self._rings[key] = ring
            return ring

    # -- lifecycle -------------------------------------------------------------
    def start(self) -> None:
        """Start the membership consumer (needs a broker; without one,
        feed ``membership.observe`` directly — unit tests do)."""
        if self._consumer is not None and self._consumer.is_alive():
            return
        self._stop.clear()
        self._consumer = threading.Thread(
            target=self._membership_loop, daemon=True,
            name="router-membership",
        )
        self._consumer.start()

    def stop(self) -> None:
        self._stop.set()
        consumer = self._consumer
        if consumer is not None:
            consumer.join(timeout=2.0)
        self._consumer = None
        self._failover_pool.shutdown(wait=False)

    def close(self) -> None:
        self.stop()

    def _membership_loop(self) -> None:
        """Poll heartbeats, commit each (observe is idempotent under the
        at-least-once contract: stale seqs are dropped), sweep timers and
        export gauges at most once per heartbeat interval."""
        topic = self.config.heartbeat_topic
        last_export = 0.0
        while not self._stop.is_set():
            msg = None
            if self.broker is not None:
                try:
                    msg = self.broker.subscribe(topic)
                except Exception as exc:
                    if self._logger is not None:
                        self._logger.debug(f"heartbeat poll failed: {exc}")
                    self._stop.wait(self.config.heartbeat_s)
            if msg is not None:
                try:
                    self.observe_heartbeat(ms.Heartbeat.from_json(msg.value))
                except (ValueError, KeyError, TypeError):
                    pass  # malformed beat: drop, never crash the loop
                try:
                    msg.commit()
                except Exception:
                    pass  # redelivery is harmless (seq-idempotent)
            else:
                # a driver whose subscribe() returns None without blocking
                # on its own poll timeout must not spin this thread at
                # 100% (the subscriber.py IDLE_SLEEP lesson); bounded so
                # beat-observation latency stays well inside suspect_after
                self._stop.wait(min(self.config.heartbeat_s, 0.05))
            now = time.monotonic()
            if now - last_export >= min(self.config.heartbeat_s, 0.5):
                last_export = now
                self._export_states()

    def observe_heartbeat(self, hb: ms.Heartbeat) -> bool:
        """Commit one heartbeat into membership AND the prefix index —
        both idempotent under the at-least-once pubsub contract (the
        beat's per-replica ``seq`` gates each)."""
        fresh = self.membership.observe(hb)
        if hb.prefix_keys is not None:
            self.prefix_index.observe(hb.replica_id, hb.seq, hb.prefix_keys)
        return fresh

    def _export_states(self) -> None:
        if self._metrics is None:
            return
        snapshot = self.membership.snapshot()
        for rid, view in snapshot.items():
            self._metrics.set_gauge(
                "app_router_replica_state",
                float(ms.STATE_VALUES.get(view["state"], ms.STATE_VALUES[ms.DOWN])),
                replica=rid,
            )
        self._metrics.set_gauge(
            "app_router_queue_wait_seconds",
            self.membership.aggregate_queue_wait(),
        )

    # -- routing ---------------------------------------------------------------
    def _is_interactive(self, tenant: str | None) -> bool:
        """True when ``tenant`` resolves to the interactive deadline
        class in the wired TenantRegistry. No registry (or a lookup
        failure) means the class is unknown — no steering, never a
        routing error."""
        if self._tenants is None:
            return False
        try:
            policy = self._tenants.policy(tenant)
        except Exception:
            return False
        return getattr(policy, "deadline_class", None) == "interactive"

    def _candidates_for(self, prompt: Any,
                        role: str | None = None,
                        adapter_id: str | None = None,
                        tenant: str | None = None) -> tuple[list[str], bool]:
        """Ordered candidate replicas for a new request: the prefix-
        affine replica first (when healthy and under the spill bound),
        then every other routable replica by least estimated wait.
        ``role`` restricts the pool to one disaggregation phase (the
        affinity ring is built over that pool, so shared prefixes keep
        landing on the same prefill replica's chunk cache).
        ``adapter_id`` joins the affinity key: same prompt under two
        adapters is two cache chains (the keys carry the adapter id), and
        same-adapter traffic pinning to one replica keeps that adapter
        device-resident there instead of thrashing every table in the
        tier (serving/lora.py). Returns (candidates, spilled)."""
        routable = self.membership.candidates(role=role)
        if not routable:
            return [], False
        key = prefix_affinity_key(prompt, self.config.affinity_prefix_tokens)
        if adapter_id:
            key = key + adapter_id.encode("utf-8")
        affine = self._ring_for(routable).lookup(key)
        spilled = False
        if affine in routable:
            wait, _depth = self.membership.load_of(affine)
            cap = self.config.spill_wait_s
            hbm_cap = self.config.spill_hbm_frac
            _kv_free, hbm_free = self.membership.headroom_of(affine)
            if (cap > 0 and wait > cap) or (
                hbm_cap > 0 and hbm_free is not None and hbm_free < hbm_cap
            ):
                # load-aware spill: the affine replica is healthy but
                # queued past the bound — or its heartbeat reports real
                # HBM pressure (device-telemetry hbm_free_frac below the
                # floor): one cold prefill elsewhere beats queueing
                # behind its backlog or OOMing its pools
                routable = [r for r in routable if r != affine] + [affine]
                spilled = True
            else:
                routable = [affine] + [r for r in routable if r != affine]
        if tenant is not None and self._is_interactive(tenant):
            # reclamation-aware steering: interactive-class traffic
            # prefers on-demand capacity — a preemptible replica can be
            # noticed away mid-stream, and an interactive SLO has no
            # budget for the resulting retry. Stable partition: the
            # affinity/spill order is preserved within each half, and a
            # pure-preemptible pool routes normally (steering picks
            # among candidates, it never shrinks the set).
            on_demand = [
                r for r in routable
                if not self.membership.is_preemptible(r)
            ]
            if on_demand and len(on_demand) < len(routable):
                back = set(on_demand)
                routable = on_demand + [
                    r for r in routable if r not in back
                ]
        return routable, spilled

    def submit(
        self,
        prompt: str | list[int],
        *,
        deadline: float | None = None,
        stream_cb: Callable[[int, str, bool], None] | None = None,
        trace_ctx: Any = None,
        **kw: Any,
    ) -> Any:
        """Route a request to a replica; returns a Future resolving to
        that replica's GenerationResult (annotated with ``replica_id``).
        Mirrors ``ServingEngine.submit`` so transports can front a
        router exactly like an engine. The deadline is the caller's
        remaining budget in seconds; across failovers the ORIGINAL
        absolute deadline is preserved — a re-route never resets the
        clock. ``trace_ctx`` (or the caller's active span) parents the
        per-attempt router spans, and propagates to each replica —
        in-process directly, over the wire as a W3C ``traceparent``."""
        with self._req_mu:
            self._next_rid += 1
            rid = self._next_rid
        deadline_abs = (
            time.monotonic() + deadline
            if deadline is not None and deadline > 0 else None
        )
        req = _RouterRequest(
            rid, prompt, dict(kw), stream_cb, deadline_abs,
            trace_ctx=trace_ctx if trace_ctx is not None else current_span(),
        )
        # disaggregated tier (a prefill pool AND a decode pool are both
        # live): the request splits into a prefill phase + KV handoff +
        # decode phase — a separate path because the prefill future must
        # be awaited asynchronously, never on this caller thread
        present = self.membership.roles_present()
        if ms.ROLE_PREFILL in present and ms.ROLE_DECODE in present:
            return self._submit_disagg(req)
        candidates, spilled = self._candidates_for(
            prompt, adapter_id=kw.get("adapter_id"), tenant=kw.get("tenant")
        )
        if not candidates:
            with self._stats_mu:
                self.no_replica_total += 1
            raise ErrorServiceUnavailable(
                "no routable replica (all draining, wedged, or down)",
                retry_after=self.config.heartbeat_s,
            )
        candidates = self._idem_fast_path(kw.get("idempotency_key"),
                                          candidates)
        self._note_last_resort(candidates)
        if spilled:
            with self._stats_mu:
                self.spills_total += 1
        # register BEFORE dispatching: a microsecond-fast settlement runs
        # _settle (which pops this rid) from the attempt's done-callback —
        # inserting afterwards would re-add a finished request that no
        # path ever removes (a permanent leak per occurrence)
        with self._req_mu:
            self._requests[rid] = req
        last_error: Exception | None = None
        dispatched = False
        try:
            for replica_id in candidates:
                try:
                    self._submit_attempt(req, replica_id)
                except RETRIABLE_ERRORS as exc:
                    last_error = exc
                    continue
                dispatched = True
                self._arm_hedge(req)
                return req.future
            # every candidate refused at admission: clean retriable
            # error — the client (or the LB above us) decides when to
            # come back
            assert last_error is not None
            raise last_error
        finally:
            if not dispatched:
                # nothing owns this request: unregister it (any raise —
                # retriable walk exhausted, deadline, non-retriable —
                # lands here; a dispatched request is removed by _settle)
                with self._req_mu:
                    self._requests.pop(rid, None)

    # -- disaggregated prefill/decode routing ----------------------------------
    def _submit_disagg(self, req: _RouterRequest) -> Any:
        """Two-phase routing for a role-split tier (ROADMAP item 2,
        AIBrix arXiv:2504.03648): admit the prompt on a PREFILL replica
        with ``prefill_only`` (it computes the prompt KV into its prefix
        cache and retires — no decode slots burned), then, when the
        prefill future settles, admit the generation on a DECODE replica
        with ``handoff_from`` naming the prefill source — the decode
        engine pulls the KV chain over the PR 11 transfer machinery
        under the two-phase-commit handoff discipline (kv.handoff).

        Crash-safety is the headline invariant: EVERY failure in the
        prefill phase — no prefill candidate, admission refused, the
        source dying mid-prefill — degrades to the decode phase without
        a handoff hint, where the decode replica simply re-prefills
        (roles are policy, not capability). The client future settles
        exactly once either way."""
        req.phase_role = ms.ROLE_DECODE  # generation attempts (primary,
        # failover, hedge) must never land on a prefill-only replica
        with self._req_mu:
            self._requests[req.rid] = req
        registered = True
        try:
            candidates, _ = self._candidates_for(
                req.prompt, role=ms.ROLE_PREFILL,
                adapter_id=req.kw.get("adapter_id"),
                tenant=req.kw.get("tenant"),
            )
            prefill_fut = None
            for replica_id in candidates:
                try:
                    prefill_fut = self._prefill_attempt(req, replica_id)
                except RETRIABLE_ERRORS:
                    continue
                break
            if prefill_fut is None:
                # no prefill replica would take it: the decode pool
                # serves the whole generation (one replica, no handoff)
                self._degrade_handoff(req, "no-prefill-candidate")
                self._failover_pool.submit(self._decode_phase, req)
            registered = False  # a phase now owns the request
            return req.future
        finally:
            if registered:
                # nothing owns this request (the prefill walk raised
                # non-retriably, or the pool rejected the phase task):
                # unregister before the raise reaches the caller
                with self._req_mu:
                    self._requests.pop(req.rid, None)

    def _open_attempt(
        self, req: _RouterRequest, replica_id: str, kind: str
    ) -> tuple[Any, Any, float | None]:
        """The admission prologue shared by EVERY attempt kind (primary/
        failover/hedge/prefill): deadline gate, handle lookup, the
        ``router.route`` chaos seam and the attempt span. One
        implementation so the two attempt paths cannot drift. Returns
        (handle, span, remaining_deadline); raises the typed admission
        errors."""
        remaining = req.remaining()
        if remaining is not None and remaining <= 0:
            raise ErrorDeadlineExceeded(
                f"request {req.rid}: deadline passed before reaching a replica"
            )
        with self._handles_mu:
            handle = self._handles.get(replica_id)
        if handle is None:
            raise ErrorServiceUnavailable(
                f"replica {replica_id} has no handle", retry_after=1.0
            )
        chaos.maybe_fail("router.route")
        span = None
        if self._tracer is not None:
            span = self._tracer.start_span(
                "router.attempt", parent=req.trace_ctx, kind="client",
                activate=False,
            )
            span.set_attribute("request.id", req.rid)
            span.set_attribute("replica.id", replica_id)
            span.set_attribute("attempt.kind", kind)
        return handle, span, remaining

    def _count_route(self, replica_id: str) -> None:
        with self._stats_mu:
            self.routed_total += 1
            self.routes_by_replica[replica_id] = (
                self.routes_by_replica.get(replica_id, 0) + 1
            )

    # -- HA plane (docs/robustness.md "The HA plane") ---------------------------
    _IDEM_ROUTES_CAP = 4096

    def _idem_fast_path(self, idempotency_key: Any,
                        candidates: list[str]) -> list[str]:
        """Reorder ``candidates`` so a keyed duplicate lands on the
        replica that (this router believes) owns the key's live request —
        one hop instead of a walk. The ``router.claim`` chaos seam sits
        on the lookup: a fault here degrades to the UNORDERED walk, and
        the replica-side registry still guarantees exactly-once (the
        point exists precisely to prove the fast path is not
        load-bearing)."""
        if not idempotency_key:
            return candidates
        try:
            chaos.maybe_fail("router.claim")
        except ChaosFault:
            return candidates
        with self._idem_mu:
            owner = self._idem_routes.get(str(idempotency_key))
        if owner and owner in candidates:
            return [owner] + [c for c in candidates if c != owner]
        return candidates

    def _record_idem_route(self, idempotency_key: Any,
                           replica_id: str) -> None:
        if not idempotency_key:
            return
        key = str(idempotency_key)
        with self._idem_mu:
            self._idem_routes.pop(key, None)
            self._idem_routes[key] = replica_id
            while len(self._idem_routes) > self._IDEM_ROUTES_CAP:
                # FIFO-ish bound (dict preserves insertion order): the
                # hint only matters for the key's in-flight window
                self._idem_routes.pop(next(iter(self._idem_routes)))

    def _attempt_kwargs(self, req: _RouterRequest,
                        replica_id: str) -> dict[str, Any]:
        """Per-attempt kwargs: the request's kw with ``fence_epoch``
        re-stamped from THIS router's membership view of THIS replica.
        Re-stamping per attempt (not per request) is what makes
        ``ErrorStaleEpoch`` retriable at the router: the failover
        attempt carries the refreshed fence, so a router that lagged a
        warm restart self-heals in one re-route instead of surfacing
        409 to the client. A replica whose heartbeat never carried an
        epoch (older replica, pre-beat registration) is not fenced."""
        kw = dict(req.kw)
        epoch = self.membership.epoch_of(replica_id)
        if epoch:
            kw["fence_epoch"] = epoch
        return kw

    def _note_last_resort(self, candidates: list[str]) -> None:
        """A route about to dispatch into a SUSPECT-only pool (no UP
        candidate anywhere): best-effort routing, loud in metrics — the
        operator's first signal that the tier is coasting on replicas
        that stopped heartbeating (satellite of the HA plane; mirrors
        health_check's DEGRADED)."""
        if any(
            self.membership.state_of(rid) == ms.UP for rid in candidates
        ):
            return
        with self._stats_mu:
            self.last_resort_routes_total += 1
        if self._metrics is not None:
            self._metrics.increment_counter(
                "app_router_last_resort_routes_total"
            )

    def resume(self, idempotency_key: str, *, last_seq: int = 0,
               stream_cb: Any = None, deadline: float | None = None) -> Any:
        """Re-attach to a keyed stream SOMEWHERE in the tier: the
        idempotency-route hint first, then every other routable replica
        — each attempt fence-stamped from membership. This is the
        survivor-router path after an active router dies mid-stream: the
        generation is still running on its replica; only the router-side
        subscription died with the router. 404 from a replica means
        "never saw the key, or its replay window evicted" — keep
        walking; when NO replica knows the key, the 404 propagates and
        the client falls back to a keyed submit (which dedups safely).
        ``stream_cb`` is the 4-arg resumable wire
        ``(seq, token_id, piece, done)``."""
        candidates = self.membership.candidates()
        with self._idem_mu:
            owner = self._idem_routes.get(str(idempotency_key))
        if owner and owner in candidates:
            candidates = [owner] + [c for c in candidates if c != owner]
        if not candidates:
            raise ErrorServiceUnavailable(
                "no routable replica to resume on",
                retry_after=self.config.heartbeat_s,
            )
        last_error: Exception | None = None
        for replica_id in candidates:
            with self._handles_mu:
                handle = self._handles.get(replica_id)
            if handle is None or not hasattr(handle, "resume"):
                continue
            epoch = self.membership.epoch_of(replica_id)
            try:
                future = handle.resume(
                    idempotency_key, last_seq=last_seq,
                    stream_cb=stream_cb, fence_epoch=epoch or None,
                    deadline=deadline,
                )
            except ErrorEntityNotFound as exc:
                last_error = exc
                continue
            except RETRIABLE_ERRORS as exc:
                last_error = exc
                continue
            self._record_idem_route(idempotency_key, replica_id)
            return future
        raise last_error if last_error is not None else ErrorEntityNotFound(
            "idempotency_key", str(idempotency_key)
        )

    def _prefill_attempt(self, req: _RouterRequest, replica_id: str) -> Any:
        """Admit the prefill phase on one prefill replica. Raises the
        replica's admission error (the caller's candidate walk decides);
        once admitted, the settlement callback drives the decode phase."""
        handle, span, remaining = self._open_attempt(req, replica_id, "prefill")
        submitted = False
        try:
            kw = {
                k: v for k, v in req.kw.items()
                if k in ("temperature", "top_k", "top_p", "priority",
                         "adapter_id", "tenant")
            }
            # the fence rides the prefill phase too — a prefill stamped
            # against a warm-restarted replica must not feed a handoff
            # hint pointing at a cache generation that no longer exists.
            # The idempotency key deliberately does NOT: the prefill is
            # an internal phase, keying it would dedup against the real
            # generation.
            epoch = self.membership.epoch_of(replica_id)
            if epoch:
                kw["fence_epoch"] = epoch
            prefill_fut = handle.submit(
                req.prompt, deadline=remaining, prefill_only=True,
                max_new_tokens=1,
                trace_ctx=span if span is not None else req.trace_ctx,
                **kw,
            )
            submitted = True
        finally:
            if not submitted and span is not None:
                span.set_attribute("attempt.outcome", "admission-failed")
                span.end()
        with req.mu:
            req.tried.append(replica_id)
            req.prefill_attempt = (replica_id, prefill_fut, span)
        self._count_route(replica_id)
        prefill_fut.add_done_callback(
            lambda f: self._on_prefill_done(req, replica_id, f)
        )
        return prefill_fut

    def _on_prefill_done(self, req: _RouterRequest, replica_id: str,
                         prefill_fut: Any) -> None:
        """The prefill phase settled (on the prefill replica's settlement
        thread): record the outcome, then hand the decode phase to the
        failover pool — never submit into a replica from a settlement
        callback."""
        exc = prefill_fut.exception()
        with req.mu:
            attempt = req.prefill_attempt
            span = attempt[2] if attempt is not None else None
            req.prefill_attempt = None
        if span is not None:
            span.set_attribute(
                "attempt.outcome",
                "ok" if exc is None else f"failed:{type(exc).__name__}",
            )
            span.end()
        if req.future.done():
            return  # canceled / expired while prefilling: settled already
        if req.canceled:
            # canceled during the prefill phase: the decode phase must
            # never run (it would serve a full generation the client
            # already walked away from). Mirror the unified path's
            # semantics — the client future settles with the cancel
            # result; a result still labeled "handoff" (cancel raced the
            # prefill's completion) is relabeled, it must not leak the
            # internal phase marker.
            if exc is None:
                result = prefill_fut.result()
                try:
                    if getattr(result, "finish_reason", None) == "handoff":
                        result.finish_reason = "cancel"
                except Exception:
                    pass  # frozen result types settle unlabeled
                self._settle(req, result=result, replica_id=replica_id)
            else:
                self._settle(req, error=exc, replica_id=replica_id)
            return
        if exc is None:
            # the handoff hint: the decode replica pulls the KV chain
            # directly from this source (no heartbeat-advertisement wait)
            req.kw["handoff_from"] = replica_id
            with self._stats_mu:
                self.handoffs_total += 1
        else:
            # source died mid-prefill (or refused late): the decode
            # replica re-prefills from the prompt — degraded, never lost
            self._degrade_handoff(req, f"prefill-failed:{type(exc).__name__}")
        try:
            self._failover_pool.submit(self._decode_phase, req)
        except RuntimeError:
            self._settle(req, error=ErrorServiceUnavailable(
                "router stopped during handoff; retry", retry_after=1.0,
            ), replica_id=None)

    def _degrade_handoff(self, req: _RouterRequest, reason: str) -> None:
        req.kw.pop("handoff_from", None)
        with self._stats_mu:
            self.handoff_degraded_total += 1
        if self._logger is not None:
            self._logger.debug(
                f"request {req.rid}: handoff degraded to re-prefill ({reason})"
            )

    def _decode_phase(self, req: _RouterRequest) -> None:
        """Admit the generation on the decode pool (runs on the failover
        pool). Mirrors submit's candidate walk; every exit settles the
        client future or hands ownership to the attempt machinery."""
        try:
            if req.future.done():
                return
            candidates, _ = self._candidates_for(
                req.prompt, role=req.phase_role,
                tenant=req.kw.get("tenant"),
            )
            with req.mu:
                tried = set(req.tried)
            # prefer untried decode replicas, but a handoff source that
            # is ALSO the only decode candidate may serve (tried only
            # covers this request's prefill walk, not failures)
            ordered = [c for c in candidates if c not in tried] or candidates
            if ordered:
                self._note_last_resort(ordered)
            last_error: Exception = ErrorServiceUnavailable(
                "no routable decode replica", retry_after=self.config.heartbeat_s,
            )
            for replica_id in ordered:
                try:
                    self._submit_attempt(req, replica_id)
                except RETRIABLE_ERRORS as exc:
                    last_error = exc
                    continue
                except ErrorDeadlineExceeded as exc:
                    self._settle(req, error=exc, replica_id=None)
                    return
                self._arm_hedge(req)
                return
            self._settle(req, error=last_error, replica_id=None)
        # gofrlint: disable=router-retry-untyped -- no retry happens here:
        # an unexpected raise would vanish into the failover pool and
        # strand the client future forever; settle it instead
        except BaseException as exc:
            self._settle(req, error=exc, replica_id=None)

    def _submit_attempt(self, req: _RouterRequest, replica_id: str,
                        kind: str = "primary") -> Any:
        """One submission to one replica. Raises the replica's admission
        error; the callers decide whether it is retriable (submit's
        candidate loop / the failover path). ``kind`` annotates the
        attempt span: primary, failover, or hedge."""
        handle, span, remaining = self._open_attempt(req, replica_id, kind)
        cb = self._attempt_cb(req, replica_id)
        submitted = False
        try:
            replica_future = handle.submit(
                req.prompt, deadline=remaining, stream_cb=cb,
                trace_ctx=span if span is not None else req.trace_ctx,
                **self._attempt_kwargs(req, replica_id),
            )
            submitted = True
        finally:
            # finally, not an except clause: the router-retry-untyped rule
            # pins WHICH errors may be handled here, and an admission
            # failure of any type must not leak the attempt span
            if not submitted and span is not None:
                span.set_attribute("attempt.outcome", "admission-failed")
                span.end()
        with req.mu:
            req.tried.append(replica_id)
            req.live[replica_id] = replica_future
            if span is not None:
                req.spans[replica_id] = span
        self._count_route(replica_id)
        self._record_idem_route(req.kw.get("idempotency_key"), replica_id)
        if req.canceled:
            # a cancel that landed in the async gap before this attempt
            # registered (the disaggregated decode phase runs off the
            # prefill settlement, after the caller already holds the
            # rid): nothing was live to cancel then — cancel NOW, and
            # the replica's cancel contract settles the attempt with the
            # cancel result through the normal done-callback
            self._cancel_attempt(replica_id, replica_future)
        replica_future.add_done_callback(
            lambda f: self._on_attempt_done(req, replica_id, f)
        )
        return replica_future

    def _attempt_cb(self, req: _RouterRequest,
                    replica_id: str) -> Callable[[int, str, bool], None]:
        """Per-attempt stream wrapper: the first token claims the stream
        for this attempt (canceling any hedge twin before IT streams);
        only the claimed winner's tokens reach the client — exactly-once
        on the wire, whatever the replicas do."""

        def cb(token_id: int, piece: str, done: bool) -> None:
            losers: list[tuple[str, Any]] = []
            with req.mu:
                if done and req.winner is None:
                    # terminal frame of an attempt that never streamed.
                    # The engine's failure contract settles the future
                    # FIRST and fires the done frame after — by now the
                    # attempt is out of req.live and the failover path
                    # owns this request: the dead attempt's frame must
                    # neither claim the stream nor cancel the re-route.
                    # (Same guard covers a frame racing registration.)
                    fut = req.live.get(replica_id)
                    if fut is None or (
                        fut.done() and fut.exception() is not None
                    ):
                        return
                if req.winner is None:
                    # first stream event claims the client-visible stream
                    # for this attempt
                    req.winner = replica_id
                    if not done and req.first_token_at is None:
                        req.first_token_at = time.monotonic()
                        self._observe_ttft(
                            req.first_token_at - req.submitted_at
                        )
                    losers = [
                        (lrid, lfut) for lrid, lfut in req.live.items()
                        if lrid != replica_id
                    ]
                is_winner = req.winner == replica_id
            for lrid, lfut in losers:
                self._cancel_attempt(lrid, lfut)
            if is_winner and req.stream_cb is not None:
                req.stream_cb(token_id, piece, done)

        return cb

    def _cancel_attempt(self, replica_id: str, replica_future: Any) -> None:
        with self._handles_mu:
            handle = self._handles.get(replica_id)
        if handle is None:
            return
        try:
            handle.cancel(replica_future.request_id)
        except Exception:
            pass  # the loser may have terminated on its own already

    def _on_attempt_done(self, req: _RouterRequest, replica_id: str,
                         replica_future: Any) -> None:
        """Terminal event from one replica attempt. Runs on the
        replica's settlement thread: decide, then hand any re-route to
        the failover pool — never re-enter a (possibly wedged) replica
        from here."""
        with req.mu:
            req.live.pop(replica_id, None)
            span = req.spans.pop(replica_id, None)
            live_others = bool(req.live)
            winner = req.winner
        exc = replica_future.exception()
        if span is not None:
            span.set_attribute(
                "attempt.outcome",
                "ok" if exc is None else f"failed:{type(exc).__name__}",
            )
            span.end()
        if exc is None:
            result = replica_future.result()
            with req.mu:
                claimed = req.winner is None or req.winner == replica_id
                if claimed and req.winner is None:
                    req.winner = replica_id
            if not claimed:
                return  # a canceled hedge loser completing: drop it
            self._settle(req, result=result, replica_id=replica_id)
            return
        # failed attempt —
        if req.future.done():
            # the request already concluded — the winner settled the
            # client future (or cancel/deadline did). A canceled hedge
            # twin's transport failing AFTER that is bookkeeping, not a
            # failover: it must bump no counter and schedule no re-route
            # (its span was ended above — nothing leaks).
            return
        if winner == replica_id:
            # the client-visible stream died mid-flight: this attempt
            # claimed the stream (tokens crossed the wire), so a silent
            # re-run would duplicate a non-idempotent stream. Clean typed
            # error; the client holds the partial output and the retry
            # decision. NOTE: winner identity, not a token count — a
            # LOSING hedge twin failing while the winner streams must
            # fall through to the live_others check below, never settle.
            self._settle(req, error=exc, replica_id=replica_id)
            return
        if live_others:
            return  # the hedge twin is still running: it IS the failover
        if not isinstance(exc, RETRIABLE_ERRORS):
            self._settle(req, error=exc, replica_id=replica_id)
            return
        remaining = req.remaining()
        if remaining is not None and remaining <= 0:
            self._settle(
                req,
                error=ErrorDeadlineExceeded(
                    f"request {req.rid}: deadline passed during failover"
                ),
                replica_id=replica_id,
            )
            return
        if req.failovers >= self.config.max_failovers or req.canceled:
            self._settle(req, error=exc, replica_id=replica_id)
            return
        req.failovers += 1
        with self._stats_mu:
            self.failovers_total += 1
        if self._metrics is not None:
            self._metrics.increment_counter("app_router_failovers_total")
        try:
            self._failover_pool.submit(self._failover, req, exc)
        except RuntimeError:
            # router stopped between the failure and the re-route: the
            # client must still get its terminal — never a stranded future
            self._settle(req, error=exc, replica_id=replica_id)

    def _failover(self, req: _RouterRequest, cause: Exception) -> None:
        """Re-route after a pre-first-token replica failure: next
        candidate, original absolute deadline, tried replicas excluded
        (a replica that just failed this request does not get it back
        before the untried ones)."""
        try:
            # phase_role restricts the re-route to the decode pool on a
            # disaggregated tier: a failover must never land generation
            # work on a prefill-only replica
            candidates, _ = self._candidates_for(
                req.prompt, role=req.phase_role,
                tenant=req.kw.get("tenant"),
            )
            with req.mu:
                tried = set(req.tried)
            ordered = [c for c in candidates if c not in tried] or candidates
            last_error: Exception = cause
            for replica_id in ordered:
                try:
                    self._submit_attempt(req, replica_id, kind="failover")
                    return
                except RETRIABLE_ERRORS as exc:
                    last_error = exc
                    continue
                except ErrorDeadlineExceeded as exc:
                    self._settle(req, error=exc, replica_id=None)
                    return
            self._settle(req, error=last_error, replica_id=None)
        # gofrlint: disable=router-retry-untyped -- no retry happens here:
        # an unexpected raise (a handle whose pool was closed mid-shutdown
        # raises RuntimeError) would vanish into the failover pool and
        # strand the client future forever; settle it instead
        except BaseException as exc:
            self._settle(req, error=exc, replica_id=None)

    # -- hedging ---------------------------------------------------------------
    _TTFT_METRIC = "app_request_ttft_seconds"
    _TTFT_LABELS = {"source": "router"}

    def _ttft_histogram(self) -> Histogram:
        """The shared registered TTFT histogram (container/container.py)
        when a metrics manager is wired; a private instance of the SAME
        instrument type otherwise — either way ``percentile()`` is the
        one percentile implementation (no private sample ring)."""
        if self._metrics is not None:
            inst = self._metrics.get(self._TTFT_METRIC)
            if isinstance(inst, Histogram):
                return inst
        if self._private_ttft is None:
            self._private_ttft = Histogram(
                self._TTFT_METRIC, "router-observed time to first token"
            )
        return self._private_ttft

    def hedge_delay(self) -> float:
        """The armed hedge delay: the configured floor, raised to the
        observed TTFT p99 once enough samples exist (hedging inside
        normal first-token latency doubles prefill load for nothing)."""
        base = self.config.hedge_delay_s
        if base <= 0:
            return 0.0
        if not self.config.hedge_from_p99:
            return base
        hist = self._ttft_histogram()
        _, n = hist.snapshot(self._TTFT_LABELS)
        if n < 20:
            return base
        return max(base, hist.percentile(0.99, self._TTFT_LABELS))

    def _observe_ttft(self, seconds: float) -> None:
        # source=router keeps the router's submit→first-token series
        # distinct from the engine's admission-side TTFT in the shared
        # histogram — the hedge floor must key on what the CLIENT waits
        self._ttft_histogram().record(seconds, dict(self._TTFT_LABELS))

    def _arm_hedge(self, req: _RouterRequest) -> None:
        delay = self.hedge_delay()
        if delay <= 0:
            return

        def fire() -> None:
            try:
                self._failover_pool.submit(self._hedge, req)
            except RuntimeError:
                pass  # router stopped: the primary attempt stands alone

        timer = threading.Timer(delay, fire)
        timer.daemon = True
        req.hedge_timer = timer
        timer.start()

    def _hedge(self, req: _RouterRequest) -> None:
        """Hedge the prefill admission: when the first token still
        hasn't arrived, admit the same request on the next-best replica.
        First token wins; the loser is canceled before it streams. The
        hedge twin inherits the ORIGINAL deadline, like any re-route."""
        with req.mu:
            if (
                req.first_token_at is not None
                or req.future.done()
                or req.canceled
                or not req.live
            ):
                return
            tried = set(req.tried)
        candidates, _ = self._candidates_for(
            req.prompt, role=req.phase_role, tenant=req.kw.get("tenant")
        )
        for replica_id in candidates:
            if replica_id in tried:
                continue
            try:
                self._submit_attempt(req, replica_id, kind="hedge")
            except RETRIABLE_ERRORS:
                continue
            except ErrorDeadlineExceeded:
                return
            with self._stats_mu:
                self.hedges_total += 1
            if self._metrics is not None:
                self._metrics.increment_counter("app_router_hedges_total")
            return

    # -- settlement ------------------------------------------------------------
    def _settle(self, req: _RouterRequest, *, result: Any = None,
                error: Exception | None = None,
                replica_id: str | None = None) -> None:
        """Resolve the client future exactly once; cancel the hedge
        timer and any still-live twin attempts."""
        with req.mu:
            if req.future.done():
                return
            timer = req.hedge_timer
            req.hedge_timer = None
            leftovers = list(req.live.items())
            req.live = {}
            stray_spans = list(req.spans.values())
            req.spans = {}
            prefill_attempt = req.prefill_attempt
            req.prefill_attempt = None
        if timer is not None:
            timer.cancel()
        if prefill_attempt is not None:
            # a handoff prefill still in flight when the request settles
            # (canceled / expired): stop it burning prefill compute, and
            # end its span (its done-callback no-ops once the future is
            # settled here)
            prid, pfut, pspan = prefill_attempt
            self._cancel_attempt(prid, pfut)
            if pspan is not None:
                pspan.end()
        for span in stray_spans:
            # normally ended by each attempt's done-callback; a handle
            # whose future never settles must not leak its span
            span.end()
        for lrid, lfut in leftovers:
            self._cancel_attempt(lrid, lfut)
        with self._req_mu:
            self._requests.pop(req.rid, None)
        if error is not None:
            req.future.set_exception(error)
            return
        if result is not None and replica_id is not None:
            try:
                result.replica_id = replica_id  # terminal attribution
            except Exception:
                pass  # frozen/slotted result types keep working unlabeled
        req.future.set_result(result)

    def cancel(self, request_id: int) -> None:
        """Cancel a routed request on every replica it is live on."""
        with self._req_mu:
            req = self._requests.get(request_id)
        if req is None:
            return
        with req.mu:
            req.canceled = True
            live = list(req.live.items())
            prefill_attempt = req.prefill_attempt
        for replica_id, replica_future in live:
            self._cancel_attempt(replica_id, replica_future)
        if prefill_attempt is not None:
            self._cancel_attempt(prefill_attempt[0], prefill_attempt[1])

    # -- observability ---------------------------------------------------------
    def health_check(self) -> dict[str, Any]:
        routable = self.membership.candidates()
        if not routable:
            status = "DOWN"
        elif any(
            self.membership.state_of(rid) == ms.UP for rid in routable
        ):
            status = "UP"
        else:
            # only SUSPECT (last-resort) candidates: routing is
            # best-effort — loud in health, not a soothing UP
            status = "DEGRADED"
        return {
            "status": status,
            "details": {
                "replicas": self.membership.snapshot(),
                "routable": routable,
                "routed_total": self.routed_total,
                "failovers_total": self.failovers_total,
                "hedges_total": self.hedges_total,
            },
        }

    def _counters(self) -> dict[str, Any]:
        with self._stats_mu:
            return {
                "routed_total": self.routed_total,
                "failovers_total": self.failovers_total,
                "hedges_total": self.hedges_total,
                "spills_total": self.spills_total,
                "no_replica_total": self.no_replica_total,
                "handoffs_total": self.handoffs_total,
                "handoff_degraded_total": self.handoff_degraded_total,
                "last_resort_routes_total": self.last_resort_routes_total,
                "routes_by_replica": dict(self.routes_by_replica),
            }

    def routerz(self) -> dict[str, Any]:
        """The ``/routerz`` health view: membership, routing counters,
        and the live knob values — everything an operator needs to see
        why a request went where it went."""
        return {
            "replicas": self.membership.snapshot(),
            "routable": self.membership.candidates(),
            "roles_present": sorted(self.membership.roles_present()),
            "aggregate_queue_wait_s": round(
                self.membership.aggregate_queue_wait(), 4
            ),
            "prefix_index": self.prefix_index.snapshot(),
            "counters": self._counters(),
            "config": {
                "heartbeat_s": self.config.heartbeat_s,
                "suspect_after_s": self.config.suspect_after_s,
                "down_after_s": self.config.down_after_s,
                "spill_wait_s": self.config.spill_wait_s,
                "affinity_prefix_tokens": self.config.affinity_prefix_tokens,
                "vnodes": self.config.vnodes,
                "max_failovers": self.config.max_failovers,
                "spill_hbm_frac": self.config.spill_hbm_frac,
                "hedge_delay_s": self.config.hedge_delay_s,
                "hedge_delay_armed_s": round(self.hedge_delay(), 4),
            },
        }

"""Engine supervision: the watchdog + self-healing warm-restart plane.

The scarcest resource at the north-star operating point is the single
engine thread driving the TPU — and before this module existed, a hung
PJRT dispatch or a poisoned executable was only *discovered* at stop()
time: a WEDGED engine stayed dead until the whole process was replaced,
even though ``ServingEngine.from_checkpoint`` already proved a warm
restart is cheap. :class:`EngineSupervisor` owns the engine lifecycle the
way the pubsub ``SubscriptionManager`` owns consumer loops
(subscriber.py): detect, restart with a budget, park loudly when the
budget is spent.

Detection — the engine loop stamps a monotonic heartbeat every scheduler
iteration; the watchdog thread reads three signals:

- **stall**: ``heartbeat_age() > TPU_ENGINE_STALL_S`` — a dispatch that
  will never return (no exception will ever surface; only time can tell);
- **crash**: the loop thread died with ``_running`` still set (an escape
  past the per-step recovery, e.g. a C-extension abort);
- **poison storm**: ``device_poisonings`` grew by ``poison_threshold``
  since the last restart — the in-place KV rebuild (``_fail_all``) is not
  sticking, so rebuilding buffers under the same executable is thrashing.

Health states ``UP → SUSPECT → RESTARTING → (UP | WEDGED)`` surface
through ``container.health`` (the engine's health_check embeds
``snapshot()``), and three metrics: ``app_engine_restarts_total``,
``app_engine_heartbeat_age_seconds``, ``app_engine_supervisor_state``
(0 UP / 1 SUSPECT / 2 RESTARTING / 3 WEDGED).

Restart budget with earn-back (mirrors the consumer plane): up to
``TPU_ENGINE_RESTART_BUDGET`` consecutive restarts; a restart followed by
``TPU_ENGINE_RESTART_RESET_S`` of healthy running earns the budget back.
One more detection past the budget parks the engine WEDGED — stopped,
loud in health, never flapping — because an engine that needs its Nth
restart in a row has a fault no restart will fix.
"""

from __future__ import annotations

import threading
import time
from typing import Any

UP = "UP"
SUSPECT = "SUSPECT"
RESTARTING = "RESTARTING"
WEDGED = "WEDGED"

# gauge encoding for app_engine_supervisor_state
STATE_VALUES = {UP: 0, SUSPECT: 1, RESTARTING: 2, WEDGED: 3}

DEFAULT_STALL_S = 5.0
DEFAULT_COMPILE_GRACE_S = 120.0
DEFAULT_RESTART_BUDGET = 3
DEFAULT_RESTART_RESET_S = 60.0
DEFAULT_POISON_THRESHOLD = 3


def _knob(config: Any, key: str, default: float) -> float:
    if config is None:
        return default
    return float(config.get_or_default(key, str(default)))


class EngineSupervisor:
    """Owns a :class:`ServingEngine`'s lifecycle: start it, watch it,
    warm-restart it, park it WEDGED when restarts stop helping.

    ``start()``/``drain()``/``stop()`` are the lifecycle surface handlers
    wire instead of the engine's own (serving/handlers.py) — the watchdog
    stands down FIRST on the way out, so a deliberate drain is never
    "detected" as a stall mid-teardown.
    """

    def __init__(
        self,
        engine: Any,
        *,
        config: Any = None,
        metrics: Any = None,
        logger: Any = None,
        stall_s: float | None = None,
        compile_grace_s: float | None = None,
        restart_budget: int | None = None,
        restart_reset_s: float | None = None,
        poison_threshold: int | None = None,
        poll_s: float | None = None,
        join_timeout: float = 5.0,
    ) -> None:
        self.engine = engine
        self._metrics = metrics if metrics is not None else engine._metrics
        self._logger = logger if logger is not None else engine._logger
        self.stall_s = (
            stall_s if stall_s is not None
            else _knob(config, "TPU_ENGINE_STALL_S", DEFAULT_STALL_S)
        )
        # a first dispatch of a signature jit-compiles: minutes of silence
        # that IS progress. While the engine reports in_cold_dispatch the
        # stall threshold widens to this — a hang during a first compile is
        # still caught, just on the compile budget instead of stall_s.
        self.compile_grace_s = (
            compile_grace_s if compile_grace_s is not None
            else _knob(config, "TPU_ENGINE_COMPILE_GRACE_S",
                       DEFAULT_COMPILE_GRACE_S)
        )
        self.restart_budget = int(
            restart_budget if restart_budget is not None
            else _knob(config, "TPU_ENGINE_RESTART_BUDGET", DEFAULT_RESTART_BUDGET)
        )
        self.restart_reset_s = (
            restart_reset_s if restart_reset_s is not None
            else _knob(config, "TPU_ENGINE_RESTART_RESET_S", DEFAULT_RESTART_RESET_S)
        )
        self.poison_threshold = int(
            poison_threshold if poison_threshold is not None
            else _knob(config, "TPU_ENGINE_POISON_THRESHOLD", DEFAULT_POISON_THRESHOLD)
        )
        # poll often enough that detection latency stays well inside the
        # stall budget, without busy-spinning on tiny test thresholds
        self.poll_s = (
            poll_s if poll_s is not None else max(self.stall_s / 4.0, 0.01)
        )
        self.join_timeout = join_timeout

        self.state = UP
        self.restarts = 0  # completed warm restarts, lifetime
        self.failed_restarts = 0
        self.last_reason: str | None = None
        self._consecutive = 0
        self._last_restart_t: float | None = None
        self._poison_mark = engine.device_poisonings
        self._poison_seen = engine.device_poisonings
        self._last_poison_t: float | None = None
        self._retry_pending = False  # a failed restart left the engine down
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        engine._supervisor = self  # health backref (engine.health_check)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        """Start the engine (if needed) and the watchdog thread."""
        self.engine.start()
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self.state = UP
        self._thread = threading.Thread(
            target=self._watch, name="engine-supervisor", daemon=True
        )
        self._thread.start()

    def stop(self, join_timeout: float = 10.0) -> None:
        self._halt_watchdog()
        self.engine.stop(join_timeout=join_timeout)

    def drain(self, deadline_s: float | None = None, *,
              join_timeout: float = 10.0) -> bool:
        """Watchdog stands down first, then the engine drains: the drain's
        deliberate quiet period must not read as a stall."""
        self._halt_watchdog()
        return self.engine.drain(deadline_s, join_timeout=join_timeout)

    def _halt_watchdog(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=10.0)
        # the watchdog may have died between claiming SUSPECT/RESTARTING
        # and standing down (e.g. a failed restart left the retry pending
        # when stop()/drain() interrupted) — health ranks those claims
        # above the engine's own DOWN/DRAINING, so a stale one would
        # report a cleanly stopped engine as RESTARTING forever
        self._stand_down()

    # ------------------------------------------------------------- inspection
    def snapshot(self) -> dict[str, Any]:
        return {
            "state": self.state,
            "restarts": self.restarts,
            "failed_restarts": self.failed_restarts,
            "consecutive_restarts": self._consecutive,
            "restart_budget": self.restart_budget,
            "stall_s": self.stall_s,
            "compile_grace_s": self.compile_grace_s,
            "last_reason": self.last_reason,
        }

    def health_check(self) -> dict[str, Any]:
        """The engine's health (which embeds this supervisor's snapshot and
        lets WEDGED/RESTARTING/SUSPECT outrank its own states)."""
        return self.engine.health_check()

    # ------------------------------------------------------------- watchdog
    def _transition(self, state: str) -> None:
        if state != self.state:
            self.state = state
            if self._logger:
                self._logger.warn(f"engine supervisor: {state}"
                                  + (f" ({self.last_reason})"
                                     if self.last_reason and state != UP else ""))
        if self._metrics:
            self._metrics.set_gauge(
                "app_engine_supervisor_state", float(STATE_VALUES[state])
            )

    def _detect(self) -> str | None:
        """The SUSPECT verdict: which signal (if any) says the engine is no
        longer making progress."""
        eng = self.engine
        if eng.loop_crashed:
            return "loop thread died"
        if self._retry_pending:
            return "previous restart attempt failed"
        if not eng._running:
            return None  # stopped deliberately elsewhere; nothing to heal
        if eng.device_poisonings - self._poison_mark >= self.poison_threshold:
            return (
                f"device poisoned {eng.device_poisonings - self._poison_mark}"
                f" time(s) since last restart"
            )
        age = eng.heartbeat_age()
        limit = self.stall_s
        if getattr(eng, "in_cold_dispatch", False):
            limit = max(limit, self.compile_grace_s)
        if age > limit:
            return f"heartbeat stale {age:.2f}s (> {limit:g}s)"
        return None

    def _stand_down(self) -> None:
        """The engine's own lifecycle (drain/stop/wedge) owns the verdict
        now: mirror a wedge, otherwise drop any SUSPECT/RESTARTING claim
        so health reads the engine's DOWN/DRAINING directly."""
        if self.engine._wedged:
            self._transition(WEDGED)
        elif self.state in (SUSPECT, RESTARTING):
            self._transition(UP)

    def _watch(self) -> None:
        eng = self.engine
        while not self._stop.wait(self.poll_s):
            if eng._draining or eng._stop_requested or eng._wedged:
                # lifecycle owned elsewhere: the watchdog stands down. A
                # stale SUSPECT/RESTARTING must not outlive it — health
                # ranks those above the engine's own DOWN/DRAINING, so a
                # cleanly drained engine would report RESTARTING forever.
                self._stand_down()
                return
            if self._metrics and eng._running:
                self._metrics.set_gauge(
                    "app_engine_heartbeat_age_seconds", eng.heartbeat_age()
                )
            # poison-count decay: only a STORM (repeated poisonings with no
            # quiet window) means the in-place KV rebuild is not sticking.
            # Isolated, fully-healed poisonings spread over days must not
            # accumulate into a spurious restart of a healthy engine, so a
            # restart_reset_s of quiet rebases the mark — mirroring the
            # consecutive-restart earn-back.
            poisonings = eng.device_poisonings
            if poisonings != self._poison_seen:
                self._poison_seen = poisonings
                self._last_poison_t = time.monotonic()
            elif (
                self._last_poison_t is not None
                and time.monotonic() - self._last_poison_t
                >= self.restart_reset_s
            ):
                self._poison_mark = poisonings
            reason = self._detect()
            if reason is None:
                if self.state != UP:
                    self._transition(UP)
                elif self._metrics:
                    self._metrics.set_gauge("app_engine_supervisor_state", 0.0)
                if (
                    self._consecutive
                    and self._last_restart_t is not None
                    and time.monotonic() - self._last_restart_t
                    >= self.restart_reset_s
                ):
                    self._consecutive = 0  # healthy run earns the budget back
                continue
            self.last_reason = reason
            self._transition(SUSPECT)
            if self._consecutive >= self.restart_budget:
                self._park(reason)
                return  # parked: never flap
            self._restart(reason)

    def _restart(self, reason: str) -> None:
        eng = self.engine
        self._transition(RESTARTING)
        self._consecutive += 1
        self._last_restart_t = time.monotonic()
        if self._logger:
            self._logger.error(
                f"engine supervisor restarting ({reason}); attempt "
                f"{self._consecutive}/{self.restart_budget}"
            )
        try:
            ok = eng.warm_restart(join_timeout=self.join_timeout)
        except Exception as exc:
            ok = False
            if self._logger:
                self._logger.error(f"engine warm restart failed: {exc}")
        self._poison_mark = eng.device_poisonings
        if ok:
            self._retry_pending = False
            self.restarts += 1
            if self._metrics:
                self._metrics.increment_counter("app_engine_restarts_total")
            self._transition(UP)
        elif eng._draining or eng._stop_requested or eng._wedged:
            # drain/stop won the race mid-restart — exactly one winner;
            # clear the RESTARTING claim so health falls through to the
            # engine's own DOWN/DRAINING/WEDGED verdict
            self._stand_down()
            self._stop.set()
        else:
            self.failed_restarts += 1
            # the engine may be down with no crash flag now: remember that
            # the next tick must retry instead of reading "cleanly stopped"
            self._retry_pending = True

    def _park(self, reason: str) -> None:
        """Budget spent: stop the engine (native frees are skipped under a
        live thread, exactly like stop()'s wedge path), pin health to
        WEDGED, and stand down. A process manager replaces WEDGED
        replicas; the supervisor's job here is to be loud and still."""
        eng = self.engine
        if self._logger:
            self._logger.error(
                f"engine supervisor restart budget "
                f"({self.restart_budget}) spent ({reason}); parking WEDGED"
            )
        try:
            eng.stop(join_timeout=self.join_timeout)
        except Exception:
            pass
        eng._wedged = True  # even a clean join parks: restarts stopped helping
        self._transition(WEDGED)

"""Prompt-prefill (prefix) cache for the serving engine.

Implements the container ``Cache`` contract (container/datasources.py:
get/put/evict/stats — the TPU-build addition for KV-prefix reuse): the
engine keys an entry by (prefill bucket, prompt token ids) and stores
the prefill's outputs — last-position logits plus the K/V slabs — so a
REPEATED prompt skips the entire prefill forward pass and admits at
decode cost. System prompts, retried requests, and health probes are
the common repeat offenders; sampling params are NOT part of the key
(sampling happens after the cached logits).

Device memory per entry is one prompt-bucket of KV
(2 x L x bucket x Hkv x Dh weights-dtype; ~8 MB for an 8B model at
bucket 64), bounded by LRU eviction over ``max_entries``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Hashable


def _value_bytes(value: Any) -> int:
    total = 0
    for leaf in _tree_leaves(value):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def _tree_leaves(value: Any):
    if isinstance(value, (tuple, list)):
        for v in value:
            yield from _tree_leaves(v)
    elif isinstance(value, dict):
        for v in value.values():
            yield from _tree_leaves(v)
    else:
        yield value


class PrefixCache:
    """Thread-safe LRU keyed by arbitrary hashables. Values are pytrees
    of device arrays; eviction drops the reference and lets the device
    allocator reclaim the buffers.

    Eviction is bounded by BOTH entry count and cumulative bytes: entry
    sizes vary ~64x across prefill buckets (32..2048 tokens), so an
    entry cap alone cannot bound HBM — a workload of long repeated
    prompts would pin gigabytes beside the serving KV cache."""

    def __init__(self, max_entries: int = 32,
                 max_bytes: int = 256 * 1024 * 1024,
                 on_evict: Any = None) -> None:
        self.max_entries = max(1, max_entries)
        self.max_bytes = max_bytes
        self._entries: "OrderedDict[Hashable, Any]" = OrderedDict()
        self._sizes: dict[Hashable, int] = {}
        self._total_bytes = 0
        self._lock = threading.Lock()
        self._hits = 0
        self._misses = 0
        # capacity-eviction hook (the host-RAM spill tier,
        # serving/kv_spill.py): called OUTSIDE the lock with each
        # (key, value) the LRU dropped — the lock stays leaf-only
        self.on_evict = on_evict

    def get(self, key: Hashable) -> Any | None:
        with self._lock:
            value = self._entries.get(key)
            if value is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return value

    def put(self, key: Hashable, value: Any) -> None:
        size = _value_bytes(value)
        if size > self.max_bytes:
            # inserting-then-evicting would flush every useful entry to
            # make room for one that cannot fit anyway (code-review r4)
            return
        evicted: list[tuple[Hashable, Any]] = []
        with self._lock:
            if key in self._entries:
                self._total_bytes -= self._sizes.get(key, 0)
            self._entries[key] = value
            self._sizes[key] = size
            self._total_bytes += size
            self._entries.move_to_end(key)
            while self._entries and (
                len(self._entries) > self.max_entries
                or self._total_bytes > self.max_bytes
            ):
                old_key, old_value = self._entries.popitem(last=False)
                self._total_bytes -= self._sizes.pop(old_key, 0)
                evicted.append((old_key, old_value))
        if self.on_evict is not None:
            for old_key, old_value in evicted:
                try:
                    self.on_evict(old_key, old_value)
                except Exception:
                    pass  # the spill tier is advisory: a failed spill is a miss later

    def evict(self, key: Hashable) -> None:
        with self._lock:
            if self._entries.pop(key, None) is not None:
                self._total_bytes -= self._sizes.pop(key, 0)

    def keys(self) -> list[Hashable]:
        """Resident keys, least-recently-used first — the distributed
        prefix index advertises these (serving/prefix_index.py)."""
        with self._lock:
            return list(self._entries.keys())

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._sizes.clear()
            self._total_bytes = 0

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "max_entries": self.max_entries,
                "bytes": self._total_bytes,
                "max_bytes": self.max_bytes,
                "hits": self._hits,
                "misses": self._misses,
            }

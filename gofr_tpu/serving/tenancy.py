"""Per-tenant SLO classes: priority, deadline classes, token-rate budgets.

The serving plane treated every request as anonymous and equal — one
tenant's burst starved another, and nothing in the stack could even SAY
which tenant a request belonged to. This module is the policy half of the
multi-tenant plane (ROADMAP item 4, AIBrix arXiv:2504.03648; Gemma TPU
serving comparison, arXiv:2605.25645); the enforcement lives at three
existing layers:

1. **shed/admission** (``ServingEngine.submit``): a tenant over its
   token-rate budget is rejected in microseconds with 429 + Retry-After
   (the PR 3 shed contract — clients and routers already key on it), and
   a request with no explicit deadline inherits its class default so the
   expired-while-queued drop and mid-stream retire work for every tenant;
2. **step planning** (``serving/stepplan.py``): decode rows stay reserved
   first, and chunk-prefill grants walk cursors by (priority, FIFO) — a
   batch-class 32k-token prompt can no longer absorb the chunk budget
   ahead of an interactive prompt;
3. **preemption** (``serving/engine.py`` ``_maybe_preempt``): when a
   higher class waits and the batch is full (slots or KV pages), the
   lowest-priority decode row is PAUSED — its committed KV pages page out
   through the PR 11 prefix-cache/host-spill tier, the row requeues, and
   it resumes warm via the chunk-boundary cache with its emitted tokens
   intact. A tenant storm can delay its own class, never a higher one.

Deadline classes (knob table in docs/serving.md "Multi-tenancy"):

===========  ========  ======================================
class        priority  default deadline
===========  ========  ======================================
interactive  0         ``TPU_TENANT_INTERACTIVE_DEADLINE_S`` (2s)
standard     1         ``TPU_TENANT_STANDARD_DEADLINE_S`` (10s)
batch        2         ``TPU_TENANT_BATCH_DEADLINE_S`` (60s)
===========  ========  ======================================

Pure host policy: no device work; the registry lock is leaf-only.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any

__all__ = [
    "TenantPolicy", "TenantRegistry", "TokenBucket", "DEADLINE_CLASSES",
    "DEFAULT_TENANT",
]

# class name -> (priority, default deadline seconds). Priority is the
# scheduler's convention throughout the stack: LOWER runs first.
DEADLINE_CLASSES: dict[str, tuple[int, float]] = {
    "interactive": (0, 2.0),
    "standard": (1, 10.0),
    "batch": (2, 60.0),
}

DEFAULT_TENANT = "default"


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's SLO class: scheduling priority, the deadline its
    requests inherit when they carry none, and a token-rate budget
    (prompt + generation tokens per second; 0 = unmetered)."""

    name: str = DEFAULT_TENANT
    deadline_class: str = "standard"
    priority: int | None = None   # None = the class's priority
    deadline_s: float | None = None  # None = the class's default
    token_rate: float = 0.0       # tokens/second; 0 = unmetered
    burst_tokens: float = 0.0     # bucket size; 0 = 2s worth of rate

    def __post_init__(self) -> None:
        if self.deadline_class not in DEADLINE_CLASSES:
            raise ValueError(
                f"tenant {self.name!r}: deadline_class "
                f"{self.deadline_class!r} not in {sorted(DEADLINE_CLASSES)}"
            )
        cls_prio, cls_deadline = DEADLINE_CLASSES[self.deadline_class]
        if self.priority is None:
            self.priority = cls_prio
        if self.deadline_s is None:
            self.deadline_s = cls_deadline
        if self.token_rate > 0 and self.burst_tokens <= 0:
            self.burst_tokens = 2.0 * self.token_rate


class TokenBucket:
    """Classic token bucket, thread-safe. ``take(n)`` returns
    ``(ok, retry_after_s)`` — retry_after is how long until the bucket
    holds ``n`` tokens again, the number the 429's Retry-After carries."""

    def __init__(self, rate: float, burst: float) -> None:
        self.rate = float(rate)
        self.burst = float(burst)
        self._level = float(burst)
        # lazily anchored to the FIRST take's clock, so callers driving
        # an explicit test clock (take(now=...)) get exact refill math
        self._t: float | None = None
        self._mu = threading.Lock()

    def take(self, n: float, now: float | None = None) -> tuple[bool, float]:
        with self._mu:
            now = time.monotonic() if now is None else now
            if self._t is None:
                self._t = now
            self._level = min(
                self.burst, self._level + (now - self._t) * self.rate
            )
            self._t = now
            if n <= self._level:
                self._level -= n
                return True, 0.0
            deficit = n - self._level
            retry = deficit / self.rate if self.rate > 0 else 60.0
            return False, retry

    def level(self) -> float:
        with self._mu:
            return self._level


class TenantRegistry:
    """Tenant → policy + live rate bucket. Unknown tenants get the
    default policy (and, when it is metered, a per-tenant bucket of the
    default's rate — ten unknown tenants are ten budgets, not one)."""

    def __init__(self, default: TenantPolicy | None = None,
                 classes: dict[str, tuple[int, float]] | None = None) -> None:
        self._mu = threading.Lock()
        self._policies: dict[str, TenantPolicy] = {}
        self._buckets: dict[str, TokenBucket] = {}
        # per-registry class table: env overrides must not leak into the
        # module global (tests build many registries per process)
        self.classes = dict(classes or DEADLINE_CLASSES)
        self.default = default or TenantPolicy()
        self.rejections: dict[str, int] = {}

    @classmethod
    def from_config(cls, config: Any) -> "TenantRegistry":
        """Env wiring: ``TPU_TENANT_POLICIES`` is a semicolon list of
        ``name:class[:token_rate]`` entries, e.g.
        ``gold:interactive;bulk:batch:500``. Class deadline defaults are
        overridable via ``TPU_TENANT_<CLASS>_DEADLINE_S``."""
        classes = dict(DEADLINE_CLASSES)
        for name in classes:
            raw = config.get(f"TPU_TENANT_{name.upper()}_DEADLINE_S")
            if raw:
                prio, _ = classes[name]
                classes[name] = (prio, float(raw))
        reg = cls(
            default=TenantPolicy(deadline_s=classes["standard"][1]),
            classes=classes,
        )
        spec = config.get_or_default("TPU_TENANT_POLICIES", "")
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            parts = entry.split(":")
            if len(parts) < 2:
                raise ValueError(
                    f"TPU_TENANT_POLICIES entry {entry!r}: want "
                    "name:class[:token_rate]"
                )
            rate = float(parts[2]) if len(parts) > 2 else 0.0
            if parts[1] not in classes:
                raise ValueError(
                    f"TPU_TENANT_POLICIES entry {entry!r}: class "
                    f"{parts[1]!r} not in {sorted(classes)}"
                )
            reg.set_policy(TenantPolicy(
                name=parts[0], deadline_class=parts[1],
                deadline_s=classes[parts[1]][1], token_rate=rate,
            ))
        return reg

    def set_policy(self, policy: TenantPolicy) -> None:
        with self._mu:
            self._policies[policy.name] = policy
            self._buckets.pop(policy.name, None)  # rate changed: rebuild

    def policy(self, tenant: str | None) -> TenantPolicy:
        if not tenant:
            return self.default
        with self._mu:
            return self._policies.get(tenant, self.default)

    def admit(self, tenant: str | None, tokens: int) -> tuple[bool, float]:
        """Charge ``tokens`` (prompt + requested generation) against the
        tenant's rate budget. Returns ``(ok, retry_after_s)``; unmetered
        tenants always admit. Called on the submit path — one lock, one
        bucket update, microseconds."""
        name = tenant or DEFAULT_TENANT
        pol = self.policy(tenant)
        if pol.token_rate <= 0:
            return True, 0.0
        with self._mu:
            bucket = self._buckets.get(name)
            if bucket is None:
                bucket = TokenBucket(pol.token_rate, pol.burst_tokens)
                self._buckets[name] = bucket
        ok, retry = bucket.take(float(tokens))
        if not ok:
            with self._mu:
                self.rejections[name] = self.rejections.get(name, 0) + 1
        return ok, retry

    def snapshot(self) -> dict[str, Any]:
        with self._mu:
            return {
                "policies": {
                    name: {
                        "deadline_class": p.deadline_class,
                        "priority": p.priority,
                        "deadline_s": p.deadline_s,
                        "token_rate": p.token_rate,
                    }
                    for name, p in self._policies.items()
                },
                "rejections": dict(self.rejections),
                "bucket_levels": {
                    name: round(b.level(), 1)
                    for name, b in self._buckets.items()
                },
            }

"""Load shedding: an EWMA queue-wait estimator for admission control.

At saturation the failure mode is not errors but tail-latency collapse:
requests queue past the point where their answer is useful, then time out
after holding queue and memory for seconds. The cure (AIBrix, and every
production serving comparison in PAPERS.md) is to reject *early*: estimate
how long a new request would wait behind the current queue and, when that
estimate exceeds the request's own deadline or a configured shed threshold,
reject in microseconds with 429 + ``Retry-After`` instead of timing out in
seconds.

The estimate is deliberately cheap — two EWMAs updated on the engine
thread, one multiply on the submit path:

    wait ≈ (queue_depth / max_slots) × EWMA(request service time)

queue_depth/max_slots is how many admission "waves" stand ahead of this
request; each wave costs roughly one smoothed request duration. An empty
queue estimates 0.0 — an idle engine must never shed, even when warm-up
(compile time) has inflated the service-time EWMA.

Cold start is the estimator's known blind spot (PR 18 loadlab found it):
the service-time EWMA is seeded only by COMPLETED requests, so the first
burst after startup estimates 0.0 however deep the queue gets, and nothing
sheds until requests already doomed to time out have piled up. The blend:
until the first completion, service time falls back to the warmest signal
available — the TTFT EWMA (first tokens of the warming wave are a live
lower bound on service time), then the configured ``cold_prior_s``. The
prior defaults to 0.0 — never-shed-blind stays the out-of-the-box
behavior, because a wrong prior on a cold engine would reject the very
traffic that warms it — and deployments that know their service-time
scale (the load harness, production configs) opt in via
``TPU_SHED_COLD_PRIOR_S``.
"""

from __future__ import annotations

import threading


class QueueWaitEstimator:
    """Thread-safe EWMA estimator of queue wait for a slot-based engine."""

    def __init__(self, alpha: float = 0.25, cold_prior_s: float = 0.0) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        if cold_prior_s < 0.0:
            raise ValueError("cold_prior_s must be >= 0")
        self.alpha = alpha
        self.cold_prior_s = cold_prior_s
        self._mu = threading.Lock()
        self._ttft_s: float | None = None
        self._req_s: float | None = None

    def _blend(self, prev: float | None, obs: float) -> float:
        if prev is None:
            return obs
        return prev + self.alpha * (obs - prev)

    def observe_ttft(self, seconds: float) -> None:
        with self._mu:
            self._ttft_s = self._blend(self._ttft_s, max(0.0, seconds))

    def observe_request(self, seconds: float) -> None:
        """One completed request's total service time (submit → terminal)."""
        with self._mu:
            self._req_s = self._blend(self._req_s, max(0.0, seconds))

    def estimate_wait(self, queue_depth: int, max_slots: int) -> float:
        """Predicted seconds a request submitted NOW spends queued behind
        the ``queue_depth`` requests ahead of it. 0.0 at empty queue (an
        idle engine never sheds). Before the first completion the service
        time blends down the cold-start ladder: TTFT EWMA if the warming
        wave has produced first tokens, else ``cold_prior_s`` — which is
        0.0 unless configured, preserving never-shed-blind by default."""
        with self._mu:
            req_s = self._req_s
            ttft_s = self._ttft_s
        if queue_depth <= 0:
            return 0.0
        if req_s is None:
            req_s = max(ttft_s if ttft_s is not None else 0.0,
                        self.cold_prior_s)
            if req_s <= 0.0:
                return 0.0
        waves = queue_depth / max(max_slots, 1)
        return waves * req_s

    def snapshot(self) -> dict[str, float]:
        with self._mu:
            return {
                "ewma_ttft_s": self._ttft_s or 0.0,
                "ewma_request_s": self._req_s or 0.0,
                "cold_prior_s": self.cold_prior_s,
            }

"""Load shedding: an EWMA queue-wait estimator for admission control.

At saturation the failure mode is not errors but tail-latency collapse:
requests queue past the point where their answer is useful, then time out
after holding queue and memory for seconds. The cure (AIBrix, and every
production serving comparison in PAPERS.md) is to reject *early*: estimate
how long a new request would wait behind the current queue and, when that
estimate exceeds the request's own deadline or a configured shed threshold,
reject in microseconds with 429 + ``Retry-After`` instead of timing out in
seconds.

The estimate is deliberately cheap — two EWMAs updated on the engine
thread, one multiply on the submit path:

    wait ≈ (queue_depth / max_slots) × EWMA(request service time)

queue_depth/max_slots is how many admission "waves" stand ahead of this
request; each wave costs roughly one smoothed request duration. An empty
queue estimates 0.0 — an idle engine must never shed, even when warm-up
(compile time) has inflated the service-time EWMA. Bias-corrected EWMAs
would be overkill: the first observation seeds the average directly, and
until the first completion the estimator reports 0.0 — shedding blind on
a cold engine would reject the very traffic that warms it.
"""

from __future__ import annotations

import threading


class QueueWaitEstimator:
    """Thread-safe EWMA estimator of queue wait for a slot-based engine."""

    def __init__(self, alpha: float = 0.25) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.alpha = alpha
        self._mu = threading.Lock()
        self._ttft_s: float | None = None
        self._req_s: float | None = None

    def _blend(self, prev: float | None, obs: float) -> float:
        if prev is None:
            return obs
        return prev + self.alpha * (obs - prev)

    def observe_ttft(self, seconds: float) -> None:
        with self._mu:
            self._ttft_s = self._blend(self._ttft_s, max(0.0, seconds))

    def observe_request(self, seconds: float) -> None:
        """One completed request's total service time (submit → terminal)."""
        with self._mu:
            self._req_s = self._blend(self._req_s, max(0.0, seconds))

    def estimate_wait(self, queue_depth: int, max_slots: int) -> float:
        """Predicted seconds a request submitted NOW spends queued behind
        the ``queue_depth`` requests ahead of it. 0.0 until the first
        completion (never shed blind) and 0.0 at empty queue (an idle
        engine never sheds)."""
        with self._mu:
            req_s = self._req_s
        if req_s is None or queue_depth <= 0:
            return 0.0
        waves = queue_depth / max(max_slots, 1)
        return waves * req_s

    def snapshot(self) -> dict[str, float]:
        with self._mu:
            return {
                "ewma_ttft_s": self._ttft_s or 0.0,
                "ewma_request_s": self._req_s or 0.0,
            }

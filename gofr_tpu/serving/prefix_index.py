"""The distributed prefix index + warm KV page migration.

Before this module, every prefix-cache hit stopped at the replica
boundary: the router's consistent-hash affinity was the only
cross-replica reuse, and it collapsed into a cold re-prefill on failover
or load spill. This is the cluster tier (ROADMAP item 3, AIBrix
multi-tier KV pooling arXiv:2504.03648), in two halves:

- :class:`PrefixIndex` — who has what. Replicas piggyback a bounded
  ``prefix_keys`` advertisement ([key, tier] pairs straight out of their
  :class:`TieredPrefixCache`) on the existing membership heartbeat
  (serving/membership.py), riding the same idempotent per-replica ``seq``
  discipline: the at-least-once pubsub layer may redeliver or reorder
  beats, and a stale advertisement must never overwrite a newer one.
  Lookups are **advisory** — a stale entry degrades to a compute miss on
  the fetch path, never an error.

- :class:`KVMigrator` — go get it. When a replica admits a request whose
  prefix is cached elsewhere (affinity spill, pre-first-token failover),
  the migrator locates the peer with the longest advertised
  chunk-boundary chain and fetches the slabs: direct cache-to-cache when
  the peer is colocated in-process (:func:`local_engine_fetcher` — the
  slabs are already device arrays), serialized page transfer over the
  HTTP surface otherwise (``/kv/fetch``, serving/handlers.py +
  ``HTTPReplica.fetch_kv``). Fetched entries admit through the existing
  chunk-prefix commit path (``kv_cache.write_span`` /
  ``batch_ops.insert_chunk``) and land in the local cache, so the
  migration pays once per replica, not once per request.

The ``kv.migrate`` chaos point sits on every peer fetch: a fault there
IS a source replica dying mid-transfer — the migrator returns whatever
contiguous prefix it already fetched and the engine computes the rest
(tests/test_router_chaos.py pins that this degrades to re-prefill,
never corrupts KV or double-serves).

Lock discipline: the index lock is LEAF-ONLY (never held across a fetch
or any call out); the migrator itself is lock-free — peers are
registered before serving starts and the dict is read-only after.
"""

from __future__ import annotations

import base64
import threading
import time
from typing import Any, Callable

import numpy as np

from gofr_tpu import chaos

__all__ = [
    "PrefixIndex",
    "KVMigrator",
    "local_engine_fetcher",
    "local_engine_store",
    "encode_entry",
    "decode_entry",
]


# -- slab serialization (the remote page-transfer wire format) -----------------

def _np_dtype(name: str) -> Any:
    """Resolve a dtype name, including the ml_dtypes extended set
    (bfloat16 — the KV slab dtype on every bf16 layout)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # ships with jax

        return np.dtype(getattr(ml_dtypes, name))


def encode_entry(value: Any) -> dict[str, Any]:
    """Serialize one prefix-cache entry — a (last_logits, k_slab,
    v_slab) tuple of arrays — to a JSON-safe dict. The caller owns the
    device→host materialization cost (np.asarray on each leaf): this
    runs on an HTTP worker thread, never the engine thread."""
    leaves = []
    for leaf in value:
        arr = np.asarray(leaf)
        leaves.append({
            "shape": list(arr.shape),
            "dtype": arr.dtype.name,
            "data": base64.b64encode(arr.tobytes()).decode("ascii"),
        })
    return {"leaves": leaves}


def decode_entry(payload: dict[str, Any]) -> tuple:
    """Inverse of :func:`encode_entry`: host numpy arrays, ready for an
    async ``jnp.asarray`` upload at the commit site."""
    out = []
    for leaf in payload["leaves"]:
        arr = np.frombuffer(
            base64.b64decode(leaf["data"]), dtype=_np_dtype(leaf["dtype"])
        ).reshape(leaf["shape"])
        out.append(arr)
    return tuple(out)


# -- the cluster-wide index ----------------------------------------------------

class PrefixIndex:
    """digest → (replica, tier) advertisements, replica-versioned.

    ``observe`` REPLACES a replica's advertised set (each beat carries
    the replica's current bounded view, not a delta) and drops stale
    ``seq``s — the same idempotency discipline MembershipTable.observe
    applies to the beats these advertisements ride on."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # replica_id -> (seq, {key: tier})
        self._by_replica: dict[str, tuple[int, dict[str, str]]] = {}

    def observe(self, replica_id: str, seq: int,
                entries: list[Any] | None) -> bool:
        """Ingest one replica's advertisement. Returns False for
        stale/duplicate seqs (pubsub redelivery or reorder)."""
        if entries is None:
            return False
        keyed: dict[str, str] = {}
        for item in entries:
            try:
                key, tier = item[0], item[1]
            except (TypeError, IndexError, KeyError):
                continue  # malformed advertisement rows are dropped
            keyed[str(key)] = str(tier)
        with self._mu:
            prev = self._by_replica.get(replica_id)
            if prev is not None and seq <= prev[0]:
                return False
            self._by_replica[replica_id] = (int(seq), keyed)
            return True

    def drop_replica(self, replica_id: str) -> None:
        with self._mu:
            self._by_replica.pop(replica_id, None)

    def locate(self, key: str,
               exclude: str | None = None) -> list[tuple[str, str]]:
        """Replicas advertising ``key``, as (replica_id, tier) pairs —
        device tier first (a device-resident slab serves the transfer
        without its owner touching host RAM)."""
        out: list[tuple[str, str]] = []
        with self._mu:
            for rid, (_seq, entries) in self._by_replica.items():
                if rid == exclude:
                    continue
                tier = entries.get(key)
                if tier is not None:
                    out.append((rid, tier))
        out.sort(key=lambda rt: (rt[1] != "device", rt[0]))
        return out

    def longest_chain(self, keys: list[str],
                      exclude: str | None = None) -> tuple[str | None, int]:
        """The replica advertising the longest CONTIGUOUS leading run of
        ``keys`` (the chunk-boundary chain of one prompt) — the router's
        and the migrator's shared question: where does the warmest copy
        of this prefix live?"""
        best: tuple[str | None, int] = (None, 0)
        with self._mu:
            for rid, (_seq, entries) in self._by_replica.items():
                if rid == exclude:
                    continue
                n = 0
                for key in keys:
                    if key not in entries:
                        break
                    n += 1
                if n > best[1]:
                    best = (rid, n)
        return best

    def snapshot(self) -> dict[str, Any]:
        with self._mu:
            return {
                rid: {"seq": seq, "advertised": len(entries)}
                for rid, (seq, entries) in self._by_replica.items()
            }


# -- warm page migration -------------------------------------------------------

def local_engine_fetcher(engine: Any) -> Callable[[list[str]], dict[str, tuple]]:
    """Peer fetcher for a COLOCATED in-process replica: reads the
    owning engine's prefix cache directly — the slabs are already
    device arrays, so the 'transfer' is a reference (device-to-device
    by construction, zero copies)."""

    def fetch(keys: list[str]) -> dict[str, tuple]:
        cache = getattr(engine, "_prefix_cache", None)
        if cache is None:
            return {}
        # peek, never get: a peer read must not mutate the owner's LRU
        # order, promote host-tier entries into its device budget, or
        # destructively pop its only host copy (TieredPrefixCache.peek)
        read = getattr(cache, "peek", None) or cache.get
        out: dict[str, tuple] = {}
        for key in keys:
            value = read(key)
            if value is not None:
                out[key] = value
        return out

    return fetch


def local_engine_store(engine: Any) -> Callable[[list[tuple]], int]:
    """Survivor-side commit for an in-process bulk evacuation
    (:meth:`KVMigrator.evacuate_chain`): stages every pushed entry,
    audits the batch, then commits it into the target engine's prefix
    cache whole — or not at all (a mid-commit failure evicts what this
    batch already wrote, so a torn push can never leave a partial set
    masquerading as a complete one). REFUSES (raises) when the target is
    itself reclaiming/draining/stopped: a notice storm must never
    evacuate KV onto capacity that is about to need evacuating."""

    def store(entries: list[tuple]) -> int:
        cache = getattr(engine, "_prefix_cache", None)
        if cache is None:
            raise RuntimeError("evacuation target has no prefix cache")
        if (getattr(engine, "_reclaiming", False)
                or getattr(engine, "draining", False)
                or not getattr(engine, "_running", True)):
            raise RuntimeError(
                "evacuation target is reclaiming/draining/stopped"
            )
        # phase one: audit the whole batch before any commit — one
        # malformed entry rejects the push entire
        staged: list[tuple[Any, tuple]] = []
        for item in entries:
            try:
                key, value = item
            except (TypeError, ValueError) as exc:
                raise ValueError(f"malformed evacuation entry: {exc}")
            if value is None or len(value) != 3:
                raise ValueError("malformed evacuation entry value")
            staged.append((key, value))
        # phase two: commit; a failure mid-batch discards the whole
        # batch (survivors degrade to re-prefill, never trust a torn set)
        committed: list[Any] = []
        try:
            for key, value in staged:
                cache.put(key, value)
                committed.append(key)
        except Exception:
            for key in committed:
                try:
                    cache.evict(key)
                except Exception:
                    pass
            raise
        return len(committed)

    return store


class KVMigrator:
    """The admitting replica's pull side of warm KV migration.

    ``peers`` maps replica_id → ``fetch(keys) -> {key: (logits, k, v)}``
    (device arrays from a local peer, host numpy from a remote one —
    the engine's commit path uploads either asynchronously). Every
    failure mode — peer gone, stale advertisement, chaos fault, torn
    transfer — degrades to a compute miss: the migrator returns the
    contiguous prefix it DID fetch and the engine prefills the rest.

    A FAILED fetch suppresses that peer for ``failure_backoff_s``: a
    dead replica's advertisements can outlive it (mark-down keeps no
    fresh beats coming, but nothing retracts the old ones), and without
    negative caching every cache-miss admission would block the engine
    thread up to the transport timeout, per request, forever. The
    single-caller discipline (one engine's admission thread) keeps the
    migrator lock-free.
    """

    def __init__(self, replica_id: str, index: PrefixIndex, *,
                 logger: Any = None, metrics: Any = None,
                 failure_backoff_s: float = 5.0,
                 fetch_timeout_s: float = 2.0) -> None:
        self.replica_id = replica_id
        self.index = index
        self._logger = logger
        self._metrics = metrics
        self.failure_backoff_s = failure_backoff_s
        self.fetch_timeout_s = fetch_timeout_s
        self._peers: dict[str, Callable[[list[str]], dict[str, tuple]]] = {}
        self._peer_bounded: dict[str, bool] = {}
        self._suppressed_until: dict[str, float] = {}
        # push side (reclamation evacuation): replica_id -> store(entries)
        self._push_peers: dict[str, Callable[[list[tuple]], int]] = {}
        self._push_bounded: dict[str, bool] = {}
        self.migrations_total = 0
        self.handoffs_total = 0
        self.failed_fetches_total = 0
        self.evacuations_total = 0
        self.failed_evacuations_total = 0

    def add_peer(self, replica_id: str,
                 fetch: Callable[[list[str]], dict[str, tuple]]) -> None:
        self._peers[replica_id] = fetch
        # a remote transport fetcher (HTTPReplica.fetch_kv) takes a
        # timeout kwarg; a local peek-based one doesn't block at all.
        # Detected once here so fetch_chain can clamp the wire wait to
        # the request's remaining deadline without changing the plain
        # fetch(keys) peer contract.
        try:
            import inspect

            self._peer_bounded[replica_id] = (
                "timeout" in inspect.signature(fetch).parameters
            )
        except (TypeError, ValueError):
            self._peer_bounded[replica_id] = False

    def remove_peer(self, replica_id: str) -> None:
        self._peers.pop(replica_id, None)
        self._peer_bounded.pop(replica_id, None)

    def add_push_peer(self, replica_id: str,
                      store: Callable[[list[tuple]], int]) -> None:
        """Register a survivor the bulk evacuation may push to:
        ``store(entries) -> committed count`` with all-or-nothing commit
        semantics (:func:`local_engine_store` in-process; a remote store
        takes a ``timeout`` kwarg, detected like :meth:`add_peer`)."""
        self._push_peers[replica_id] = store
        try:
            import inspect

            self._push_bounded[replica_id] = (
                "timeout" in inspect.signature(store).parameters
            )
        except (TypeError, ValueError):
            self._push_bounded[replica_id] = False

    def remove_push_peer(self, replica_id: str) -> None:
        self._push_peers.pop(replica_id, None)
        self._push_bounded.pop(replica_id, None)

    def _peer_kwargs(self, replica_id: str,
                     deadline: float | None) -> dict[str, float]:
        if not self._peer_bounded.get(replica_id):
            return {}
        if deadline is None:
            return {"timeout": self.fetch_timeout_s}
        return {"timeout": min(self.fetch_timeout_s, deadline)}

    def fetch_chain(
        self, boundaries: list[tuple[int, int, str]],
        deadline: float | None = None,
    ) -> list[tuple[int, int, tuple]]:
        """Fetch the longest advertised contiguous run of chunk-boundary
        entries for ``boundaries`` ([(start, end, key), ...], the
        engine's remaining un-cached chain). Returns [(start, end,
        value), ...], contiguous from the first boundary — possibly
        empty, never raising. ``deadline`` is the request's remaining
        budget in seconds: an already-expired request degrades to a
        compute miss without touching the wire, and a bounded peer's
        transport timeout is clamped to it."""
        if deadline is not None and deadline <= 0:
            return []  # expired: never block admission on a dead request
        if not boundaries or not self._peers:
            return []
        keys = [key for _s, _e, key in boundaries]
        rid, n = self.index.longest_chain(keys, exclude=self.replica_id)
        if rid is None or n == 0:
            return []
        fetch = self._peers.get(rid)
        if fetch is None:
            return []  # advertised by a replica we hold no transport to
        until = self._suppressed_until.get(rid)
        if until is not None and time.monotonic() < until:
            return []  # peer recently failed a fetch: don't stall
            # admission behind its transport timeout again yet
        want = boundaries[:n]
        try:
            chaos.maybe_fail("kv.migrate")
            fetched = fetch(
                [key for _s, _e, key in want],
                **self._peer_kwargs(rid, deadline),
            )
        except Exception as exc:
            # the source died mid-transfer (or the chaos point fired):
            # nothing was committed — a clean degrade to re-prefill,
            # and the peer goes quiet for failure_backoff_s
            self.failed_fetches_total += 1
            self._suppressed_until[rid] = (
                time.monotonic() + self.failure_backoff_s
            )
            if self._logger is not None:
                self._logger.warn(
                    f"KV migration fetch from {rid} failed; "
                    f"re-prefilling: {exc}"
                )
            return []
        self._suppressed_until.pop(rid, None)
        out: list[tuple[int, int, tuple]] = []
        for start, end, key in want:
            value = fetched.get(key)
            if value is None:
                break  # stale advertisement: keep the contiguous prefix
            out.append((start, end, value))
        if out:
            self.migrations_total += 1
            if self._metrics is not None:
                self._metrics.increment_counter("app_kv_migrations_total")
        return out

    def fetch_one(self, key: str,
                  deadline: float | None = None) -> tuple | None:
        """Single-entry fetch (the whole-prompt/monolithic prefill
        path). Same advisory contract as :meth:`fetch_chain`."""
        got = self.fetch_chain([(0, 0, key)], deadline=deadline)
        return got[0][2] if got else None

    # -- disaggregated prefill→decode handoff ----------------------------------
    def fetch_handoff(
        self, boundaries: list[tuple[int, int, str]], source: str,
        deadline: float | None = None,
    ) -> list[tuple[int, int, tuple]]:
        """The prefill→decode KV handoff fetch (docs/robustness.md "The
        disaggregation plane"): pull ``boundaries`` from the NAMED
        prefill replica under a two-phase-commit discipline — phase one
        fetches every span into host staging, phase two hands the chain
        to the engine's commit path ONLY when it is complete and
        contiguity-audited (every boundary present, spans abutting,
        covering the request exactly). Anything less returns ``[]`` and
        the decode replica re-prefills: a torn handoff must degrade, not
        commit a partial chain the admission believed complete.

        The ``kv.handoff`` chaos point models the source dying (or the
        transport tearing) mid-handoff; a failed source is suppressed
        for ``failure_backoff_s`` exactly like the advisory tier.
        ``deadline`` follows the :meth:`fetch_chain` contract: expired →
        degrade without touching the wire, bounded peer → clamped
        transport timeout."""
        if deadline is not None and deadline <= 0:
            return []
        if not boundaries:
            return []
        fetch = self._peers.get(source)
        if fetch is None:
            return []  # no transport to the named source: re-prefill
        until = self._suppressed_until.get(source)
        if until is not None and time.monotonic() < until:
            return []
        try:
            chaos.maybe_fail("kv.handoff")
            fetched = fetch(
                [key for _s, _e, key in boundaries],
                **self._peer_kwargs(source, deadline),
            )
        except Exception as exc:
            self.failed_fetches_total += 1
            self._suppressed_until[source] = (
                time.monotonic() + self.failure_backoff_s
            )
            if self._logger is not None:
                self._logger.warn(
                    f"KV handoff fetch from {source} failed; "
                    f"re-prefilling: {exc}"
                )
            return []
        self._suppressed_until.pop(source, None)
        out: list[tuple[int, int, tuple]] = []
        pos = boundaries[0][0]
        for start, end, key in boundaries:
            value = fetched.get(key)
            # the audit: every span present, well-formed, and abutting
            # the previous one — the prefill side evicting a chunk
            # mid-handoff (or a codec tearing a leaf) fails the WHOLE
            # chain, never admits a gap
            if (value is None or len(value) != 3 or start != pos
                    or end <= start):
                self.failed_fetches_total += 1
                return []
            out.append((start, end, value))
            pos = end
        self.handoffs_total += 1
        if self._metrics is not None:
            self._metrics.increment_counter("app_kv_handoffs_total")
        return out

    def fetch_one_handoff(self, key: str, source: str,
                          deadline: float | None = None) -> tuple | None:
        """Monolithic-prompt handoff: the single whole-prompt prefill
        entry from the named source — present and well-formed, or None
        (re-prefill). Same 2PC/backoff contract as :meth:`fetch_handoff`."""
        got = self.fetch_handoff([(0, 1, key)], source, deadline=deadline)
        return got[0][2] if got else None

    # -- reclamation-notice bulk evacuation (push side) -------------------------
    def evacuate_chain(
        self, entries: list[tuple], deadline: float | None = None,
    ) -> tuple[str, int] | None:
        """Push this replica's committed KV entries to ONE surviving
        peer under a reclamation notice (docs/robustness.md "The
        reclamation plane"). ``entries`` is ``[(key, (logits, k, v)),
        ...]`` straight off the local prefix cache; ``deadline`` is the
        notice's REMAINING budget in seconds and threads into every wire
        call — a spent budget degrades to re-prefill on the survivors
        without touching the wire, and a bounded (remote) store's
        transport timeout is clamped to it.

        Two-phase like the handoff fetch: the store commits the batch
        whole or raises (:func:`local_engine_store`), so a source dying
        mid-push — the ``kv.evacuate`` chaos point — leaves the survivor
        clean, never holding a partial set it believes complete. A
        refusing/failed survivor is suppressed for ``failure_backoff_s``
        and the next one is tried; returns ``(replica_id, committed)``
        on success, None when no survivor accepted (degrade: survivors
        re-prefill)."""
        if not entries or not self._push_peers:
            return None
        t0 = time.monotonic()
        for rid in sorted(self._push_peers):
            remaining = (
                None if deadline is None
                else deadline - (time.monotonic() - t0)
            )
            if remaining is not None and remaining <= 0:
                return None  # budget spent: never start a push that
                # cannot finish — a torn commit helps nobody
            until = self._suppressed_until.get(rid)
            if until is not None and time.monotonic() < until:
                continue
            store = self._push_peers[rid]
            kwargs: dict[str, float] = {}
            if self._push_bounded.get(rid):
                kwargs["timeout"] = (
                    self.fetch_timeout_s if remaining is None
                    else min(self.fetch_timeout_s, remaining)
                )
            try:
                chaos.maybe_fail("kv.evacuate")
                n = store(entries, **kwargs)
            except Exception as exc:
                # the push tore (source dying, survivor refusing, chaos
                # fault): the store's all-or-nothing contract means the
                # survivor holds nothing from this batch — try the next
                self.failed_evacuations_total += 1
                self._suppressed_until[rid] = (
                    time.monotonic() + self.failure_backoff_s
                )
                if self._logger is not None:
                    self._logger.warn(
                        f"KV evacuation push to {rid} failed; "
                        f"trying next survivor: {exc}"
                    )
                continue
            self._suppressed_until.pop(rid, None)
            self.evacuations_total += 1
            if self._metrics is not None:
                self._metrics.increment_counter("app_kv_migrations_total")
            return (rid, int(n))
        return None

"""Ready-made serving handlers: /generate (JSON + SSE stream), /embed,
/v1/models, /requestz — the endpoints BASELINE.json configs[1..2]
measure, plus the request flight recorder (docs/observability.md).

Wire-up (mirrors the reference's route ergonomics)::

    app = gofr_tpu.App()
    engine = ServingEngine(cfg, params, metrics=app.container.metrics_manager)
    register_generation_routes(app, engine)

Streaming: ``"stream": true`` returns Server-Sent Events over chunked
transfer — each token a ``data:`` line, final event carries usage stats.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
from typing import Any

from gofr_tpu.http.errors import (
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorMissingParam,
    HTTPError,
)
from gofr_tpu.http.responder import WireResponse
from gofr_tpu.tracing.trace import current_span


@dataclasses.dataclass
class GenerateRequest:
    prompt: str = ""
    max_tokens: int = 0
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    stream: bool = False
    # disaggregated serving (docs/robustness.md "The disaggregation
    # plane"): prefill_only runs the prompt KV into the prefix cache and
    # retires (finish_reason "handoff"); handoff_from names the prefill
    # replica this request's admission pulls its KV chain from
    prefill_only: bool = False
    handoff_from: str = ""
    # multi-tenant plane (docs/serving.md "Multi-tenancy"): the request's
    # LoRA adapter (unknown ids 400 at submit) and tenant (SLO class,
    # rate budget, the timeline/span/metric label). The X-Tenant-Id
    # header overrides the body field so gateways can stamp tenancy
    # without rewriting payloads.
    adapter_id: str = ""
    tenant: str = ""
    # HA plane (docs/robustness.md "The HA plane"): the client's
    # Idempotency-Key — a duplicate submit attaches to the live request
    # or replays its terminal instead of dispatching twice (the header
    # outranks the body field, same contract as tenancy); fence_epoch
    # stamps the caller's view of the replica's fence epoch — stale
    # callers are rejected 409 before any engine state is touched.
    idempotency_key: str = ""
    fence_epoch: int = 0


def _shutdown_hook(engine: Any) -> Any:
    """Drain, not stop: SIGTERM lets in-flight generations finish within
    the drain deadline instead of abandoning their streams (engines
    without drain — injected test doubles — fall back to stop)."""
    return getattr(engine, "drain", None) or engine.stop


def _wire_lifecycle(app: Any, engine: Any, supervisor: Any) -> None:
    """Start/shutdown hooks for the generation surfaces. With a
    supervisor (serving/supervisor.py), IT owns the lifecycle: start
    brings up engine + watchdog, shutdown halts the watchdog FIRST so the
    deliberate drain quiet-period is never detected as a stall."""
    if supervisor is not None:
        app.on_start(lambda ctx: supervisor.start())
        app.on_shutdown(supervisor.drain)
    else:
        app.on_start(lambda ctx: engine.start())
        app.on_shutdown(_shutdown_hook(engine))


def deadline_from_ctx(ctx: Any) -> float | None:
    """The HTTP deadline contract: ``X-Request-Timeout`` (or bare
    ``Request-Timeout``), seconds, float. Invalid values are a client
    error, not a silently-ignored header."""
    raw = ctx.header("x-request-timeout") or ctx.header("request-timeout")
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        raise ErrorInvalidParam("X-Request-Timeout") from None
    return value if value > 0 else None


def register_generation_routes(app: Any, engine: Any, prefix: str = "",
                               supervisor: Any = None) -> None:
    app.container.serving = engine
    _wire_lifecycle(app, engine, supervisor)

    async def generate(ctx: Any):
        body = ctx.bind(GenerateRequest)
        kw = _request_kwargs(ctx, body)
        if body.stream:
            return _sse_response(engine, body.prompt, kw)
        result = await engine.generate(body.prompt, **kw)
        return {
            "id": result.request_id,
            "text": result.text,
            "finish_reason": result.finish_reason,
            "usage": {
                "prompt_tokens": result.prompt_tokens,
                "completion_tokens": result.completion_tokens,
                "ttft_ms": round(result.ttft_s * 1000, 2),
                "duration_ms": round(result.duration_s * 1000, 2),
            },
        }

    def models(ctx: Any):
        cfg = engine.model_cfg
        return {
            "models": [
                {
                    "id": "flagship",
                    "family": "llama",
                    "n_layers": cfg.n_layers,
                    "d_model": cfg.d_model,
                    "vocab_size": cfg.vocab_size,
                    "max_seq_len": engine.config.max_seq_len,
                    "slots": engine.config.max_slots,
                }
            ]
        }

    async def generate_stream(ctx: Any):
        """The remote token-stream transport (docs/serving.md): always
        streams, whatever the body's ``stream`` flag says — a router's
        HTTPReplica needs a surface whose FIRST byte is the request id
        frame and whose tokens arrive as they decode, so remote TTFT is
        decoupled from completion time.

        Re-attach (docs/serving.md "Resumable streams"): a request with
        BOTH ``Last-Event-ID`` and ``Idempotency-Key`` headers resumes
        the keyed stream instead of submitting — the engine replays every
        frame past the acked seq token-identically and the response rides
        the still-running generation. No prompt needed (the original
        submit owns it), so the branch runs before body validation."""
        last_id = ctx.header("last-event-id")
        idem = ctx.header("idempotency-key")
        if last_id and idem and hasattr(engine, "resume"):
            return _sse_resume_response(engine, ctx, idem, last_id)
        body = ctx.bind(GenerateRequest)
        kw = _request_kwargs(ctx, body)
        return _sse_response(engine, body.prompt, kw)

    async def generate_cancel(ctx: Any):
        """The remote cancel wire: ``{"id": N}`` marks the request
        canceled — a running row frees its slot at the next block sync
        (its stream ends with finish_reason "cancel"), a queued one
        resolves at admission. Idempotent; an unknown id is a no-op
        (the request may have finished while the cancel was in flight)."""
        body = ctx.bind(dict) or {}
        rid = body.get("id")
        if rid is None:
            raise ErrorMissingParam("id")
        try:
            rid = int(rid)
        except (TypeError, ValueError):
            raise ErrorInvalidParam("id") from None
        engine.cancel(rid)
        return {"canceled": rid}

    app.post(prefix + "/generate", generate)
    app.post(prefix + "/generate/stream", generate_stream)
    app.post(prefix + "/generate/cancel", generate_cancel)
    app.get(prefix + "/v1/models", models)
    register_requestz_routes(app, engine, prefix + "/requestz")
    register_kv_fetch_routes(app, engine, prefix + "/kv/fetch")


def _sse_response(engine: Any, prompt: str, kw: dict) -> WireResponse:
    # submit EAGERLY, inside the handler, before the 200 head is committed:
    # admission-time rejections (shed 429 + Retry-After, drain 503) must
    # reach the client as real statuses retry middleware can key on, not
    # as error events buried in a 200 stream
    loop = asyncio.get_running_loop()
    q: asyncio.Queue = asyncio.Queue()

    def cb(token_id: int, piece: str, done: bool) -> None:
        loop.call_soon_threadsafe(q.put_nowait, (token_id, piece, done))

    future = engine.submit(prompt, stream_cb=cb, **kw)

    async def gen():
        try:
            # id frame FIRST (docs/serving.md wire format): the remote
            # cancel wire needs the request id before any token arrives —
            # a client that hedges/aborts pre-first-token must be able to
            # name what it is canceling. ``id:`` lines carry the frame
            # sequence (id frame 0, tokens 1..N, terminal N+1) — the
            # handler's local count provably matches the engine-side
            # replay ring (same ordered single-worker detok stream), so a
            # client's Last-Event-ID re-attach replays exactly the unseen
            # suffix (docs/serving.md "Resumable streams"). A keyed
            # duplicate that attached PAST the replay window starts at the
            # engine's true sequence (``stream_base_seq``), announced in
            # the head frame as ``attached_at`` — its ``id:`` lines still
            # name real engine frames, so a later Last-Event-ID resumes
            # correctly even on a truncated stream.
            base = getattr(future, "stream_base_seq", 0)
            head = {"id": future.request_id}
            if base:
                head["attached_at"] = base
            yield (
                f"id: {base}\ndata: " + json.dumps(head) + "\n\n"
            ).encode()
            seq = base
            while True:
                token_id, piece, done = await q.get()
                if done:
                    break
                seq += 1
                payload = json.dumps({"token": token_id, "text": piece})
                yield f"id: {seq}\ndata: {payload}\n\n".encode()
            result = await asyncio.wrap_future(future)
            if result is not None:
                # terminal event: finish_reason (stop/length/cancel/
                # deadline_exceeded) + usage, so streaming clients learn WHY
                # the stream ended, not just that it did
                yield (
                    f"id: {seq + 1}\ndata: " + json.dumps({
                        "finish_reason": result.finish_reason,
                        "usage": {
                            "prompt_tokens": result.prompt_tokens,
                            "completion_tokens": result.completion_tokens,
                        },
                    }) + "\n\n"
                ).encode()
            yield b"data: [DONE]\n\n"
        except asyncio.CancelledError:
            raise
        except HTTPError as exc:
            # the response head (200, chunked) is already on the wire by
            # now; a LATE typed error (queued-expiry 504, drain-deadline
            # 503) becomes a terminal error event instead of a torn
            # connection — admission errors never reach here, they raised
            # from the eager submit above with a real status. Error
            # frames carry no id: the dedup entry is forgotten on an
            # exception terminal, so there is nothing to resume past.
            yield (
                "data: " + json.dumps({
                    "error": exc.message, "status": exc.status_code,
                }) + "\n\n"
            ).encode()
            yield b"data: [DONE]\n\n"
        finally:
            # client disconnected mid-stream (server aclose()s the
            # generator): free the slot instead of decoding into the void.
            # A KEYED stream is resumable — the disconnect may be a dying
            # router whose survivor re-attaches — so it parks for the
            # orphan-grace window instead of canceling outright.
            if not future.done():
                if kw.get("idempotency_key") and hasattr(engine, "orphan"):
                    engine.orphan(future.request_id)
                else:
                    engine.cancel(future.request_id)

    return WireResponse(
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        },
        stream=gen(),
    )


def _sse_resume_response(engine: Any, ctx: Any, idem_key: str,
                         last_raw: str) -> WireResponse:
    """The ``Last-Event-ID`` re-attach wire: replays the keyed stream's
    unseen suffix (token-identical, from the engine's bounded replay
    ring or the stored terminal) and rides the live generation. Resume
    errors (unknown key 404, evicted window 404, stale epoch 409) raise
    BEFORE the head commits — real statuses clients can key on."""
    try:
        last_seq = int(last_raw)
    except (TypeError, ValueError):
        raise ErrorInvalidParam("Last-Event-ID") from None
    fence_raw = ctx.header("x-fence-epoch")
    fence_epoch = None
    if fence_raw:
        try:
            fence_epoch = int(fence_raw)
        except ValueError:
            raise ErrorInvalidParam("X-Fence-Epoch") from None
    loop = asyncio.get_running_loop()
    q: asyncio.Queue = asyncio.Queue()

    def cb(seq: int, token_id: int, piece: str, done: bool) -> None:
        loop.call_soon_threadsafe(q.put_nowait, (seq, token_id, piece, done))

    future = engine.resume(
        idem_key, last_seq=last_seq, stream_cb=cb, fence_epoch=fence_epoch
    )

    async def gen():
        try:
            # re-attach head frame: names the request and echoes the seq
            # the replay starts after; id stays at the client's own
            # high-water mark so a naive tracker never regresses
            yield (
                f"id: {last_seq}\ndata: " + json.dumps({
                    "id": future.request_id, "resumed": last_seq,
                }) + "\n\n"
            ).encode()
            term_seq = last_seq
            while True:
                seq, token_id, piece, done = await q.get()
                if done:
                    term_seq = seq
                    break
                payload = json.dumps({"token": token_id, "text": piece})
                yield f"id: {seq}\ndata: {payload}\n\n".encode()
            result = await asyncio.wrap_future(future)
            if result is not None:
                yield (
                    f"id: {term_seq}\ndata: " + json.dumps({
                        "finish_reason": result.finish_reason,
                        "usage": {
                            "prompt_tokens": result.prompt_tokens,
                            "completion_tokens": result.completion_tokens,
                        },
                    }) + "\n\n"
                ).encode()
            yield b"data: [DONE]\n\n"
        except asyncio.CancelledError:
            raise
        except HTTPError as exc:
            yield (
                "data: " + json.dumps({
                    "error": exc.message, "status": exc.status_code,
                }) + "\n\n"
            ).encode()
            yield b"data: [DONE]\n\n"
        finally:
            # a resumed stream is keyed by construction: its disconnect
            # parks for the grace window like the original stream's did
            if not future.done():
                orphan = getattr(engine, "orphan", None)
                if orphan is not None:
                    orphan(future.request_id)
                else:
                    engine.cancel(future.request_id)

    return WireResponse(
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        },
        stream=gen(),
    )


def _validated_generate_kwargs(body: GenerateRequest) -> dict:
    """One binding/validation behavior for every generation surface
    (HTTP, SSE, WebSocket): raises the typed param errors."""
    if not body.prompt:
        raise ErrorMissingParam("prompt")
    if body.temperature < 0 or body.top_p <= 0 or body.top_p > 1:
        raise ErrorInvalidParam("temperature", "top_p")
    kw = dict(
        max_new_tokens=body.max_tokens or None,
        temperature=body.temperature,
        top_k=body.top_k,
        top_p=body.top_p,
    )
    # disaggregation flags ride only when set: engines without the
    # disaggregation plane (injected doubles) keep their old signature
    if body.prefill_only:
        kw["prefill_only"] = True
    if body.handoff_from:
        kw["handoff_from"] = body.handoff_from
    # tenancy flags likewise ride only when set
    if body.adapter_id:
        kw["adapter_id"] = body.adapter_id
    if body.tenant:
        kw["tenant"] = body.tenant
    # HA-plane flags ride only when set, same engine-double contract
    if body.idempotency_key:
        kw["idempotency_key"] = body.idempotency_key
    if body.fence_epoch:
        kw["fence_epoch"] = int(body.fence_epoch)
    return kw


def _request_kwargs(ctx: Any, body: GenerateRequest) -> dict:
    """The ONE per-request kwargs assembly every HTTP/WS generation
    route uses: validated body kwargs, then the context-derived fields —
    the deadline header, the caller's trace context, and the tenancy
    contract: ``X-Tenant-Id`` outranks the body's ``tenant`` field (a
    gateway stamping tenancy must win over whatever the client
    claimed)."""
    kw = _validated_generate_kwargs(body)
    header_tenant = ctx.header("x-tenant-id")
    if header_tenant:
        kw["tenant"] = header_tenant
    header_idem = ctx.header("idempotency-key")
    if header_idem:
        kw["idempotency_key"] = header_idem
    # a gateway stamping the fence outranks the body's claim, same as
    # tenancy; 0 means unfenced (epochs start at 1), same as the body
    fence_raw = ctx.header("x-fence-epoch")
    if fence_raw:
        try:
            fence = int(fence_raw)
        except ValueError:
            raise ErrorInvalidParam("X-Fence-Epoch") from None
        if fence:
            kw["fence_epoch"] = fence
    kw["deadline"] = deadline_from_ctx(ctx)
    # hang the engine's lifecycle spans off the request's server span
    # (which carries the inbound W3C traceparent when one was sent)
    kw["trace_ctx"] = current_span()
    return kw


def register_generation_ws(app: Any, engine: Any, path: str = "/ws/generate",
                           supervisor: Any = None) -> None:
    """WebSocket token streaming: each inbound message is a generate
    request; tokens push back as JSON frames, then a final summary frame.
    The WS twin of the SSE stream (gofr websocket.go:30-49 handler loop ×
    the gRPC server-stream decode), for clients that want bidirectional
    framing. Wires the engine lifecycle like register_generation_routes,
    so registering only the WS surface still serves."""
    app.container.serving = engine
    _wire_lifecycle(app, engine, supervisor)

    async def ws_generate(ctx: Any):
        body = ctx.bind(GenerateRequest)
        kw = _request_kwargs(ctx, body)
        n = 0
        final: dict = {}
        try:
            async for token_id, piece in engine.stream(
                body.prompt, on_result=lambda r: final.setdefault("result", r),
                **kw,
            ):
                n += 1
                # AWAIT each frame: fire-and-forget sends could reorder
                # after the final summary frame, and a dead/closed socket
                # must surface HERE so engine.stream's finally cancels the
                # request instead of decoding into the void
                await ctx.websocket.send_async(
                    json.dumps({"token": token_id, "text": piece})
                )
        except (ConnectionError, OSError):
            # routine client departure mid-stream, not a server panic: the
            # stream generator's finally already canceled the request
            return None
        result = final.get("result")
        summary = {"done": True, "tokens": n}
        if result is not None:
            summary["finish_reason"] = result.finish_reason
        return summary

    app.websocket(path, ws_generate)


def register_requestz_routes(app: Any, engine: Any,
                             path: str = "/requestz") -> None:
    """The request flight recorder (docs/observability.md): GET
    ``/requestz`` returns every in-flight request timeline plus the
    bounded ring of recently completed ones; ``/requestz/<request_id>``
    returns one timeline in full. Pure host-side data stamped at points
    the engine thread already touches — scraping this view costs zero
    device syncs. Registered automatically by
    ``register_generation_routes``; callable directly for bare engines."""
    recorder = getattr(engine, "timeline", None)

    async def requestz(ctx: Any):
        if recorder is None:
            return {"in_flight": [], "completed": [],
                    "error": "engine has no timeline recorder"}
        raw_limit = ctx.param("limit")
        try:
            limit = int(raw_limit) if raw_limit else 64
        except ValueError:
            raise ErrorInvalidParam("limit") from None
        return recorder.snapshot(limit=limit)

    async def requestz_one(ctx: Any):
        if recorder is None:
            raise ErrorEntityNotFound("timeline", ctx.path_param("request_id"))
        raw = ctx.path_param("request_id")
        try:
            rid = int(raw)
        except ValueError:
            raise ErrorInvalidParam("request_id") from None
        tl = recorder.get(rid)
        if tl is None:
            raise ErrorEntityNotFound("timeline", raw)
        return tl.to_dict()

    app.get(path, requestz)
    app.get(path + "/{request_id}", requestz_one)


def register_kv_fetch_routes(app: Any, engine: Any,
                             path: str = "/kv/fetch") -> None:
    """Warm KV page migration, server half (docs/performance.md "KV
    reuse tiers"): POST ``{"keys": [...]}`` returns the requested
    prefix-cache entries — whole-prompt prefills and chunk-boundary K/V
    delta slabs — serialized for the wire
    (serving/prefix_index.encode_entry). Keys the cache no longer holds
    are simply absent from the response: the advertisement that named
    them was stale, and the fetching replica degrades to a compute miss.
    The device→host materialization runs on the HTTP worker thread,
    never the engine thread. Registered automatically by
    ``register_generation_routes``."""
    from gofr_tpu.serving.prefix_index import encode_entry

    MAX_KEYS = 64  # one fetch moves at most one prompt's chain

    async def kv_fetch(ctx: Any):
        body = ctx.bind(dict) or {}
        # HA plane: a fenced caller (zombie router on a pre-restart
        # membership view) is rejected 409 before any cache is touched
        fence = body.get("fence_epoch")
        if fence is not None:
            try:
                fence = int(fence)
            except (TypeError, ValueError):
                # malformed fence is the CALLER's bug: a typed 400, not
                # an uncaught ValueError surfacing as a 500
                raise ErrorInvalidParam("fence_epoch") from None
            check = getattr(engine, "check_fence", None)
            if check is not None:
                check(fence)
        keys = body.get("keys")
        if not keys or not isinstance(keys, list):
            raise ErrorMissingParam("keys")
        if len(keys) > MAX_KEYS:
            raise ErrorInvalidParam("keys")
        cache = getattr(engine, "_prefix_cache", None)
        entries: dict[str, Any] = {}
        if cache is not None:
            loop = asyncio.get_running_loop()

            # peek, never get: serving a peer must not mutate this
            # replica's LRU order or pop its host-tier copies
            read = getattr(cache, "peek", None) or cache.get

            def gather() -> dict[str, Any]:
                out: dict[str, Any] = {}
                for key in keys:
                    value = read(str(key))
                    if value is not None:
                        out[str(key)] = encode_entry(value)
                return out

            # off the event loop: encode_entry materializes device
            # arrays host-side (a sync) and base64s megabytes of slab
            entries = await loop.run_in_executor(None, gather)
        return {"entries": entries}

    app.post(path, kv_fetch)


def register_router_routes(app: Any, router: Any,
                           path: str = "/routerz") -> None:
    """The router tier's health view (docs/robustness.md "The router
    plane"): GET ``/routerz`` returns membership (per-replica state,
    heartbeat age, queue-wait, KV headroom), routing counters and the
    live knob values. Also hands the router to the container so
    ``/.well-known/health`` aggregates it, and wires start/stop into the
    app lifecycle."""
    app.container.register_datasource("router", router)

    async def routerz_handler(ctx: Any):
        return router.routerz()

    app.get(path, routerz_handler)
    app.on_start(lambda ctx: router.start())
    app.on_shutdown(router.stop)


def register_admin_drain(app: Any, path: str = "/.well-known/drain") -> None:
    """The admin drain trigger: POST flips the app to DRAINING (same path
    SIGTERM takes — new work rejected with a retriable 503, in-flight
    generations finish within the drain deadline) and schedules shutdown.
    NOT registered by default: wire it behind auth middleware — an
    unauthenticated drain endpoint is a one-request denial of service."""

    async def drain_handler(ctx: Any):
        app.drain()
        return {"status": "DRAINING"}

    app.post(path, drain_handler)


def register_embedding_routes(app: Any, bert_cfg: Any, bert_params: Any,
                              tokenizer: Any, prefix: str = "",
                              native_embedder: Any = None) -> None:
    """The /embed endpoint (BASELINE.json configs[1]): tokenize, batch to a
    padded bucket, run the jitted embedder. When ``native_embedder`` is
    given (or TPU_NATIVE_PJRT=1 builds one), requests execute through the
    native PJRT runtime instead — no JAX in the serving loop
    (serving/native_embed.py); the response's ``engine`` field reports
    which path served."""
    import jax.numpy as jnp
    import numpy as np

    from gofr_tpu.models import bert as bert_model
    from gofr_tpu.serving.tokenizer import pad_batch

    if native_embedder is None:
        from gofr_tpu.serving.native_embed import maybe_native_embedder

        native_embedder = maybe_native_embedder(
            bert_cfg, bert_params, getattr(app.container, "config", None),
            logger=getattr(app.container, "logger", None),
        )
        if native_embedder is not None and hasattr(app, "on_shutdown"):
            # the PJRT client + executable are native resources; mirror
            # register_generation_routes' engine.stop hook
            app.on_shutdown(native_embedder.close)

    async def embed(ctx: Any):
        body = ctx.bind(dict) or {}
        texts = body.get("input") or body.get("texts")
        if isinstance(texts, str):
            texts = [texts]
        if not texts:
            raise ErrorMissingParam("input")
        loop = asyncio.get_running_loop()
        if native_embedder is not None:
            emb, n_tokens = await loop.run_in_executor(
                None, lambda: native_embedder.embed_texts(tokenizer, texts)
            )
            engine = "native-pjrt"
        else:
            arr, lens = pad_batch(tokenizer, texts, bert_cfg.max_seq_len)
            emb = await loop.run_in_executor(
                None,
                lambda: np.asarray(
                    bert_model.embed(
                        bert_cfg, bert_params, jnp.asarray(arr),
                        jnp.asarray(lens, jnp.int32),
                    )
                ),
            )
            n_tokens = int(sum(lens))
            engine = "jax"
        return {
            "embeddings": emb.tolist(),
            "dim": int(emb.shape[1]),
            "engine": engine,
            "usage": {"prompt_tokens": n_tokens},
        }

    app.post(prefix + "/embed", embed)

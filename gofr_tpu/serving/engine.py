"""The continuous-batching serving engine.

Replaces the reference's per-request isolation model (handler.go:55-113, one
goroutine per request) with slot-based continuous batching: requests are
admitted into rows of a persistent device cache between decode steps, every
step serves all active rows, finished/canceled rows free their slot
immediately. The worker runs in a dedicated thread (device steps block);
tokens cross into asyncio land through ``loop.call_soon_threadsafe``.

Observability (SURVEY §5.5): queue depth, batch occupancy, TTFT and TPOT
histograms, KV slot gauge — all through the standard metrics Manager.
Backpressure: admission beyond ``max_queue`` raises ErrorTooManyRequests
(429) instead of queueing unboundedly.
"""

from __future__ import annotations

import asyncio
import collections
import concurrent.futures
import contextlib
import dataclasses
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from gofr_tpu import chaos
from gofr_tpu.http.errors import (
    ErrorDeadlineExceeded,
    ErrorEntityNotFound,
    ErrorInvalidParam,
    ErrorRequestEntityTooLarge,
    ErrorServiceUnavailable,
    ErrorStaleEpoch,
    ErrorTooManyRequests,
)
from gofr_tpu.models import llama
from gofr_tpu.native.runtime import QueueFull, Scheduler
from gofr_tpu.serving import batch as batch_ops
from gofr_tpu.serving.dedup import DedupEntry, DedupRegistry, ReplayGap, ReplayStream
from gofr_tpu.serving.shed import QueueWaitEstimator
from gofr_tpu.serving.stepplan import ChunkCursor, StepPlan, StepPlanner
from gofr_tpu.serving.timeline import TimelineRecorder
from gofr_tpu.serving.tokenizer import ByteTokenizer, Tokenizer

DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048)


@dataclasses.dataclass
class EngineConfig:
    max_slots: int = 8
    max_seq_len: int = 1024
    max_new_tokens_default: int = 128
    max_queue: int = 256
    prefill_buckets: tuple[int, ...] = DEFAULT_BUCKETS
    # DEPRECATED alias (continuous batching, docs/performance.md): caps
    # fresh admissions per step plan — the planner's max_admissions
    admission_per_step: int = 4
    # DEPRECATED alias: the native scheduler's per-admit token gate; the
    # per-iteration prefill pacing now lives in prefill_chunk_tokens /
    # step_token_budget (serving/stepplan.py)
    prefill_token_budget: int = 4096
    # continuous batching: prompts longer than this prefill in chunks of
    # this many tokens, interleaved with decode blocks in one ragged
    # dispatch — one long prefill can no longer head-of-line-block the
    # decoding rows. Also the per-iteration prefill token budget when
    # step_token_budget is 0 (auto).
    prefill_chunk_tokens: int = 256
    # explicit per-iteration token target: decode rows (rows*block_steps)
    # are reserved FIRST, prefill chunks fill the remainder. 0 = auto
    # (decode implicitly reserved + one chunk budget of prefill).
    step_token_budget: int = 0
    idle_sleep_s: float = 0.002
    # KV layout: "dense" reserves [slots, max_seq] rows; "paged" commits HBM
    # by resident tokens through the pooled page table (serving/kv_cache.py)
    kv_layout: str = "dense"
    kv_page_size: int = 16
    kv_num_pages: int | None = None  # default: slots*max_seq worth of pages
    # "int8" stores dense KV quantized (per-vector absmax; llama.KVCache):
    # half the decode HBM stream, double the resident slots per GB
    kv_dtype: str = "bf16"
    # decode tokens per device dispatch (dense AND paged layouts), i.e.
    # the N of the CPU-free N-step block: sampling + stop-condition
    # evaluation run on device, so a row that stops mid-block freezes
    # there and the host syncs ONCE per block. None = default (4; 1 when
    # spec_tokens chunks instead). docs/performance.md.
    multi_step: int | None = None
    # outstanding decode blocks before the host materializes the oldest
    # one (double-buffer depth): 1 = dispatch k+1, then consume k
    decode_sync_every: int = 1
    # prompt-prefill (prefix) cache entries; 0 disables. A repeated prompt
    # skips its entire prefill forward pass (serving/prefix_cache.py).
    # The byte bound caps HBM regardless of bucket sizes.
    prefix_cache_entries: int = 0
    prefix_cache_bytes: int = 256 * 1024 * 1024
    # host-RAM spill tier under the device prefix cache (serving/
    # kv_spill.py, docs/performance.md "KV reuse tiers"): entries the
    # device LRU evicts spill to pinned host arrays instead of dropping,
    # and a hit re-uploads asynchronously. 0 disables the tier.
    kv_spill_bytes: int = 0
    # speculative decoding (prompt-lookup drafting): K draft tokens are
    # verified per dispatch; greedy rows commit the accepted prefix + a
    # bonus token (LOSSLESS vs plain greedy), sampled rows take normal
    # single-token steps through the same chunk executable. 0 disables.
    # Mutually exclusive with multi_step > 1 (both are chunking policies).
    spec_tokens: int = 0
    spec_ngram: int = 3
    # /requestz flight recorder: completed request timelines retained in
    # the bounded ring (in-flight ones are always all visible)
    requestz_capacity: int = 256
    # load shedding: reject at submit when the EWMA queue-wait estimate
    # exceeds this many seconds (0 disables the threshold; deadline-aware
    # shedding is always on for requests that carry a deadline)
    shed_max_wait_s: float = 0.0
    # cold-start service-time prior for the shed estimator (seconds): the
    # EWMA is seeded only by completed requests, so the first burst after
    # startup otherwise estimates 0.0 wait at any queue depth and sheds
    # nothing until the queue is already doomed. 0 keeps never-shed-blind.
    shed_cold_prior_s: float = 0.0
    # graceful drain: how long in-flight generations get to finish before
    # the remainder is failed with a retriable error
    drain_deadline_s: float = 30.0
    # disaggregation role (serving/membership.py ROLES): "unified" serves
    # whole generations; "prefill" computes prompt KV and hands it off;
    # "decode" admits handed-off KV chains and streams. The role rides
    # the membership heartbeat (ReplicaAnnouncer reads engine.role) and
    # drives the router's role-split policy — the engine itself stays
    # capable of both phases (the crash-safety degrade path re-prefills
    # on a decode replica when a handoff source dies).
    role: str = "unified"
    # preemptible capacity class (docs/robustness.md "The reclamation
    # plane"): True marks this replica as running on reclaimable
    # (spot) capacity — the flag rides the membership heartbeat, the
    # router steers interactive-class tenants off it when on-demand
    # candidates exist, and `begin_reclaim` is expected to arrive.
    preemptible: bool = False
    # reclamation drain: fraction of the notice budget reserved for the
    # bulk KV evacuation AFTER in-flight work drains (the push must not
    # start with zero wire budget left)
    reclaim_evacuate_frac: float = 0.35
    # multi-tenant preemption (serving/tenancy.py, docs/serving.md
    # "Multi-tenancy"): when a strictly higher class waits and the batch
    # is full (slots or KV pages), pause the lowest-priority decode row —
    # its committed KV pages out through the prefix-cache/host-spill tier
    # and the row resumes warm with its emitted tokens intact. Off = the
    # A/B control: a tenant storm then starves higher classes.
    tenant_preempt: bool = True
    # HA plane (docs/robustness.md "The HA plane"): bounded per-request
    # emitted-frame ring for idempotency-keyed requests — a client (or a
    # second router) re-attaching after a router/transport death replays
    # the acked-but-unseen suffix token-identically instead of re-running
    # the generation. Sized in frames (tokens + 1 terminal).
    stream_replay_tokens: int = 512
    # terminal entries retained in the idempotency dedup registry (LRU);
    # live entries are bounded by in-flight requests and don't count
    idem_capacity: int = 1024
    # grace window after a keyed stream's client vanishes mid-generation:
    # the request keeps running this long awaiting a resume re-attach
    # before it is canceled like an unkeyed disconnect would be
    stream_orphan_grace_s: float = 10.0

    @classmethod
    def from_config(cls, config: Any) -> "EngineConfig":
        """Every knob is env-tunable (VERDICT r2 weak #8: ops must be able
        to trade TTFT vs TPOT — admission cadence, buckets, idle sleep —
        without a code change)."""
        num_pages = config.get("TPU_KV_NUM_PAGES")
        buckets = config.get("TPU_BATCH_PREFILL_BUCKETS")
        multi_step = config.get("TPU_BATCH_MULTI_STEP")
        return cls(
            max_slots=int(config.get_or_default("TPU_BATCH_MAX_SLOTS", "8")),
            max_seq_len=int(config.get_or_default("TPU_BATCH_MAX_TOKENS", "1024")),
            max_new_tokens_default=int(
                config.get_or_default("TPU_MAX_NEW_TOKENS_DEFAULT", "128")
            ),
            max_queue=int(config.get_or_default("TPU_BATCH_MAX_QUEUE", "256")),
            prefill_buckets=(
                tuple(int(b) for b in buckets.split(",") if b.strip())
                if buckets else DEFAULT_BUCKETS
            ),
            admission_per_step=int(
                config.get_or_default("TPU_BATCH_ADMISSION_PER_STEP", "4")
            ),
            prefill_token_budget=int(
                config.get_or_default("TPU_BATCH_PREFILL_BUDGET", "4096")
            ),
            prefill_chunk_tokens=int(
                config.get_or_default("TPU_PREFILL_CHUNK_TOKENS", "256")
            ),
            step_token_budget=int(
                config.get_or_default("TPU_STEP_TOKEN_BUDGET", "0")
            ),
            idle_sleep_s=float(config.get_or_default("TPU_IDLE_SLEEP_S", "0.002")),
            kv_layout=config.get_or_default("TPU_KV_LAYOUT", "dense"),
            kv_page_size=int(config.get_or_default("TPU_KV_PAGE_SIZE", "16")),
            kv_num_pages=int(num_pages) if num_pages else None,
            kv_dtype=config.get_or_default("TPU_KV_DTYPE", "bf16"),
            multi_step=int(multi_step) if multi_step else None,
            decode_sync_every=int(
                config.get_or_default("TPU_DECODE_SYNC_EVERY", "1")
            ),
            prefix_cache_entries=int(
                config.get_or_default("TPU_PREFIX_CACHE_ENTRIES", "0")
            ),
            prefix_cache_bytes=int(
                config.get_or_default("TPU_PREFIX_CACHE_BYTES",
                                      str(256 * 1024 * 1024))
            ),
            kv_spill_bytes=int(
                config.get_or_default("TPU_KV_SPILL_BYTES", "0")
            ),
            spec_tokens=int(config.get_or_default("TPU_SPEC_TOKENS", "0")),
            spec_ngram=int(config.get_or_default("TPU_SPEC_NGRAM", "3")),
            requestz_capacity=int(
                config.get_or_default("TPU_REQUESTZ_CAPACITY", "256")
            ),
            shed_max_wait_s=float(config.get_or_default("TPU_SHED_MAX_WAIT_S", "0")),
            shed_cold_prior_s=float(
                config.get_or_default("TPU_SHED_COLD_PRIOR_S", "0")
            ),
            drain_deadline_s=float(
                config.get_or_default("TPU_DRAIN_DEADLINE_S", "30")
            ),
            role=config.get_or_default("TPU_REPLICA_ROLE", "unified"),
            preemptible=config.get_or_default(
                "TPU_REPLICA_PREEMPTIBLE", "0"
            ) not in ("0", "false", "off"),
            reclaim_evacuate_frac=float(config.get_or_default(
                "TPU_RECLAIM_EVACUATE_FRAC", "0.35"
            )),
            tenant_preempt=config.get_or_default(
                "TPU_TENANT_PREEMPT", "1"
            ) not in ("0", "false", "off"),
            stream_replay_tokens=int(
                config.get_or_default("TPU_STREAM_REPLAY_TOKENS", "512")
            ),
            idem_capacity=int(config.get_or_default("TPU_IDEM_CAPACITY", "1024")),
            stream_orphan_grace_s=float(
                config.get_or_default("TPU_STREAM_ORPHAN_GRACE_S", "10")
            ),
        )


@dataclasses.dataclass
class GenerationResult:
    request_id: int
    text: str
    token_ids: list[int]
    prompt_tokens: int
    completion_tokens: int
    finish_reason: str  # "stop" | "length" | "kv_exhausted" | "cancel" | "deadline_exceeded" | "error"
    ttft_s: float
    duration_s: float


class _RequeueRequest(Exception):
    """Raised inside _prefill_into when a transient resource (KV pages) is
    unavailable: the request goes back to the queue head, not to an error."""


class _ThreadRetired(BaseException):
    """Raised on the engine loop thread when it discovers it has been
    replaced (a warm restart that could not join it quarantine-leaked its
    resources and started a successor). BaseException on purpose: the
    per-step ``except Exception`` recovery must NOT catch it — a retired
    thread settling futures, mutating rebuilt state, or running _fail_all
    would race the replacement thread over state it no longer owns."""


class _Request:
    __slots__ = (
        "id", "prompt_ids", "max_new_tokens", "temperature", "top_k", "top_p",
        "stream_cb", "future", "created", "first_token_at", "tokens", "slot",
        "canceled", "stop_ids", "priority", "dispatched", "deadline",
        "kv_exhausted", "timeline", "trace_ctx", "prefill_only",
        "handoff_from", "tenant", "adapter_id", "adapter_slot", "preemptions",
        "idem_key", "replay",
    )

    def __init__(self, rid: int, prompt_ids: list[int], max_new_tokens: int,
                 temperature: float, top_k: int, top_p: float,
                 stream_cb: Callable | None, future: Any, stop_ids: set[int],
                 deadline: float | None = None) -> None:
        self.id = rid
        self.prompt_ids = prompt_ids
        self.max_new_tokens = max_new_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.stream_cb = stream_cb
        self.future = future
        self.created = time.perf_counter()
        self.first_token_at: float | None = None
        self.tokens: list[int] = []
        self.slot: int | None = None
        self.canceled = False
        self.stop_ids = stop_ids
        self.priority = 0
        self.dispatched = 0  # decode steps dispatched (pipelined, ≥ consumed)
        # the row was cut short by KV-pool pressure, not by its own token
        # budget: the limit-check retire reports "kv_exhausted", a signal
        # distinct from a legitimate max-tokens "length" stop
        self.kv_exhausted = False
        # observability rails: the request's flight-recorder timeline and
        # the caller's trace context (a Span the lifecycle spans hang off)
        self.timeline: Any = None
        self.trace_ctx: Any = None
        # disaggregated serving (docs/robustness.md "The disaggregation
        # plane"): a prefill_only request retires at the first-token
        # commit with finish_reason "handoff" (its prompt KV stays in the
        # prefix cache for the decode replica to pull); handoff_from
        # names the prefill replica whose cache this request's admission
        # should pull its KV chain from, under the kv.handoff 2PC fetch.
        self.prefill_only = False
        self.handoff_from: str | None = None
        # multi-tenant plane (serving/tenancy.py + serving/lora.py):
        # tenant name (timeline/span/metric label + preemption class),
        # the request's named LoRA adapter and its pinned device-table
        # slot (0 = base), and how many times this row was preempted
        self.tenant: str | None = None
        self.adapter_id: str | None = None
        self.adapter_slot = 0
        self.preemptions = 0
        # HA plane: the request's Idempotency-Key (duplicates attach
        # instead of dispatching) and its bounded emitted-frame ring
        # (serving/dedup.py ReplayStream); both None for unkeyed requests
        self.idem_key: str | None = None
        self.replay: Any = None
        # absolute perf_counter time the caller stops caring; None = forever
        self.deadline = (self.created + deadline) if deadline else None

    def expired(self, now: float) -> bool:
        return self.deadline is not None and now > self.deadline

    def remaining(self, now: float) -> float | None:
        """Seconds left of the deadline (None when deadline-less),
        clamped at 0.0 — the budget handed to a downstream wait is
        never negative."""
        if self.deadline is None:
            return None
        return max(self.deadline - now, 0.0)

    @property
    def serve_ids(self) -> list[int]:
        """The token sequence a (re-)admission must make KV-resident:
        the prompt plus every token already emitted. Fresh requests have
        no tokens, so this IS the prompt; a preempted request resumes by
        prefilling (warm, via the chunk-boundary cache) its whole
        generated context and sampling the NEXT token — emitted tokens
        are preserved, never re-run."""
        return self.prompt_ids + self.tokens

    @property
    def new_budget(self) -> int:
        """Tokens the request may still emit (max_new minus what is
        already out) — the admission-time budget for fresh AND resumed
        requests."""
        return self.max_new_tokens - len(self.tokens)


class _Inflight:
    """A dispatched-but-not-consumed N-step decode block: the packed
    device-side result ([B, steps+2] — token columns, done flag, n_valid;
    batch_ops._pack_block) plus the (slot, request) snapshot the dispatch
    was built from. The snapshot is what makes pipelining safe — by
    consume time a slot may have been retired and even re-admitted, and
    ``slots[slot] is req`` detects that and discards the stale tokens.
    ``packed`` is the block's ONLY host-read device value, and it is
    never donated anywhere — holding it here cannot alias a donated
    carry (the round-4 use-after-donate shape)."""

    __slots__ = ("packed", "rows", "dispatched_at", "steps", "host_s",
                 "prefill_rows", "last_logits")

    def __init__(self, packed: Any, rows: list, dispatched_at: float,
                 steps: int = 1, host_s: float = 0.0,
                 prefill_rows: list | None = None,
                 last_logits: Any = None) -> None:
        self.packed = packed
        self.rows = rows
        self.dispatched_at = dispatched_at
        self.steps = steps
        self.host_s = host_s  # host-side time spent building the dispatch
        # ragged dispatches only: the prefill-chunk rows this block ran —
        # (slot, req, cursor, start, n_tokens, final, chunk_index) — plus
        # the device-resident last-position logits (retained ONLY for the
        # chunk-prefix cache; never synced here)
        self.prefill_rows = prefill_rows or []
        self.last_logits = last_logits


def _block_sync(value: Any) -> np.ndarray:
    """THE decode loop's one sanctioned host-device synchronization point:
    materialize a dispatched block's packed result. Everything the host
    needs from N device steps comes through this single call — tests
    monkeypatch it to count syncs, and gofrlint's host-sync rule keeps any
    other materialization out of the hot functions."""
    return np.asarray(value)  # gofrlint: disable=host-sync -- the one sanctioned block-sync point


class ServingEngine:
    """Owns model params + slot cache + the step loop thread."""

    def __init__(
        self,
        cfg: llama.LlamaConfig,
        params: dict,
        engine_config: EngineConfig | None = None,
        tokenizer: Tokenizer | None = None,
        *,
        metrics: Any = None,
        logger: Any = None,
        tracer: Any = None,
        seed: int = 0,
        prefix_cache: Any = None,
        kv_migrator: Any = None,
        lora: Any = None,
        tenants: Any = None,
    ) -> None:
        self.model_cfg = cfg
        self.params = params
        self.config = engine_config or EngineConfig()
        if self.config.role not in ("prefill", "decode", "unified"):
            raise ValueError(
                f"TPU_REPLICA_ROLE={self.config.role!r}: must be "
                "prefill, decode or unified"
            )
        # read by the membership announcer (heartbeat role) and /routerz
        self.role = self.config.role
        # preemptible capacity class (docs/robustness.md "The reclamation
        # plane"): rides the heartbeat; begin_reclaim() is the notice path
        self.preemptible = self.config.preemptible
        self._reclaiming = False
        self._reclaim_deadline: float | None = None  # absolute monotonic
        self._reclaim_swept = False  # batch shed done for this notice
        self.tokenizer: Tokenizer = tokenizer or ByteTokenizer(cfg.vocab_size)
        self._metrics = metrics
        self._logger = logger
        self._tracer = tracer
        if prefix_cache is not None:
            self._prefix_cache = prefix_cache  # any container Cache impl
        elif self.config.prefix_cache_entries > 0:
            if self.config.kv_spill_bytes > 0:
                # two-tier: device LRU over a host-RAM spill pool —
                # capacity evictions demote instead of dropping
                # (docs/performance.md "KV reuse tiers")
                from gofr_tpu.serving.kv_spill import TieredPrefixCache

                self._prefix_cache = TieredPrefixCache(
                    self.config.prefix_cache_entries,
                    max_bytes=self.config.prefix_cache_bytes,
                    spill_bytes=self.config.kv_spill_bytes,
                    metrics=metrics,
                    # demotion by timeline-observed reuse, not raw LRU:
                    # late-bound closure — self.timeline is built below
                    reuse_score=lambda key: self.timeline.reuse_count(key),
                )
            else:
                from gofr_tpu.serving.prefix_cache import PrefixCache

                self._prefix_cache = PrefixCache(
                    self.config.prefix_cache_entries,
                    max_bytes=self.config.prefix_cache_bytes,
                )
        else:
            self._prefix_cache = None
        # cluster-wide KV reuse (serving/prefix_index.py): when wired, a
        # local cache miss consults the distributed prefix index and
        # migrates the advertised slabs from the owning replica instead
        # of re-prefilling — advisory, every failure degrades to compute
        self._kv_migrator = kv_migrator
        # multi-tenant plane (docs/serving.md "Multi-tenancy"): the LoRA
        # adapter registry (serving/lora.py — per-request adapter_id,
        # heterogeneous-adapter batched decode) and the tenant policy
        # registry (serving/tenancy.py — priority/deadline classes,
        # token-rate budgets, the preemption ladder). Both optional; an
        # engine without them is byte-identical to the pre-tenancy one.
        self._lora = lora
        self._tenants = tenants
        if self._lora is not None and self.config.spec_tokens > 0:
            raise ValueError(
                "TPU_SPEC_TOKENS and a LoRA adapter registry are mutually "
                "exclusive: the speculative verify path predates the "
                "adapter gather (serve adapters from non-spec replicas)"
            )

        if self.config.kv_dtype not in ("bf16", "int8"):
            raise ValueError(
                f"TPU_KV_DTYPE={self.config.kv_dtype!r}: must be bf16 or int8"
            )
        if self.config.spec_tokens < 0:
            raise ValueError("TPU_SPEC_TOKENS must be >= 0")
        if (self.config.multi_step is not None and self.config.multi_step > 1
                and self.config.spec_tokens > 0):
            raise ValueError(
                "TPU_SPEC_TOKENS and TPU_BATCH_MULTI_STEP>1 are both "
                "chunking policies; enable one"
            )
        # resolve the N-step block size: an explicit TPU_BATCH_MULTI_STEP
        # wins; speculative mode chunks through the verify executable
        # instead (one position per draft); otherwise the CPU-free default
        # is a 4-step block (ROADMAP item 4 — one host sync per 4 tokens)
        if self.config.multi_step is not None:
            self._block_steps = max(1, int(self.config.multi_step))
        elif self.config.spec_tokens > 0:
            self._block_steps = 1
        else:
            self._block_steps = 4
        self._sync_every = max(1, int(self.config.decode_sync_every))
        # continuous batching (serving/stepplan.py, docs/performance.md):
        # prompts longer than one chunk prefill through the unified ragged
        # dispatch, interleaved with decode blocks. Speculative mode keeps
        # monolithic prefills — spec chunking and prefill chunking are
        # both per-dispatch chunking policies and the spec path is
        # unpipelined by design.
        self._chunk_enabled = self.config.spec_tokens == 0
        chunk = max(1, int(self.config.prefill_chunk_tokens))
        if self.config.kv_layout == "paged":
            # chunk boundaries double as chunk-prefix-cache boundaries,
            # and cached slabs scatter through whole pages — align the
            # chunk size down to the page grid
            page = max(1, int(self.config.kv_page_size))
            chunk = max(page, (chunk // page) * page)
        self._chunk_tokens = min(chunk, self.config.max_seq_len)
        self._planner = StepPlanner(
            chunk_tokens=self._chunk_tokens,
            block_steps=self._block_steps,
            step_token_budget=self.config.step_token_budget,
            max_admissions=self.config.admission_per_step,
        )
        # chunk-prefix cache entries hold raw bf16 slabs; a quantized
        # layout would re-quantize on every hit and drift — int8 engines
        # keep only the whole-prompt (single-chunk) prefix cache
        self._chunk_cache_enabled = self.config.kv_dtype != "int8"
        # the /requestz flight recorder: per-request lifecycle timelines,
        # stamped only with host-side data already materialized at the
        # existing sync points (docs/observability.md). Process-lifetime
        # like the detok executor — a warm restart must not erase the
        # record of the requests it swept.
        self.timeline = TimelineRecorder(self.config.requestz_capacity)
        # engine duty cycle: cumulative busy seconds stamped by the loop
        # thread (single writer); the device-telemetry poller reads the
        # delta over its interval (serving/device_telemetry.py)
        self._busy_s = 0.0
        self._iter_t0 = time.monotonic()  # rebased at each loop iteration
        # optional DeviceTelemetry poller backref: health_check embeds its
        # last sample, the membership announcer reads HBM headroom off it
        self.device_telemetry: Any = None
        # executable-level runtime state (KV storage, per-slot arrays,
        # pipelined-decode device state, admission scheduler) — built by
        # the shared helper so the supervisor's warm restart rebuilds
        # EXACTLY this, never a hand-copied drift of it
        self._init_runtime_state()
        self.rng = jax.random.PRNGKey(seed)
        # per-REQUEST key root for prefill first-token sampling. The
        # shared self.rng stream is split by decode/spec dispatches too,
        # so a request's draw would depend on how many device steps
        # interleaved before its admission — which depends on jit-cache
        # warmth and thread timing (the test_spec_concurrent flake: warm
        # caches shift the interleave and a sampled row draws EOS as its
        # first prefill token). fold_in(root, rid) pins each request's
        # first token to its id alone: same submit order → same tokens,
        # standalone or mid-suite, and a requeued/warm-restarted request
        # re-prefills to the identical first token.
        self._rng_root = jax.random.PRNGKey(seed)
        # detokenization + stream emission run OFF the engine thread on
        # this single-worker executor, so a slow tokenizer or a blocking
        # stream_cb overlaps the device block instead of stalling it. ONE
        # worker on purpose: per-request frame order (tokens, then the
        # terminal done frame) is the transports' contract. Process-
        # lifetime (NOT rebuilt by warm_restart — pending emissions for
        # swept requests settle harmlessly; _try_resolve is race-tolerant).
        self._detok = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serving-detok"
        )
        self._detok_depth = 0  # emissions queued, for the backlog gauge
        self._detok_mu = threading.Lock()
        # set whenever the detok queue is empty: drain() waits on it — the
        # drain contract is "generations FINISHED", and terminal
        # settlement (done frames, future resolution) rides this executor
        self._detok_idle = threading.Event()
        self._detok_idle.set()
        # speculative-decode counters (observable uplift: emitted /
        # dispatches > 1 means drafts are being accepted)
        self.spec_stats = {"dispatches": 0, "accepted": 0, "emitted": 0}
        self._by_id: dict[int, _Request] = {}  # queued + active, by request id
        self._count_lock = threading.Lock()
        self._next_id = 0
        self._running = False
        self._thread: threading.Thread | None = None
        self._wake = threading.Event()
        # request-lifecycle robustness state: the queue-wait estimator
        # behind load shedding, and the drain/wedge lifecycle flags
        self._shed = QueueWaitEstimator(
            cold_prior_s=self.config.shed_cold_prior_s
        )
        self._draining = False
        self._wedged = False
        self._stop_requested = False  # distinguishes "stopped" from "not yet started"
        self._idle = threading.Event()  # set by the loop when drained dry
        # -- engine supervision state (serving/supervisor.py) --------------
        # the loop stamps this monotonic heartbeat every iteration; the
        # supervisor's watchdog reads heartbeat_age() to detect a hung
        # dispatch that no exception will ever surface
        self.heartbeat = time.monotonic()
        self.loop_crashed = False  # the loop thread died with _running set
        self.device_poisonings = 0  # _fail_all runs that found KV poisoned
        self._restarting = False  # warm_restart in progress: submit 503s
        # first dispatch of a signature jit-compiles — slow but MOVING, and
        # the heartbeat cannot show it (the stamp lands only when the
        # dispatch returns). _cold_dispatch marks those sections so the
        # watchdog widens its stall threshold to TPU_ENGINE_COMPILE_GRACE_S
        # instead of restarting a healthy engine mid-compile. _warmed is
        # per-process knowledge (the jit cache is process-global), so it
        # deliberately survives warm_restart.
        self._warmed: set[tuple] = set()
        self._cold_key: tuple | None = None
        # serializes warm_restart against stop()/drain(): exactly one of
        # them owns the teardown — a drain racing a restart must never
        # interleave their native-resource frees. RLock: stop() may run
        # while the same thread already holds it through warm_restart's
        # failure path.
        self._lifecycle_mu = threading.RLock()
        # makes submit's register+enqueue atomic w.r.t. warm_restart's
        # request sweep and _restarting flips (see submit). Lock order:
        # _lifecycle_mu → _submit_mu → _count_lock.
        self._submit_mu = threading.Lock()
        self._supervisor: Any = None  # EngineSupervisor backref (health)
        # -- HA plane (docs/robustness.md "The HA plane") ------------------
        # fence epoch: monotonic, bumped by warm_restart / begin_reclaim /
        # announcer re-register and gossiped on the heartbeat. A caller
        # presenting fence_epoch != current is acting on a pre-restart view
        # of this replica and is rejected (ErrorStaleEpoch) before any
        # scheduler state is touched — the zombie-router fence.
        self.epoch = 1
        # idempotency dedup registry: the replica-side exactly-once
        # authority — duplicates attach to the live future or replay the
        # stored terminal; _try_resolve stays the one terminal gate.
        self._dedup = DedupRegistry(self.config.idem_capacity)

    @classmethod
    def from_checkpoint(
        cls,
        cfg: llama.LlamaConfig,
        checkpoint_dir: str,
        *,
        step: int | None = None,
        sharding: Any = None,
        seed_key: Any = None,
        **kw: Any,
    ) -> "ServingEngine":
        """Warm restart (SURVEY §5.4): build an engine whose weights come
        from the newest committed checkpoint step (or ``step``), optionally
        placed straight onto a sharding pytree. Falls back to random init
        only when ``seed_key`` is given and no checkpoint exists."""
        from gofr_tpu.checkpoint import CheckpointError, CheckpointManager

        mgr = CheckpointManager(checkpoint_dir)
        abstract = jax.eval_shape(lambda: llama.init_params(cfg, jax.random.PRNGKey(0)))
        if mgr.latest_step() is None:  # raises on a corrupt manifest
            if seed_key is None:
                raise CheckpointError(
                    f"no committed checkpoints in {checkpoint_dir} "
                    "(pass seed_key for random-init fallback)"
                )
            params = llama.init_params(cfg, seed_key)
        else:
            # corruption in an EXISTING checkpoint propagates: silently
            # serving random weights would be worse than failing startup
            params = mgr.restore(abstract, step=step, sharding=sharding)
        return cls(cfg, params, **kw)

    @classmethod
    def from_hf(
        cls,
        path: str,
        *,
        dtype: Any = None,
        sharding: Any = None,
        fs: Any = None,
        tokenizer: Any = None,
        **kw: Any,
    ) -> "ServingEngine":
        """Serve a real externally-produced checkpoint: HF-layout
        safetensors weights + the tokenizer asset next to them
        (tokenizer.json or tokenizer.model). This is the production
        startup path — VERDICT round-1 item 3."""
        from gofr_tpu.models.hf_import import load_llama_from_hf

        cfg, params = load_llama_from_hf(
            path, dtype=dtype, sharding=sharding, fs=fs
        )
        if tokenizer is None:
            from gofr_tpu.tokenizer import load_tokenizer

            try:
                tokenizer = load_tokenizer(path, fs=fs)
            except FileNotFoundError:
                tokenizer = None  # fall through to ByteTokenizer default
        return cls(cfg, params, tokenizer=tokenizer, **kw)

    # ------------------------------------------------------------- lifecycle
    def start(self) -> None:
        if self._running:
            return
        self._draining = False
        self._wedged = False
        self._stop_requested = False
        self.loop_crashed = False
        self._start_loop_thread()
        if self._logger:
            self._logger.info(
                f"serving engine started: slots={self.config.max_slots} "
                f"max_seq={self.config.max_seq_len}"
            )

    def _start_loop_thread(self) -> None:
        """Spawn the engine loop thread — shared by start() and
        warm_restart so the ordering invariants live in ONE place:
        the heartbeat is pre-stamped before the thread exists (a watchdog
        polling the gap must not see a stale age), and self._thread is
        assigned BEFORE _running flips — a thawing wedged/quarantined
        predecessor re-checks `me is self._thread` and retires, where the
        reverse order would let it pass both loop guards and run an
        iteration it no longer owns."""
        self.heartbeat = time.monotonic()
        self._idle.clear()
        thread = threading.Thread(
            target=self._loop, name="serving-engine", daemon=True
        )
        self._thread = thread
        self._running = True
        thread.start()

    def stop(self, join_timeout: float = 10.0) -> None:
        self._stop_requested = True  # BEFORE the sweep: see submit's re-check
        with self._lifecycle_mu:  # a mid-flight warm_restart finishes first
            self._stop_inner(join_timeout)

    def _stop_inner(self, join_timeout: float) -> None:
        self._running = False
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            if self._thread.is_alive():
                # a wedged engine thread is an incident, not a shrug: keep
                # the thread reference (health reports WEDGED, not DOWN)
                # and do NOT destroy the scheduler/pools it may still be
                # touching — that would turn a hang into a use-after-free
                self._wedged = True
                if self._logger:
                    self._logger.error(
                        f"serving engine thread failed to exit within "
                        f"{join_timeout:g}s; native resources left allocated, "
                        "health will report WEDGED"
                    )
                # the hung thread can never settle what's registered, and
                # a wedged engine never will either — fail every future
                # retriable NOW rather than strand its caller forever.
                # (Pure host-side future settlement, safe under a live
                # thread — unlike the native frees below, which stay
                # skipped; _try_resolve is idempotent if the thread thaws
                # mid-settle.)
                with self._count_lock:
                    leftovers = list(self._by_id.values())
                    self._by_id.clear()
                for req in leftovers:
                    # the registry outlives this engine: pins must not
                    self._lora_release(req)
                    self._settle_future(req, ErrorServiceUnavailable(
                        "engine wedged; retry on another replica",
                        retry_after=1.0,
                    ))
                # the host-side executors are still OURS under a wedged
                # engine thread (leakcheck's sweep found this path) —
                # only the NATIVE resources stay quarantined: those the
                # hung thread may be inside.
                self._shutdown_host_executors()
                return
            self._thread = None
            self._wedged = False  # a later stop() that joins clean recovers
        # the engine is terminal: stop accepting emissions BEFORE the
        # sweep, so no settle task enqueues behind the shutdown
        self._shutdown_host_executors()
        # the loop thread has exited: anything still registered can never
        # reach a terminal state through it (e.g. a submit that raced the
        # drain flag and enqueued after the loop's last scan) — fail it
        # retriable rather than leave its caller hanging forever
        with self._count_lock:
            leftovers = list(self._by_id.values())
            self._by_id.clear()
        for req in leftovers:
            # the adapter registry outlives this engine: release pins so
            # a successor engine sharing it can still recycle slots
            self._lora_release(req)
            self._settle_future(req, ErrorServiceUnavailable(
                "engine stopped before the request was served; retry",
                retry_after=1.0,
            ))
        try:
            self._sched.close()  # fallible: destroy status is checked
        finally:
            if self.paged_cache is not None:
                self.paged_cache.close()

    def _shutdown_host_executors(self) -> None:
        """Stop the engine's HOST-side workers accepting new work — the
        one shutdown sequence shared by the clean stop and the wedged
        stop (under a hung engine thread these are still ours; only the
        native scheduler/pools get quarantined). ``wait=False`` on
        purpose: already-queued detok/settle tasks and spills still run
        to completion (ThreadPoolExecutor drains its queue), so no
        retired request's future is stranded and stop() never blocks
        behind a client stream_cb. The spill tier is matched by
        isinstance, NOT duck-typed: an injected container cache may
        expose close() with datasource semantics the engine must never
        invoke on a shared resource."""
        self._detok.shutdown(wait=False)
        from gofr_tpu.serving.kv_spill import TieredPrefixCache

        if isinstance(self._prefix_cache, TieredPrefixCache):
            self._prefix_cache.close()

    def drain(self, deadline_s: float | None = None, *,
              join_timeout: float = 10.0) -> bool:
        """Coordinated graceful drain: stop admitting (submit raises a
        retriable 503), let queued + in-flight generations finish within
        ``deadline_s`` (config drain_deadline_s by default), fail whatever
        remains with a retriable ErrorServiceUnavailable, then stop the
        engine thread. Returns True when everything finished inside the
        deadline. Runs from any thread; called on SIGTERM via the app's
        shutdown hooks and from the admin drain trigger."""
        if not self._running:
            # never started (or already stopped): nothing to wait for, but
            # stop() must still run — it sweeps queued submissions and
            # releases the native scheduler + KV pools (both closes are
            # idempotent), which the old on_shutdown(engine.stop) hook did
            # unconditionally
            self.stop(join_timeout=join_timeout)
            return True
        deadline_s = (
            self.config.drain_deadline_s if deadline_s is None else deadline_s
        )
        self._draining = True
        self._idle.clear()
        self._wake.set()
        if self._logger:
            self._logger.info(f"serving engine draining (deadline {deadline_s:g}s)")
        drain_start = time.monotonic()
        drained = self._idle.wait(timeout=deadline_s)
        if drained:
            # the loop went dry, but terminal settlement (done frames,
            # future resolution, full-text detok) rides the detok
            # executor — "drained" means generations FINISHED, so the
            # queue must land inside the same deadline
            remaining = deadline_s - (time.monotonic() - drain_start)
            drained = self._detok_idle.wait(timeout=max(remaining, 0.0))
        if not drained:
            with self._count_lock:
                remainder = list(self._by_id.values())
            for req in remainder:
                # the engine thread may resolve this future concurrently;
                # _settle_future tolerates losing that race
                self._settle_future(req, ErrorServiceUnavailable(
                    "server draining; retry on another replica",
                    retry_after=1.0,
                ))
                req.canceled = True  # loop frees slot/KV through the cancel path
                try:
                    self._sched.cancel(req.id)
                except KeyError:
                    pass
            if self._logger and remainder:
                self._logger.warn(
                    f"drain deadline passed with {len(remainder)} request(s) "
                    "in flight; failed them with a retriable error"
                )
            self._wake.set()
            # give the loop a short window to reclaim the canceled slots
            # before the thread is asked to exit
            # gofrlint: disable=deadline-dropped -- post-deadline cleanup grace: the drain budget already elapsed, this constant bounds slot reclaim, not a request
            self._idle.wait(timeout=5.0)
        self.stop(join_timeout=join_timeout)
        return drained

    # ------------------------------------------------------- reclamation plane
    def reclaim_remaining_s(self) -> float | None:
        """Remaining seconds of an in-progress reclamation notice (None
        when not reclaiming) — the membership announcer puts this on the
        heartbeat so the router/autoscaler read the budget without asking
        the doomed replica a second question."""
        deadline = self._reclaim_deadline
        if not self._reclaiming or deadline is None:
            return None
        return max(deadline - time.monotonic(), 0.0)

    def begin_reclaim(self, deadline_s: float | None = None, *,
                      join_timeout: float = 10.0) -> dict[str, Any]:
        """The reclamation-notice ladder (docs/robustness.md "The
        reclamation plane"): the provider takes this machine back in
        ``deadline_s`` seconds whether or not we finish, so every second
        of the budget is spent in strict value order —

        1. stop admitting (health flips RECLAIMING — zero new routes —
           and ``submit`` raises a retriable 503 the router's candidate
           walk retries on a survivor);
        2. shed batch-class rows NOW via the preemption ladder's warm
           page-out (:meth:`_reclaim_sweep`, engine thread) — their
           committed chunks join the evacuation, the requests settle
           retriable;
        3. drain: in-flight interactive/standard streams finish inside
           the drain share of the budget, the remainder fails retriable
           (exactly :meth:`drain`'s contract);
        4. bulk-evacuate committed KV (prefix chains + paged-out spans)
           to a survivor over the migration transport
           (:meth:`_evacuate_kv`, two-phase: partial pushes are
           discarded whole);
        5. stop — the pool driver reaps a drained replica, never a
           serving one.

        Runs from any thread (the pool driver's notice thread); returns
        a summary dict. ``reclaim_evacuate_frac`` reserves the tail of
        the notice for step 4 so the push never starts with zero wire
        budget."""
        if deadline_s is None:
            deadline_s = self.config.drain_deadline_s
        notice_t0 = time.monotonic()
        with self._lifecycle_mu:
            if self._reclaiming or self._stop_requested or self._wedged:
                return {"accepted": False, "reason": "lifecycle-owned"}
            self._reclaim_deadline = notice_t0 + max(float(deadline_s), 0.0)
            self._reclaiming = True
            self._reclaim_swept = False
            # fence bump: from this instant a router still acting on the
            # pre-notice epoch is stale — its submits/cancels/KV-fetches
            # are rejected at the wire (the heartbeat gossips the new one)
            self.epoch += 1
        if self._metrics:
            self._metrics.increment_counter("app_replica_reclamations_total")
        if self._logger:
            self._logger.warn(
                f"reclamation notice: {deadline_s:g}s to drain + evacuate"
            )
        # stamp every in-flight timeline: /requestz shows which requests
        # a notice touched, whatever their terminal state turns out to be
        with self._count_lock:
            inflight = list(self._by_id.values())
        for req in inflight:
            tl = req.timeline
            if tl is not None:
                tl.stamp("reclaim")
        summary: dict[str, Any] = {
            "accepted": True, "deadline_s": float(deadline_s),
            "inflight": len(inflight),
        }
        if not self._running:
            # never started / already stopped: nothing drains, but the
            # committed cache may still hold chains worth saving
            summary["drained"] = True
            summary["evacuation"] = self._evacuate_kv(
                self._reclaim_deadline - time.monotonic()
            )
            self.stop(join_timeout=join_timeout)
            self._reclaiming = False
            self._reclaim_deadline = None
            return summary
        # drain share of the notice: the evacuation reserve comes off the
        # top so the push starts with real wire budget left
        evac_frac = min(max(self.config.reclaim_evacuate_frac, 0.0), 0.9)
        drain_budget = max(float(deadline_s) * (1.0 - evac_frac), 0.0)
        self._draining = True
        self._idle.clear()
        self._wake.set()
        drained = self._idle.wait(timeout=drain_budget)
        if drained:
            remaining = drain_budget - (time.monotonic() - notice_t0)
            drained = self._detok_idle.wait(timeout=max(remaining, 0.0))
        if not drained:
            # same contract as drain() past its deadline: the remainder
            # fails retriable — never killed mid-write, never stranded
            with self._count_lock:
                remainder = list(self._by_id.values())
            for req in remainder:
                self._settle_future(req, ErrorServiceUnavailable(
                    "replica reclaiming; retry on another replica",
                    retry_after=0.5,
                ))
                req.canceled = True
                try:
                    self._sched.cancel(req.id)
                except KeyError:
                    pass
            if self._logger and remainder:
                self._logger.warn(
                    f"reclaim drain budget passed with {len(remainder)} "
                    "request(s) in flight; failed them retriable"
                )
            self._wake.set()
            # bounded slot-reclaim grace, same as drain(): the notice
            # deadline still caps the whole ladder
            # gofrlint: disable=deadline-dropped -- post-budget cleanup grace; the evacuation step below re-derives its budget from the absolute notice deadline
            self._idle.wait(timeout=min(
                2.0, max(self._reclaim_deadline - time.monotonic(), 0.0)
            ))
        summary["drained"] = drained
        summary["evacuation"] = self._evacuate_kv(
            self._reclaim_deadline - time.monotonic()
        )
        self.stop(join_timeout=join_timeout)
        if self._metrics:
            self._metrics.record_histogram(
                "app_reclaim_drain_seconds", time.monotonic() - notice_t0
            )
        self._reclaiming = False
        self._reclaim_deadline = None
        return summary

    def _reclaim_sweep(self) -> bool:
        """Engine-thread arm of the notice ladder: shed batch-class work
        immediately so the drain budget serves interactive streams.
        Queued batch requests fail retriable without prefilling; active
        batch rows take the preemption ladder's warm page-out
        (``_preempt(reclaim=True)``) — their committed chunk spans land
        in the prefix cache, whence the bulk evacuation carries them to
        a survivor. Rows with device work in flight are skipped this
        iteration and swept on the next (preempting under an in-flight
        block would free pages the dispatched device work still
        writes)."""
        from gofr_tpu.serving.tenancy import DEADLINE_CLASSES

        threshold = DEADLINE_CLASSES["batch"][0]
        did = False
        with self._count_lock:
            queued = [
                r for r in self._by_id.values()
                if r.slot is None and not r.canceled
                and r.priority >= threshold
            ]
        for req in queued:
            self._settle_future(req, ErrorServiceUnavailable(
                "replica reclaiming; retry on another replica",
                retry_after=0.5,
            ))
            req.canceled = True
            try:
                self._sched.cancel(req.id)
            except KeyError:
                pass
            did = True
        for slot, req in enumerate(self.slots):
            if req is None or req.priority < threshold or req.canceled:
                continue
            cursor = self._cursors.get(slot)
            if self._slot_in_flight(slot, req) or (
                cursor is not None and cursor.in_flight > 0
            ):
                continue  # pipeline drains first; next iteration sweeps
            self._preempt(slot, reclaim=True)
            did = True
        return did

    def _evacuate_kv(self, deadline: float | None) -> dict[str, Any]:
        """Bulk-evacuate the committed prefix-cache contents (prefill
        chains, chunk spans, paged-out rows — device AND host tiers) to
        one surviving replica through the migrator's push side
        (:meth:`KVMigrator.evacuate_chain`). Two-phase by construction:
        the survivor commits the batch whole or not at all, so an
        interrupted push degrades to re-prefill — never a corrupt chain
        believed complete. Advisory end to end: every failure returns an
        outcome, nothing raises past here."""
        cache = self._prefix_cache
        migrator = self._kv_migrator
        out: dict[str, Any] = {"entries": 0, "committed": 0,
                               "target": None, "outcome": "skipped"}
        if (cache is None or migrator is None
                or not hasattr(migrator, "evacuate_chain")):
            if self._metrics:
                self._metrics.increment_counter(
                    "app_kv_evacuations_total", outcome="skipped"
                )
            return out
        entries: list[tuple[Any, Any]] = []
        try:
            # PrefixCache and TieredPrefixCache both enumerate via
            # keys() (the tiered one spans device + host); an injected
            # container cache without it simply has nothing to evacuate
            keys = list(cache.keys()) if hasattr(cache, "keys") else []
            reader = cache.peek if hasattr(cache, "peek") else cache.get
            for key in keys:
                val = reader(key)
                if val is None:
                    continue
                entries.append((key, val))
        except Exception:
            out["outcome"] = "harvest_error"
            if self._metrics:
                self._metrics.increment_counter(
                    "app_kv_evacuations_total", outcome="harvest_error"
                )
            return out
        out["entries"] = len(entries)
        if not entries:
            out["outcome"] = "empty"
            if self._metrics:
                self._metrics.increment_counter(
                    "app_kv_evacuations_total", outcome="empty"
                )
            return out
        try:
            committed = migrator.evacuate_chain(entries, deadline=deadline)
        except Exception:
            committed = None
        if committed:
            target, n = committed
            out.update(committed=int(n), target=target, outcome="committed")
        else:
            # no survivor accepted (all reclaiming/down, deadline spent,
            # or a chaos fault tore the push): survivors re-prefill
            out["outcome"] = "degraded"
        if self._metrics:
            self._metrics.increment_counter(
                "app_kv_evacuations_total", outcome=out["outcome"]
            )
        if self._logger:
            self._logger.info(
                f"kv evacuation: {out['outcome']} "
                f"({out['committed']}/{out['entries']} entries"
                + (f" -> {out['target']}" if out["target"] else "") + ")"
            )
        return out

    def warm_restart(self, join_timeout: float = 5.0) -> bool:
        """Self-healing restart, driven by the supervisor's watchdog when
        the loop thread hung, crashed, or keeps poisoning its device state.

        Contract (docs/robustness.md "The engine plane"):

        - in-flight generations fail RETRIABLE (503 + Retry-After /
          UNAVAILABLE) — their partial KV is gone with the pools;
        - queued, never-prefilled requests are requeued with their
          original deadlines (``_Request.deadline`` is absolute) and
          priority/FIFO order, and complete on the rebuilt engine;
        - native resources (scheduler, page allocator) are destroyed only
          when the old thread actually joined; under a still-hung thread
          they are deliberately QUARANTINE-LEAKED — same rationale as
          stop()'s wedge path: a leak is recoverable, a use-after-free
          is not;
        - executable-level state (KV pools, device-resident decode state,
          prefix cache) is rebuilt exactly the way __init__ built it.

        Returns True when the engine is serving again. Returns False
        without touching anything when drain()/stop() already owns the
        lifecycle — a restart racing a drain resolves to ONE winner.
        """
        with self._lifecycle_mu:
            if self._draining or self._stop_requested or self._wedged:
                return False  # drain/stop won the race: stand down
            # BEFORE the sweep, under the submit mutex: any submit section
            # that already registered has fully enqueued (the sweep below
            # sees it); any later one observes the flag and fails
            # retriable without touching the doomed scheduler. BOUNDED
            # acquire: a submit thread wedged inside a hung scheduler call
            # can hold the mutex forever — the healing plane must heal
            # past it, not deadlock behind it (that thread is lost to the
            # same hang being quarantined; its registered request is swept
            # and requeued like any other).
            locked = self._submit_mu.acquire(timeout=max(join_timeout, 1.0))
            try:
                self._restarting = True
                # fence bump under the same mutex: no submit can observe
                # the new scheduler with the old epoch — a caller fenced
                # on the pre-restart epoch is rejected from here on
                self.epoch += 1
            finally:
                if locked:
                    self._submit_mu.release()
            try:
                old_thread = self._thread
                self._running = False
                self._wake.set()
                joined = True
                if old_thread is not None:
                    old_thread.join(timeout=join_timeout)
                    joined = not old_thread.is_alive()
                # partition everything registered: queued-never-prefilled
                # requests survive the restart, in-flight ones cannot (their
                # KV residency dies with the pools) and fail retriable
                with self._count_lock:
                    pending = list(self._by_id.values())
                    self._by_id.clear()
                requeue: list[_Request] = []
                for req in pending:
                    # whatever the partition verdict, the row's adapter
                    # pin dies with the old batch (a requeued request
                    # re-acquires at its re-admission)
                    self._lora_release(req)
                    if not req.tokens and not req.canceled:
                        # never emitted a token: still queued, OR
                        # partially-prefilled behind a chunk cursor — its
                        # committed chunks die with the pools either way,
                        # so it requeues and re-prefills FROM CHUNK 0 on
                        # the rebuilt engine (the chunk-prefix cache, when
                        # on, makes the re-prefill cheap)
                        req.slot = None  # the old slot died with the pools
                        requeue.append(req)
                    else:
                        self._settle_future(req, ErrorServiceUnavailable(
                            "engine restarting; retry", retry_after=1.0,
                        ))
                old_sched, old_paged = self._sched, self.paged_cache
                if joined:
                    self._thread = None
                    try:
                        old_sched.close()
                    except Exception:
                        pass
                    if old_paged is not None:
                        try:
                            old_paged.close()
                        except Exception:
                            pass
                else:
                    # the hung thread may still be inside these objects:
                    # mark them abandoned, never destroy them — the loop's
                    # thread-identity guard retires the thread when it thaws
                    old_sched.leak()
                    if old_paged is not None:
                        old_paged.leak()
                    if self._logger:
                        self._logger.error(
                            f"engine thread failed to join within "
                            f"{join_timeout:g}s during warm restart; old "
                            "scheduler/KV pool quarantine-leaked"
                        )
                # the old thread's compile-grace claim dies with it: if it
                # is hung inside a cold dispatch, the key describes leaked
                # state — and the identity-gated clear in _cold_dispatch
                # means nobody else will ever drop it
                self._cold_key = None
                try:
                    # rebuild EXACTLY what __init__ built — the shared
                    # helper means a field added there cannot be missed here
                    self._init_runtime_state()
                    self._reset_prefix_cache()
                except Exception:
                    # the rebuild itself failed (a real device loss can
                    # leave the allocator refusing KV pools for a while):
                    # the requeued requests live ONLY in this local list
                    # now — settle them retriable before the failure
                    # escapes, or they'd strand forever while the
                    # supervisor retries over an empty queue
                    for req in requeue:
                        self._settle_future(req, ErrorServiceUnavailable(
                            "engine restart failed; retry", retry_after=1.0,
                        ))
                    raise
                for req in requeue:  # _by_id iteration preserved FIFO order
                    with self._count_lock:
                        self._by_id[req.id] = req
                    try:
                        self._sched.submit(
                            req.id, len(req.prompt_ids), req.max_new_tokens,
                            req.priority,
                        )
                    except Exception:
                        with self._count_lock:
                            self._by_id.pop(req.id, None)
                        self._settle_future(req, ErrorServiceUnavailable(
                            "engine restarting; retry", retry_after=1.0,
                        ))
                self.loop_crashed = False
            finally:
                # under the (bounded) mutex: a submit section sequenced
                # after this flip sees the REBUILT scheduler, never the
                # old one
                locked = self._submit_mu.acquire(
                    timeout=max(join_timeout, 1.0)
                )
                try:
                    self._restarting = False
                finally:
                    if locked:
                        self._submit_mu.release()
            # resume: a fresh loop thread over the rebuilt state
            self._start_loop_thread()
            if self._logger:
                self._logger.warn(
                    f"engine warm restart complete: {len(requeue)} queued "
                    f"request(s) requeued, {len(pending) - len(requeue)} "
                    "in-flight failed retriable"
                )
            return True

    @property
    def draining(self) -> bool:
        return self._draining

    def heartbeat_age(self) -> float:
        """Seconds since the loop thread last stamped its heartbeat. Only
        meaningful while the engine is running — the supervisor's watchdog
        compares it against TPU_ENGINE_STALL_S."""
        return time.monotonic() - self.heartbeat

    def busy_seconds(self) -> float:
        """Cumulative seconds the loop thread spent doing work (not
        waiting): the device-telemetry poller derives the engine duty
        cycle from the delta over its poll interval."""
        return self._busy_s

    def _flush_busy(self) -> None:
        """Fold the running iteration's elapsed work time into the busy
        counter and rebase. Called at each iteration's end AND from
        _finish before a terminal settlement is queued — a caller that
        observed its request complete must observe busy_seconds() > 0,
        even when the whole generation fit inside the loop's very first
        iteration (a prefill whose first token is EOS). Engine-thread
        only: _finish and the loop share the single writer."""
        now = time.monotonic()
        self._busy_s += now - self._iter_t0
        self._iter_t0 = now

    @property
    def in_cold_dispatch(self) -> bool:
        """True while the loop is inside a dispatch whose signature has
        never completed before — i.e. one that may be jit-compiling. The
        watchdog widens its stall threshold to compile_grace_s for these:
        a multi-second first compile is progress, not a hang."""
        return self._cold_key is not None

    @contextlib.contextmanager
    def _cold_dispatch(self, *key: Any) -> Any:
        """Context manager marking a possibly-compiling dispatch section
        (keyed by executable signature). The key is warmed only when the
        section completes, so a dispatch that faults keeps its grace."""
        if key in self._warmed:
            yield
            return
        self._cold_key = key
        try:
            yield
        finally:
            # only the loop's current owner may clear the marker: a
            # retired (quarantined) thread thawing out of its dispatch
            # here must not strip the REPLACEMENT thread's in-flight
            # compile grace — the watchdog would read a healthy first
            # compile as a stall and burn restart budget on it. (With no
            # loop thread at all — direct calls, tests — the caller owns
            # the marker and clears it.)
            if self._thread is None or threading.current_thread() is self._thread:
                self._cold_key = None
        # warming is process-global truth (the jit cache outlives the
        # thread), so even a retired thread's completed compile counts
        self._warmed.add(key)

    def _check_retired(self) -> None:
        """Quarantine guard for the loop thread: after a warm restart that
        could not join it, self._thread names a successor — the old thread
        must unwind NOW (without settling futures or touching rebuilt
        state), not at the next iteration top."""
        if threading.current_thread() is not self._thread:
            raise _ThreadRetired()

    def health_check(self) -> dict[str, Any]:
        active = sum(1 for s in self.slots if s is not None)
        stats = self._sched.stats()
        details: dict[str, Any] = {
            "slots_active": active,
            "slots_total": self.config.max_slots,
            "queue_depth": stats["queue_depth"],
            "scheduler_backend": self._sched.backend,
            "total_admitted": stats["total_admitted"],
            "kv_layout": self.config.kv_layout,
            "shed": self._shed.snapshot(),
            # HA plane: the fence epoch rides the heartbeat so routers
            # fence their per-attempt calls on the replica's current view
            "epoch": self.epoch,
            "dedup": self._dedup.stats(),
        }
        if self._running:
            details["heartbeat_age_s"] = round(self.heartbeat_age(), 3)
        if self.paged_cache is not None and self._running:
            details["kv_pages"] = self.paged_cache.stats()
        if self._prefix_cache is not None:
            details["prefix_cache"] = self._prefix_cache.stats()
        if self._lora is not None:
            details["lora"] = self._lora.residency()
        if self._tenants is not None:
            details["tenants"] = self._tenants.snapshot()
        # the flight recorder's compact latency view: median TTFT /
        # queue-wait / e2e over the completed ring (phase detail per
        # request lives at /requestz)
        details["request_latency"] = self.timeline.latency_summary()
        if self.device_telemetry is not None:
            # per-device HBM used/limit + engine duty cycle, as last
            # polled (serving/device_telemetry.py) — the heartbeat
            # announcer reads its HBM headroom from the same sample
            details["device"] = self.device_telemetry.last_sample()
        if self.preemptible:
            details["preemptible"] = True
        if self._reclaiming:
            remaining = self.reclaim_remaining_s()
            details["reclaim"] = {
                "deadline_s": round(remaining, 3)
                if remaining is not None else None,
            }
        sup = self._supervisor
        if sup is not None:
            details["supervisor"] = sup.snapshot()
        sup_state = sup.state if sup is not None else None
        # UP → DRAINING → DOWN is the normal lifecycle; WEDGED means stop()
        # timed out joining the engine thread OR the supervisor spent its
        # restart budget — the process needs replacing, which is exactly
        # why it must not masquerade as a clean DOWN. SUSPECT/RESTARTING
        # are the supervisor's self-healing window.
        if self._wedged or sup_state == "WEDGED":
            status = "WEDGED"
        elif self._restarting or sup_state == "RESTARTING":
            status = "RESTARTING"
        elif not self._running:
            status = "DOWN"
        elif self._reclaiming:
            # a reclamation notice outranks a plain drain: same zero-new-
            # routes contract, plus a hard external deadline the router
            # and autoscaler read off the beat
            status = "RECLAIMING"
        elif self._draining:
            status = "DRAINING"
        elif sup_state == "SUSPECT":
            status = "SUSPECT"
        else:
            status = "UP"
        return {"status": status, "details": details}

    # ------------------------------------------------------------- submission
    def submit(
        self,
        prompt: str | list[int],
        *,
        max_new_tokens: int | None = None,
        temperature: float = 0.0,
        top_k: int = 0,
        top_p: float = 1.0,
        priority: int = 0,
        deadline: float | None = None,
        stream_cb: Callable[[int, str, bool], None] | None = None,
        trace_ctx: Any = None,
        prefill_only: bool = False,
        handoff_from: str | None = None,
        tenant: str | None = None,
        adapter_id: str | None = None,
        idempotency_key: str | None = None,
        fence_epoch: int | None = None,
    ) -> Any:
        """Thread-safe submit. Returns a concurrent Future resolving to
        GenerationResult. ``stream_cb(token_id, text_piece, done)`` fires per
        token from the engine thread. Lower ``priority`` runs first.
        ``deadline`` is the caller's remaining budget in seconds (from the
        HTTP ``X-Request-Timeout`` header or the gRPC deadline): a request
        still queued when it passes is dropped without prefilling (504), one
        mid-stream retires with finish reason ``deadline_exceeded``.
        ``trace_ctx`` is the caller's parent Span (the HTTP/gRPC server
        span or the router's attempt span): the request's lifecycle spans
        (queue → prefill/decode/detok) hang off it, and the trace id lands
        in the request's ``/requestz`` timeline.

        HA plane: ``idempotency_key`` makes the submit exactly-once — a
        duplicate attaches to the live request's future (and replays the
        emitted-frame suffix into its ``stream_cb``) or replays the stored
        terminal; it never dispatches twice. ``fence_epoch`` is checked
        against ``self.epoch`` BEFORE any other gate: a stale caller is
        rejected (409) without touching scheduler state."""
        import concurrent.futures

        # the fence is absolutely first: a zombie router acting on a
        # pre-restart membership view must not observe queue depth, charge
        # tenant budgets, or allocate a request id
        self.check_fence(fence_epoch)
        idem_key = str(idempotency_key) if idempotency_key else None
        if idem_key:
            # duplicate fast path BEFORE the draining/restarting/shed
            # gates: attaching to (or replaying) work this replica already
            # owns is not new work — a draining replica still honors it
            entry = self._dedup.lookup(idem_key)
            if entry is not None:
                return self._attach_duplicate(entry, stream_cb)

        if self._draining:
            # retriable: the LB should route the retry to another replica
            raise ErrorServiceUnavailable(
                "server draining; retry on another replica", retry_after=1.0
            )
        if self._restarting:
            # the supervisor is mid warm-restart: the scheduler/KV pools are
            # being replaced under us — retriable, the restart is seconds
            raise ErrorServiceUnavailable(
                "engine restarting; retry", retry_after=1.0
            )

        # -- tenancy gates (serving/tenancy.py, docs/serving.md) -----------
        # resolve the tenant's SLO class FIRST: its priority drives the
        # scheduler + preemption ladder, its deadline class fills in a
        # missing deadline (so expired-while-queued and mid-stream expiry
        # work for every tenant), and its token-rate budget rejects an
        # over-budget tenant in microseconds with 429 + Retry-After — the
        # same shed contract clients and routers already key on.
        # TENANTLESS requests are untouched: naming a tenant is the
        # opt-in — merely wiring a registry must not inject deadlines or
        # demote priority on existing anonymous traffic.
        if self._tenants is not None and tenant:
            policy = self._tenants.policy(tenant)
            if priority == 0:
                priority = int(policy.priority or 0)
            if deadline is None and policy.deadline_s:
                deadline = float(policy.deadline_s)
        if adapter_id and (
            self._lora is None or not self._lora.known(adapter_id)
        ):
            # a client error either way: no registry, or an id the
            # registry has never seen — 400, never a retriable
            raise ErrorInvalidParam("adapter_id")

        # load shedding BEFORE any per-request work: rejecting here costs
        # microseconds; admitting a request that will wait past its
        # deadline costs a 504 after seconds of queueing. ONE stats
        # snapshot serves both the estimate and the queue-depth gauge —
        # stats() takes the scheduler mutex the engine thread contends on.
        depth = self._sched.stats()["queue_depth"]
        shed_depth = depth
        if self._tenants is not None:
            # CLASS-AWARE wait estimate: the priority queue admits this
            # request ahead of every lower class, so only same-or-higher
            # class waiters stand between it and a slot — a batch-tenant
            # flood must raise the batch class's estimate (and shed IT),
            # never shed the interactive tenant the flood cannot delay
            # (the preemption ladder frees the slot itself)
            with self._count_lock:
                shed_depth = sum(
                    1 for r in self._by_id.values()
                    if r.slot is None and not r.canceled
                    and r.priority <= priority
                )
        est_wait = self._shed.estimate_wait(shed_depth, self.config.max_slots)
        if self._metrics:
            self._metrics.set_gauge("app_estimated_queue_wait_seconds", est_wait)
        shed_cap = self.config.shed_max_wait_s
        if (deadline is not None and 0 < deadline < est_wait) or (
            shed_cap > 0 and est_wait > shed_cap
        ):
            if self._metrics:
                self._metrics.increment_counter("app_requests_shed_total")
            raise ErrorTooManyRequests(
                f"estimated queue wait {est_wait:.2f}s exceeds "
                + (f"request deadline {deadline:.2f}s"
                   if deadline is not None and 0 < deadline < est_wait
                   else f"shed threshold {shed_cap:.2f}s"),
                retry_after=est_wait,
            )

        with self._count_lock:
            self._next_id += 1
            rid = self._next_id

        prompt_ids = (
            self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        )
        # keep the TAIL within the sequence budget. Short prompts keep the
        # additional largest-bucket clamp (the monolithic prefill path
        # cannot scatter past its biggest bucket); prompts that route
        # through chunked prefill have no bucket — any length up to the
        # sequence cap chunks through (docs/performance.md).
        max_prompt = self.config.max_seq_len - 1
        if not self._route_chunked(min(len(prompt_ids), max_prompt)):
            max_prompt = min(max_prompt, max(self._buckets()))
        prompt_ids = prompt_ids[-max_prompt:]
        budget = self.config.max_seq_len - len(prompt_ids)
        max_new = min(max_new_tokens or self.config.max_new_tokens_default, budget)

        if self._tenants is not None:
            # token-rate budget: prompt + requested generation charged
            # against the tenant's bucket — over budget is a 429 the
            # retry ladder (and the router's candidate walk) understands
            ok, retry_after = self._tenants.admit(
                tenant, len(prompt_ids) + max_new
            )
            if not ok:
                if self._metrics:
                    self._metrics.increment_counter(
                        "app_requests_shed_total",
                        tenant=tenant or "default",
                    )
                raise ErrorTooManyRequests(
                    f"tenant {tenant or 'default'} over its token-rate "
                    "budget",
                    retry_after=max(retry_after, 0.05),
                )

        if adapter_id:
            from gofr_tpu.serving.lora import UnknownAdapter

            try:
                # submit-time prefetch AFTER every rejection gate: the
                # async upload (lora-upload worker, lora.upload chaos
                # point) runs while the request queues, so admission
                # normally finds the adapter resident — and shed/over-
                # budget traffic never touches (or thrashes) the device
                # adapter table
                self._lora.prefetch(adapter_id)
            except UnknownAdapter:  # deregistered since the gate above
                raise ErrorInvalidParam("adapter_id") from None

        claim_entry: DedupEntry | None = None
        if idem_key:
            # the atomic claim, AFTER the admission gates a fresh request
            # must pass: exactly one concurrent submit per key owns the
            # dispatch; a racer that lost between the lookup above and
            # here attaches to the owner instead
            owner, claim_entry = self._dedup.claim(idem_key)
            if not owner:
                return self._attach_duplicate(claim_entry, stream_cb)

        req: _Request | None = None
        try:
            future: Any = concurrent.futures.Future()
            future.request_id = rid
            req = _Request(
                rid, prompt_ids, max_new, temperature, top_k, top_p, stream_cb, future,
                stop_ids={self.tokenizer.eos_id}, deadline=deadline,
            )
            req.priority = priority
            if claim_entry is not None:
                # every emission path (detok token frames, all done-frame
                # settlement paths) flows through the bounded seq-numbered
                # ring so a resume can replay the acked-but-unseen suffix;
                # the original stream_cb still sees the plain 3-arg wire
                req.idem_key = idem_key
                req.replay = ReplayStream(self.config.stream_replay_tokens)
                req.stream_cb = req.replay.wrap(stream_cb)
                claim_entry.publish(rid, future, req.replay)
            req.prefill_only = bool(prefill_only)
            req.handoff_from = handoff_from
            req.tenant = tenant
            req.adapter_id = adapter_id or None
            # flight-recorder timeline + the queue span, BEFORE any admission
            # gate that can still reject: a shed/stopped request leaves a
            # terminal timeline too (the chaos tier audits exactly-one-
            # terminal over every accepted request id)
            tl = self.timeline.begin(rid, prompt_tokens=len(prompt_ids))
            tl.tenant = tenant
            req.timeline = tl
            req.trace_ctx = trace_ctx
            if self._tracer is not None:
                qspan = self._tracer.start_span(
                    "engine.queue", parent=trace_ctx, kind="internal",
                    activate=False,
                )
                qspan.set_attribute("request.id", rid)
                qspan.set_attribute("tokens.prompt", len(prompt_ids))
                if tenant:
                    qspan.set_attribute("tenant", tenant)
                if adapter_id:
                    qspan.set_attribute("lora.adapter", adapter_id)
                tl.open_span("queue", qspan)
            elif trace_ctx is not None:
                tl.trace_id = trace_ctx.trace_id
            # registration + enqueue are ATOMIC w.r.t. warm_restart (same
            # mutex): either the restart's sweep sees this request and
            # requeues/settles it, or this section observes _restarting and
            # fails retriable BEFORE touching the scheduler the restart is
            # about to replace. Without the mutex a submit could register
            # after the sweep yet enqueue into the old (about-to-be-leaked)
            # scheduler — stranding a deadline-less future forever — or
            # enqueue the same rid into the rebuilt scheduler a second time.
            # _restarting cannot flip while this section holds the mutex:
            # warm_restart flips it under the same lock.
            # bounded acquire: if another submit is wedged INSIDE a hung
            # scheduler call while holding the mutex, fail fast and retriable
            # instead of piling every client thread up behind it forever
            # gofrlint: disable=deadline-dropped -- deliberate constant: bounds a wedged-scheduler pile-up with a fast retriable 503; the request's own deadline is enforced by expired-while-queued
            if not self._submit_mu.acquire(timeout=5.0):
                raise ErrorServiceUnavailable(
                    "engine busy; retry on another replica", retry_after=1.0
                )
            try:
                if self._restarting:
                    raise ErrorServiceUnavailable(
                        "engine restarting; retry", retry_after=1.0
                    )
                with self._count_lock:
                    self._by_id[rid] = req
                try:
                    self._sched.submit(rid, len(prompt_ids), max_new, priority)
                except QueueFull:
                    with self._count_lock:
                        self._by_id.pop(rid, None)
                    if self._metrics:
                        self._metrics.increment_counter("app_requests_shed_total")
                    raise ErrorTooManyRequests(
                        retry_after=max(est_wait, 1.0)
                    ) from None
                except RuntimeError:
                    # "scheduler closed": lost the race against a concurrent
                    # stop()
                    with self._count_lock:
                        self._by_id.pop(rid, None)
                    raise ErrorServiceUnavailable(
                        "server stopped; retry on another replica",
                        retry_after=1.0,
                    ) from None
                if self._stop_requested:
                    # raced a concurrent stop(): the flag (monotonic, unlike
                    # _restarting) flips BEFORE the leftover sweep, so either
                    # that sweep saw this registration or this re-check sees
                    # the flip — the request cannot strand. (A not-yet-started
                    # engine is fine: submit-then-start is supported.)
                    with self._count_lock:
                        self._by_id.pop(rid, None)
                    try:
                        self._sched.cancel(rid)
                    except Exception:
                        pass
                    raise ErrorServiceUnavailable(
                        "server stopped; retry on another replica",
                        retry_after=1.0,
                    )
            finally:
                self._submit_mu.release()
        except Exception as exc:
            # the caller gets the raise, but the accepted request id still
            # owes a terminal timeline — settle the (discarded) future
            # through the same gate every other path uses. _try_resolve is
            # exactly-once (a stop/restart sweep that already settled this
            # registration cannot double-mark the terminal) AND the one
            # place a keyed failure forgets its dedup entry — the try
            # opens right at the claim-to-publish window, so a failure
            # ANYWHERE after the claim (request construction, timeline
            # begin, tracer spans, the scheduler section) cannot strand a
            # live entry with a never-resolving future that every later
            # duplicate of this key would attach to and hang on.
            if req is not None:
                self._try_resolve(req, exc=exc)
            if claim_entry is not None and (req is None or req.idem_key is None):
                # failed before the key was wired onto the request:
                # forget directly so the next submit re-runs fresh
                self._dedup.forget(idem_key)
            raise
        self._observe_queue(depth + 1)  # this request just joined the queue
        self._wake.set()
        return future

    async def generate(self, prompt: str | list[int], **kw: Any) -> GenerationResult:
        """Asyncio-friendly submit + await."""
        future = self.submit(prompt, **kw)
        return await asyncio.wrap_future(future)

    async def stream(self, prompt: str | list[int], *,
                     on_result: Callable[[GenerationResult], None] | None = None,
                     **kw: Any):
        """Async iterator of (token_id, text_piece) tuples. ``on_result``
        fires with the final GenerationResult after the last token, so
        transports can emit a terminal frame (finish reason, usage) without
        re-plumbing the future."""
        loop = asyncio.get_running_loop()
        q: asyncio.Queue = asyncio.Queue()

        def cb(token_id: int, piece: str, done: bool) -> None:
            loop.call_soon_threadsafe(q.put_nowait, (token_id, piece, done))

        future = self.submit(prompt, stream_cb=cb, **kw)
        try:
            while True:
                token_id, piece, done = await q.get()
                if done:
                    break
                yield token_id, piece
            result = await asyncio.wrap_future(future)
            if on_result is not None:
                on_result(result)
        finally:
            # client disconnected mid-stream (GeneratorExit) or consumer
            # stopped: free the slot instead of decoding into the void —
            # the reference's ErrorClientClosedRequest analogue for batched
            # serving (http/errors.go 499)
            if not future.done():
                self.cancel(future.request_id)

    def cancel(self, request_id: int, *, fence_epoch: int | None = None) -> None:
        """Mark a queued or running request canceled; a running one frees
        its slot on the next step, a queued one resolves at admission.
        ``fence_epoch`` rejects a stale caller (409) before any state is
        touched — a fenced zombie router must not cancel work a current
        router legitimately owns."""
        self.check_fence(fence_epoch)
        with self._count_lock:
            req = self._by_id.get(request_id)
        if req is not None:
            req.canceled = True
        try:
            self._sched.cancel(request_id)  # no-op if already admitted
        except KeyError:
            pass
        self._wake.set()

    # --------------------------------------------------- HA plane (resume)
    def check_fence(self, fence_epoch: int | None) -> None:
        """Reject a caller whose fence epoch is not this engine's current
        one. The epoch bumps on warm_restart / begin_reclaim / announcer
        re-register and gossips on the heartbeat; ``None`` (an unfenced
        caller) always passes — fencing is the router tier's opt-in."""
        if fence_epoch is not None and int(fence_epoch) != self.epoch:
            raise ErrorStaleEpoch(
                f"fence epoch {int(fence_epoch)} != engine epoch "
                f"{self.epoch}; refresh membership"
            )

    def _attach_duplicate(self, entry: DedupEntry, stream_cb: Callable | None,
                          last_seq: int = 0) -> Any:
        """A duplicate idempotency-keyed submit: attach, never dispatch.

        Live entry → the ORIGINAL future (exactly one terminal, one
        ``_try_resolve`` win) with the unseen frame suffix replayed into
        ``stream_cb``; terminal entry → a resolved future replaying the
        stored result. A live generation whose suffix fell out of the
        bounded replay window attaches WITHOUT replay — truncated stream,
        full result via the future — because the keyed-submit contract is
        "a retry dedups safely", never a hard error; the 404 on an
        evicted window belongs to the explicit ``Last-Event-ID`` resume
        wire only (``resume``), where the client asked for a
        token-identical suffix by name. The claim-to-publish window is
        closed by waiting on ``entry.ready``."""
        import concurrent.futures

        # bounds only the owner's claim-to-publish window (microseconds
        # of admission code); failure is a fast retriable 503
        if not entry.ready.wait(timeout=5.0) or (
            entry.future is None and not entry.terminal
        ):
            # the owner is still admitting (or its admission failed and
            # the key was forgotten): retriable — the retry re-runs fresh
            raise ErrorServiceUnavailable(
                "idempotent twin still admitting; retry", retry_after=0.5
            )
        if entry.terminal:
            fut: Any = concurrent.futures.Future()
            fut.request_id = entry.rid
            if stream_cb is not None:
                self._replay_result(
                    entry, last_seq,
                    lambda _seq, tid, piece, done: stream_cb(tid, piece, done),
                )
            fut.set_result(entry.result)
            return fut
        if stream_cb is not None and entry.replay is not None:

            def wire(_seq: int, tid: int, piece: str, done: bool) -> None:
                stream_cb(tid, piece, done)

            try:
                entry.replay.attach(last_seq, wire)
            except ReplayGap:
                # truncated live attach: frames from NOW on flow to this
                # client, and the shared future still resolves with the
                # FULL result. The mirror future carries the attach point
                # (``stream_base_seq``) so the SSE transport can stamp
                # TRUE engine sequence numbers on the truncated stream —
                # a later Last-Event-ID from this client then names real
                # frames, preserving exactly-once wire delivery. A fresh
                # mirror (not the shared owner future) keeps the
                # attribute per-attachment: concurrent gap-attaches at
                # different ring positions must not clobber each other.
                base = entry.replay.subscribe(wire)
                owner_future = entry.future
                fut = concurrent.futures.Future()
                fut.request_id = entry.rid
                fut.stream_base_seq = base

                def _mirror(src: Any) -> None:
                    try:
                        src_exc = src.exception()
                        if src_exc is not None:
                            fut.set_exception(src_exc)
                        else:
                            fut.set_result(src.result())
                    except Exception:
                        pass  # mirror already settled / owner canceled

                owner_future.add_done_callback(_mirror)
                return fut
        return entry.future

    def _replay_result(self, entry: DedupEntry, last_seq: int,
                       cb: Callable[[int, int, str, bool], None]) -> None:
        """Replay a stored terminal's token frames past ``last_seq``.

        Ring seq i+1 is provably token_ids[i]: the ring is fed by the
        single detok worker in emission order, stop tokens are never
        emitted as frames, and the terminal frame takes seq N+1. Pieces
        come from the entry's ``ReplayStream``, which retained every
        emitted piece — the replay is TEXT-identical to the original
        stream, not merely token-identical (a per-token re-decode can
        differ from incremental detok on multi-token unicode/byte
        sequences). The re-decode survives only as a defensive fallback
        for entries with no retained pieces (injected doubles)."""
        result = entry.result
        token_ids = list(result.token_ids)
        pieces: list[str] | None = None
        if entry.replay is not None and len(entry.replay.pieces) == len(token_ids):
            pieces = list(entry.replay.pieces)
        for i, tid in enumerate(token_ids):
            seq = i + 1
            if seq > last_seq:
                piece = (
                    pieces[i] if pieces is not None
                    else self.tokenizer.decode([tid])
                )
                cb(seq, tid, piece, False)
        done_seq = len(token_ids) + 1
        if done_seq > last_seq:
            cb(done_seq, -1, "", True)

    def resume(self, idempotency_key: str, *, last_seq: int = 0,
               stream_cb: Callable[[int, int, str, bool], None] | None = None,
               fence_epoch: int | None = None) -> Any:
        """Re-attach to an idempotency-keyed request's token stream.

        The resume wire (``Last-Event-ID`` re-attach): replays every
        frame with ``seq > last_seq`` — token-identically, from the
        bounded ring (live) or the stored terminal — then rides the
        still-running generation. ``stream_cb`` here is the 4-arg frame
        wire ``(seq, token_id, piece, done)`` so transports can stamp
        ``id:`` lines without re-counting. Unknown key → 404 (nothing to
        resume — the client must submit, which dedups safely anyway);
        evicted suffix → 404 on the replay window (a token-identical
        resume is impossible and the engine will not re-generate)."""
        chaos.maybe_fail("stream.resume")
        self.check_fence(fence_epoch)
        key = str(idempotency_key)
        entry = self._dedup.lookup(key)
        if entry is None:
            raise ErrorEntityNotFound("idempotency_key", key)
        # bounds only the owner's claim-to-publish window; failure is a
        # fast retriable 503
        if not entry.ready.wait(timeout=5.0) or (
            entry.future is None and not entry.terminal
        ):
            raise ErrorServiceUnavailable(
                "request still admitting; retry", retry_after=0.5
            )
        import concurrent.futures

        if entry.terminal:
            if stream_cb is not None:
                self._replay_result(entry, int(last_seq), stream_cb)
            fut: Any = concurrent.futures.Future()
            fut.request_id = entry.rid
            fut.set_result(entry.result)
            return fut
        if stream_cb is not None:
            try:
                entry.replay.attach(int(last_seq), stream_cb)
            except ReplayGap:
                raise ErrorEntityNotFound("replay window", key) from None
        return entry.future

    def orphan(self, request_id: int, grace_s: float | None = None) -> None:
        """ONE resumable (keyed) client vanished mid-stream: release its
        subscription and, if it was the last one, park the generation for
        a bounded grace window instead of canceling.

        A keyed request can have several live attachments at once — the
        owner's stream plus duplicate/resume attachments through any
        router — and one client's disconnect must never kill another
        client's in-flight generation: the reaper stands down while ANY
        subscriber remains attached. A resume within the window
        re-attaches and rides on; if nobody is attached when the timer
        fires (and no newer attach superseded this orphaning), it cancels
        the request exactly like an unkeyed disconnect. Unkeyed requests
        don't come here — their transports cancel directly."""
        grace = grace_s if grace_s is not None else self.config.stream_orphan_grace_s
        with self._count_lock:
            req = self._by_id.get(request_id)
        if req is None:
            return
        if req.replay is None:
            self.cancel(request_id)
            return
        remaining = req.replay.release()
        if remaining > 0:
            return  # another client still rides this generation
        if grace <= 0:
            self.cancel(request_id)
            return
        attaches_at_orphan = req.replay.attaches

        def _reap() -> None:
            if req.future.done():
                return
            if req.replay.attaches > attaches_at_orphan:
                return  # someone resumed; their disconnect re-orphans
            if req.replay.subscribers > 0:
                return  # a client re-attached and is still connected
            self.cancel(request_id)

        timer = threading.Timer(grace, _reap)
        timer.daemon = True
        timer.start()

    def dedup_stats(self) -> dict[str, int]:
        """Registry counters for /routerz-style introspection and tests."""
        return self._dedup.stats()

    # ------------------------------------------------------------- the loop
    def _loop(self) -> None:
        me = threading.current_thread()
        try:
            self._loop_body(me)
        except _ThreadRetired:
            return  # quarantined thread thawed: exit, touch nothing
        except BaseException as exc:
            # an escape from the body (the engine.step chaos point sits
            # OUTSIDE the per-step recovery, like a C-extension aborting
            # mid-dispatch would) is an unhandled loop exit: flag it so the
            # supervisor's watchdog can tell "crashed" from "stopped"
            if self._running and me is self._thread:
                self.loop_crashed = True
                if self._logger:
                    import traceback

                    self._logger.error(
                        "serving engine loop thread died",
                        stack=traceback.format_exc(limit=20),
                    )
            if not isinstance(exc, Exception):
                raise  # SystemExit/KeyboardInterrupt must propagate
            # ordinary exceptions end here: the crash flag + log ARE the
            # signal — re-raising would only spam the thread excepthook

    def _loop_body(self, me: threading.Thread) -> None:
        cfg = self.config
        # the identity guard retires a quarantined thread: after a warm
        # restart that could not join it, self._thread points at the NEW
        # loop thread — the old one must exit the moment it thaws instead
        # of racing the replacement over rebuilt state
        while self._running and me is self._thread:
            self.heartbeat = self._iter_t0 = time.monotonic()
            chaos.maybe_fail("engine.step")
            if not self._running or me is not self._thread:
                # stopped or replaced while hung at the chaos point: re-check
                # the loop condition instead of running one doomed iteration
                # (a warm_restart waiting in join() has already swept the
                # queue this iteration would admit from)
                continue
            try:
                # the preemption ladder runs BEFORE the plan: a freed
                # slot is admitted in this same iteration, so a waiting
                # higher class pays at most one loop latency
                did_work = self._maybe_preempt()
                if self._reclaiming:
                    # a reclamation notice sheds batch-class rows NOW
                    # (warm page-out, retriable failure) so the remaining
                    # drain budget serves interactive streams only
                    did_work |= self._reclaim_sweep()
                plan = self._plan_step()
                did_work |= self._admit(plan)
                if any(s is not None for s in self.slots):
                    did_work |= self._decode_step(plan)
                elif self._inflight_q:
                    # drain: every row of the in-flight blocks retired while
                    # they ran; their tokens are stale by construction
                    self._consume_block(self._inflight_q.popleft())
                    did_work = True
                else:
                    self._last_consume_t = None  # idle gap must not skew TPOT
                # duty-cycle accounting: the iteration so far was WORK
                # (dispatches, syncs, bookkeeping); the wake wait below is
                # idle. The telemetry poller divides the busy delta by
                # wall time (app_engine_duty_cycle). _iter_t0, not the
                # heartbeat — progress points re-stamp that mid-iteration,
                # and _finish flushes the running iteration's slice early
                # so a settled request always implies recorded busy time.
                self._flush_busy()
                if not did_work:
                    if (self._draining and not self._inflight_q
                            and not any(s is not None for s in self.slots)
                            and self._sched.stats()["queue_depth"] == 0):
                        # drained dry: every accepted request reached a
                        # terminal state; drain() is waiting on this
                        self._idle.set()
                    self._wake.wait(timeout=0.05)
                    self._wake.clear()
            except Exception as exc:  # the step must never kill the loop
                # a retired thread's step error is noise from quarantined
                # state — it must not _fail_all (that would sweep the
                # REPLACEMENT engine's requests) or keep looping
                self._check_retired()
                if self._logger:
                    import traceback

                    self._logger.error(
                        f"serving engine step error: {exc}",
                        stack=traceback.format_exc(limit=20),
                    )
                self._fail_all(exc)
                # gofrlint: disable=blocking-call -- error backoff in the
                # dedicated engine thread, bounded by idle_sleep_s
                time.sleep(cfg.idle_sleep_s)

    # -- admission -------------------------------------------------------------
    def _plan_step(self) -> StepPlan:
        """Assemble this iteration's step plan (serving/stepplan.py):
        decode rows reserved first, chunk grants for partially-prefilled
        cursors, an admission quota out of the leftover budget."""
        decode_rows = sum(
            1 for slot, req in enumerate(self.slots)
            if req is not None and slot not in self._cursors
        )
        free_slots = sum(1 for s in self.slots if s is None)
        plan = self._planner.plan(
            decode_rows=decode_rows,
            cursors=list(self._cursors.values()),
            free_slots=free_slots,
            queue_depth=self._sched.pending(),
        )
        if self._metrics:
            # set on CHANGE (including the drop back to zero at idle —
            # a frozen non-zero gauge would report phantom load forever),
            # skipped in steady state to keep per-iteration host cost flat
            snapshot = (plan.prefill_tokens, decode_rows, len(self._cursors))
            if snapshot != self._plan_gauges:
                self._plan_gauges = snapshot
                self._metrics.set_gauge(
                    "app_step_plan_prefill_tokens", plan.prefill_tokens
                )
                self._metrics.set_gauge(
                    "app_step_plan_decode_rows", decode_rows
                )
                self._metrics.set_gauge(
                    "app_step_plan_cursors", len(self._cursors)
                )
        return plan

    def _route_chunked(self, prompt_len: int) -> bool:
        """True when a prompt prefills through chunk cursors + the ragged
        dispatch instead of one monolithic bucketed prefill: longer than a
        chunk, or longer than every bucket (the monolithic path cannot
        scatter past its biggest bucket)."""
        if not self._chunk_enabled:
            return False
        return (prompt_len > self._chunk_tokens
                or prompt_len > max(self._buckets()))

    def _admit(self, plan: StepPlan | None = None) -> bool:
        # bind ONCE: a warm restart that replaces this thread mid-admit
        # swaps self._sched for a rebuilt one — the pairs delivered below
        # belong to THIS scheduler, and releases/requeues must never land
        # on the replacement's
        sched = self._sched
        if not sched.pending():
            # admit cadence: nothing queued (canceled requests stay queued
            # until delivered, so they keep the depth nonzero) — skip the
            # native admit round trip entirely; per-block host overhead is
            # the budget this loop is built around
            return False
        # the plan's quota is never 0 while the queue is non-empty (a
        # canceled-but-queued request resolves only through an admit
        # delivery); max(…, 1) covers a submit that raced in after the
        # plan read its queue depth
        cap = max(plan.admit_cap, 1) if plan is not None else (
            self.config.admission_per_step
        )
        pairs, canceled_ids = sched.admit(cap)
        # the admit call itself can hang (native mutex held under a wedged
        # step); a thread thawing out of it retired would otherwise process
        # the old scheduler's pairs against the REPLACEMENT engine's state
        # — releasing its slots, allocating its pages for requeued rids
        self._check_retired()
        for rid in canceled_ids:
            with self._count_lock:
                req = self._by_id.pop(rid, None)
            if req is not None:
                self._finish(req, "cancel")
        for rid, slot in pairs:
            with self._count_lock:
                req = self._by_id.get(rid)
            if req is None:  # should not happen; release the slot defensively
                sched.release(slot)
                continue
            if req.canceled:  # canceled between admit() and here
                sched.release(slot)
                with self._count_lock:
                    self._by_id.pop(rid, None)
                self._finish(req, "cancel")
                continue
            if req.expired(time.perf_counter()):
                # expired while queued: NEVER prefill it — the answer is
                # already useless, the prefill would only steal TTFT from
                # live requests. 504 / DEADLINE_EXCEEDED to the caller.
                sched.release(slot)
                with self._count_lock:
                    self._by_id.pop(rid, None)
                self._expire(req)
                continue
            # admission reached: stamp the queue→batch transition and
            # close the queue span (first stamp wins, so a page-pressure
            # requeue keeps its original queue-wait truth)
            tl = req.timeline
            if tl is not None and "admitted" not in tl.phases:
                now = time.perf_counter()
                tl.stamp("admitted")
                queue_wait = now - req.created
                qspan = tl.spans.get("queue")
                if qspan is not None:
                    qspan.set_attribute("queue.wait_s", round(queue_wait, 6))
                    qspan.end()
                if self._metrics:
                    labels = (
                        {"tenant": req.tenant} if req.tenant else {}
                    )
                    self._metrics.record_histogram(
                        "app_request_queue_wait_seconds", queue_wait,
                        **labels,
                    )
            try:
                if self._lora is not None and req.adapter_id:
                    from gofr_tpu.serving.lora import AdapterBusy

                    try:
                        # pin the adapter's device-table slot for the
                        # life of the row; every table slot pinned (or a
                        # faulted async upload) is TRANSIENT — requeue
                        # exactly like KV-pool pressure. The wait is
                        # clamped to the request's remaining deadline: a
                        # slow upload degrades to AdapterBusy → requeue,
                        # and the expired-while-queued check 504s the
                        # request next round instead of letting the
                        # acquire outlive it
                        budget = 5.0
                        rem = req.remaining(time.perf_counter())
                        if rem is not None:
                            budget = min(budget, rem)
                        req.adapter_slot = self._lora.acquire(
                            req.adapter_id, timeout=budget
                        )
                    except AdapterBusy:
                        raise _RequeueRequest() from None
                if self._route_chunked(len(req.serve_ids)):
                    self._start_cursor(slot, req)
                else:
                    self._prefill_into(slot, req)
            except _RequeueRequest:
                # transient (KV pages exhausted): back to the HEAD of its
                # priority class (it keeps its FIFO position — later smaller
                # requests must not starve it); the REST of the admitted
                # batch still proceeds — their slots are already claimed and
                # the scheduler never re-delivers an admitted pair
                self._check_retired()  # warm_restart already requeued it
                self._lora_release(req)
                sched.release(slot)
                try:
                    sched.submit(
                        rid, len(req.serve_ids), req.max_new_tokens,
                        req.priority, front=True,
                    )
                except Exception:
                    with self._count_lock:
                        self._by_id.pop(rid, None)
                    self._try_resolve(req, exc=ErrorTooManyRequests())
            except Exception as exc:
                # a failed prefill must not leak the slot, its KV pages, or
                # hang the client. A RETIRED thread unwinds instead: its
                # request was already requeued/settled by warm_restart, and
                # slots/pools here belong to the replacement engine.
                self._check_retired()
                self._lora_release(req)
                self.slots[slot] = None
                self.cache_len[slot] = 0
                if self.paged_cache is not None:
                    try:
                        self.paged_cache.free_slot(slot)
                    except Exception:
                        pass
                try:
                    sched.release(slot)
                except KeyError:
                    pass
                with self._count_lock:
                    self._by_id.pop(rid, None)
                self._try_resolve(req, exc=exc)
                if self._logger:
                    self._logger.error(f"prefill failed for request {rid}: {exc}")
                # pure host-side rejections (queue/page-budget limits) never
                # touched the device — don't pay a blocking probe for them
                if not isinstance(
                    exc, (ErrorTooManyRequests, ErrorRequestEntityTooLarge)
                ) and self._kv_unhealthy():
                    # the failing call donated the SHARED cache (insert_slot*/
                    # write_prefill) and died after donation committed: every
                    # active slot's KV is gone, not just this request's —
                    # isolated cleanup would leave the engine raising
                    # "Array has been deleted" on every future step
                    self._fail_all(exc, kv_unhealthy=True)
        self._observe_queue()
        return bool(pairs or canceled_ids)

    def _lora_adjusted(self, req: _Request, last_logits: Any,
                       last_token: int) -> Any:
        """Apply the row's adapter delta to host-path last-position
        logits before first-token sampling (monolithic prefill, full
        chunk-prefix hits). Pure device op, no sync; base rows return
        the logits untouched."""
        if self._lora is None or not req.adapter_slot:
            return last_logits
        factors = self._lora.slot_factors(req.adapter_slot)
        if factors is None:
            return last_logits
        return batch_ops.lora_adjust_logits(
            self.params["embedding"], factors[0], factors[1],
            jnp.int32(last_token), last_logits,
        )

    def _lora_release(self, req: _Request) -> None:
        """Unpin a row's adapter-table slot (no-op for base rows). Every
        path that takes a row out of the batch — retire, requeue,
        preempt, fail-all, the restart sweep — funnels through this so a
        pin can never outlive its row."""
        if req.adapter_slot and self._lora is not None:
            self._lora.release(req.adapter_slot)
            req.adapter_slot = 0

    # -- KV reuse tiers (prefix cache + host spill + cluster migration) --------
    def _cache_lookup(self, key: str) -> tuple[Any, str]:
        """Prefix-cache lookup with tier attribution: ``(value, tier)``
        where tier is ``device`` / ``host`` / ``miss``. Plain (single-
        tier) caches report ``device`` on a hit."""
        cache = self._prefix_cache
        tiered = getattr(cache, "get_with_tier", None)
        if tiered is not None:
            value, tier = tiered(key)
        else:
            value = cache.get(key)
            tier = "device" if value is not None else "miss"
        if value is not None:
            # feed the spill tier's demotion scorer: the flight recorder
            # keeps the per-key reuse counts the byte-pressure eviction
            # orders by (host dict write, zero device work)
            self.timeline.observe_prefix_reuse(key)
        return value, tier

    def _record_prefix_tier(self, req: _Request, tier: str) -> None:
        """Stamp the request's warmest-source attribution — the
        ``/requestz`` timeline's ``prefix_tier`` and the per-tier hit
        counter (docs/observability.md). First stamp wins on the
        timeline (a pool-pressure requeue keeps its original truth);
        the counter counts admission walks."""
        tl = req.timeline
        if tl is not None and tl.prefix_tier is None:
            tl.prefix_tier = tier
        if self._metrics:
            self._metrics.increment_counter(
                "app_kv_prefix_hits_total", tier=tier
            )

    def prefix_advertisement(self, limit: int = 128) -> list[list[str]] | None:
        """This replica's bounded [key, tier] advertisement for the
        distributed prefix index (serving/prefix_index.py), carried on
        the membership heartbeat. None when the cache exposes no key
        listing (injected container caches)."""
        cache = self._prefix_cache
        if cache is None:
            return None
        advertised = getattr(cache, "advertised", None)
        if advertised is not None:
            pairs = advertised(limit)
        else:
            keys_fn = getattr(cache, "keys", None)
            if keys_fn is None:
                return None
            pairs = [
                (str(k), "device")
                for k in list(reversed(keys_fn()))[:limit]
            ]
        return [[key, tier] for key, tier in pairs]

    def _prefill_into(self, slot: int, req: _Request) -> None:
        cfg = self.model_cfg
        # serve_ids = prompt + already-emitted tokens: identical to the
        # prompt for a fresh request; a preempted request re-prefills its
        # whole generated context and resumes from the NEXT token
        ids = req.serve_ids
        S = len(ids)
        bucket = batch_ops.pad_bucket(S, self._buckets())
        tokens = np.full((1, bucket), self.tokenizer.pad_id, np.int32)
        tokens[0, :S] = ids
        seq_len = jnp.array([S], jnp.int32)

        if self.paged_cache is not None:
            # page reservation first: OutOfBlocks must requeue BEFORE any
            # device work (the request keeps its place; pool pressure is a
            # transient, not an error) — unless the prompt can NEVER fit,
            # which must fail the request, not livelock the admit loop
            from gofr_tpu.serving.kv_cache import OutOfBlocks

            if self.paged_cache.pages_needed(bucket) > self.paged_cache.num_pages:
                # permanent, not transient: however empty the pool gets,
                # this prompt can NEVER fit — a 429 would invite clients to
                # retry forever; 413 / FAILED_PRECONDITION says "shrink it"
                raise ErrorRequestEntityTooLarge(
                    f"prompt needs {self.paged_cache.pages_needed(bucket)} KV pages; "
                    f"pool has {self.paged_cache.num_pages} in total"
                )
            try:
                self.paged_cache.alloc_slot(
                    slot, seq_id=req.id, prompt_len=S, reserve_tokens=bucket
                )
            except OutOfBlocks:
                raise _RequeueRequest() from None

        cache_key = None
        cached = None
        prefix_tier = None
        if self._prefix_cache is not None:
            # sampling params are NOT in the key: the cached value is the
            # pre-sampling prefill output, shared across temperatures.
            # A STRING key keeps the injected-cache contract (the container
            # Cache protocol declares str keys; a datasource-backed cache
            # can serialize it directly).
            import hashlib as _hashlib

            digest = _hashlib.blake2b(
                np.asarray(ids, np.int32).tobytes(), digest_size=16
            ).hexdigest()
            # the adapter id is part of the key BY CONSTRUCTION: a
            # cross-adapter KV hit is impossible however the cache is
            # shared/migrated (docs/serving.md "Multi-tenancy")
            cache_key = (
                f"prefill:{bucket}:{S}:{digest}:{req.adapter_id or '-'}"
            )
            cached, prefix_tier = self._cache_lookup(cache_key)
            if cached is None and self._kv_migrator is not None:
                # disaggregated handoff first (the router named the
                # prefill source — no heartbeat-advertisement wait), then
                # the advisory cluster tier: another replica advertises
                # this exact prefill — migrate its slabs instead of
                # recomputing (either failure stays a compute miss)
                fetched = None
                # the fetch is bounded by what the request has left: an
                # expired one degrades to a compute miss without a fetch
                budget = req.remaining(time.perf_counter())
                if req.handoff_from is not None:
                    fetched = self._kv_migrator.fetch_one_handoff(
                        cache_key, req.handoff_from, deadline=budget
                    )
                if fetched is None:
                    fetched = self._kv_migrator.fetch_one(
                        cache_key, deadline=budget
                    )
                # the fetch can block (remote transport timeout): a warm
                # restart may have retired this thread meanwhile — the
                # put below would poison the cache the restart just
                # reset (the same hazard as the compute-path put)
                self._check_retired()
                if fetched is not None:
                    from gofr_tpu.serving.kv_spill import _to_device

                    cached = _to_device(fetched)
                    prefix_tier = "remote"
                    # pay the transfer once per replica, not per request
                    self._prefix_cache.put(cache_key, cached)
            self._record_prefix_tier(req, prefix_tier)

        tl = req.timeline
        if tl is not None:
            tl.stamp("prefill_start")
        span = self._req_span(
            "prefill",
            f"serve.prefill b{bucket}" + (" (prefix hit)" if cached else ""),
            req,
        )
        if tl is not None:
            pspan = tl.spans.get("prefill")
            if pspan is not None:
                pspan.set_attribute("prefill.bucket", bucket)
                pspan.set_attribute("prefill.prefix_hit", cached is not None)
                pspan.set_attribute("prefix_tier", prefix_tier or "miss")
                pspan.set_attribute("tokens.prompt", S)
        # bind the KV storage ONCE, before the long dispatch: a warm
        # restart that replaces this thread mid-compute swaps
        # self.paged_cache/self.cache for rebuilt ones — re-reading them
        # after the dispatch would donate the REPLACEMENT engine's pools
        # from a quarantined thread
        pc, dense = self.paged_cache, self.cache
        with span, self._cold_dispatch("prefill", bucket, cached is not None):
            if cached is not None:
                last_logits, k_slab, v_slab = cached
            else:
                last_logits, k_slab, v_slab = batch_ops.prefill_compute(
                    cfg, self.params, jnp.asarray(tokens), seq_len
                )
            self._check_retired()  # replaced during the compute: no writes
            # ...including the prefix cache: a retired thread thawing out
            # of a device-loss hang would insert DEAD slabs into the cache
            # warm_restart just reset, poisoning every future hit on this
            # prefix
            if cached is None and cache_key is not None:
                # slabs are fresh, never-donated arrays: safe to retain
                self._prefix_cache.put(cache_key, (last_logits, k_slab, v_slab))
            if pc is not None:
                pc.write_prefill(slot, k_slab, v_slab)
            elif dense.quantized:
                self.cache = batch_ops.insert_slot_quantized(
                    dense, k_slab, v_slab, jnp.int32(slot)
                )
            else:
                dense.k, dense.v = batch_ops.insert_slot(
                    dense.k, dense.v, k_slab, v_slab, jnp.int32(slot)
                )
            # sample the first token with this request's params, keyed by
            # request id (NOT the shared stream — see _rng_root above).
            # The row's LoRA delta applies HERE, at the sampling site —
            # cached entries stay base-model logits (adapter-scoped keys
            # already make cross-adapter hits impossible).
            key = jax.random.fold_in(self._rng_root, req.id)
            from gofr_tpu.ops.sampling import sample_logits

            first = sample_logits(
                self._lora_adjusted(req, last_logits, ids[-1]), key,
                temperature=jnp.float32(req.temperature),
                top_k=jnp.int32(req.top_k),
                top_p=jnp.float32(req.top_p),
            )
            first_id = int(first[0])

        # the dispatch is back: a warm restart may have replaced this
        # thread while it sat in the compile — commit nothing if so (the
        # request was requeued; the successor thread redoes the prefill)
        self._check_retired()
        # progress stamp: a multi-prefill admission can legitimately
        # outlast TPU_ENGINE_STALL_S in one loop iteration — the watchdog
        # must see "slow but moving", not "hung"; a truly stuck dispatch
        # stamps nothing anywhere (and a first-call jit compile widens the
        # threshold via _cold_dispatch above)
        self.heartbeat = time.monotonic()
        self._commit_prefilled(slot, req, first_id, S)

    def _commit_prefilled(self, slot: int, req: _Request, first_id: int,
                          resident: int) -> None:
        """First-token commit shared by the monolithic prefill path and a
        full chunk-prefix cache hit: slot bookkeeping, the DecodeState
        admission fold, TTFT stamps/metrics, first-token emission and the
        stop/length retire chain."""
        req.slot = slot
        self.slots[slot] = req
        self.cache_len[slot] = resident
        self.last_token[slot] = first_id
        self.temperature[slot] = req.temperature
        self.top_k[slot] = req.top_k
        self.top_p[slot] = req.top_p
        self.adapter_idx[slot] = req.adapter_slot
        # folded into the device-resident DecodeState by one donated
        # scatter at the next dispatch: (first token, resident len,
        # remaining budget, stop id, adapter slot). The budget carries
        # BOTH limits — max_new and the sequence cap (submit already
        # clamped max_new to the sequence budget) — and counts only the
        # REMAINING tokens, so a preempted request resumes with what it
        # has left, not a fresh allowance. A multi-token stop set
        # disables device stop-eval (-1 sentinel); the host's
        # _commit_token still enforces it at each sync.
        self._pending_admit[slot] = (
            first_id, resident, req.new_budget - 1,
            next(iter(req.stop_ids)) if len(req.stop_ids) == 1 else -1,
            req.adapter_slot,
        )
        self._commit_first_token(slot, req, first_id)

    # -- chunked prefill (continuous batching) ---------------------------------
    def _chunk_cache_keys(
        self, prompt_ids: list[int], adapter_id: str | None = None,
    ) -> list[tuple[int, int, str]]:
        """Chunk-prefix cache keys for every chunk boundary of a prompt:
        chunk geometry + the content digest of the FULL prefix up to each
        boundary — two prompts sharing a prefix share its chunk entries,
        and a chunk-size change can never alias. The ADAPTER ID is part
        of the key: same prompt under two adapters is two cache chains,
        so a cross-adapter KV hit is impossible by construction — here,
        in the distributed prefix index, and across disaggregated
        handoffs (the keys are content-addressed everywhere). ONE
        incremental blake2b pass with a copy() snapshot per boundary:
        digesting each prefix from scratch would be quadratic in prompt
        length on the engine thread."""
        import hashlib as _hashlib

        arr = np.asarray(prompt_ids, np.int32)
        aid = adapter_id or "-"
        h = _hashlib.blake2b(digest_size=16)
        out: list[tuple[int, int, str]] = []
        pos, total = 0, len(prompt_ids)
        while pos < total:
            end = min(pos + self._chunk_tokens, total)
            h.update(arr[pos:end].tobytes())
            key = (
                f"chunkpfx:{self._chunk_tokens}:{pos}:{end}:"
                f"{h.copy().hexdigest()}:{aid}"
            )
            out.append((pos, end, key))
            pos = end
        return out

    def _start_cursor(self, slot: int, req: _Request) -> None:
        """Admit a long prompt as a chunk cursor: claim the slot, skip any
        already-cached chunk prefixes, and leave the rest of the prompt to
        the step planner's chunk grants. Raises before touching slot state
        on page pressure (_RequeueRequest) or a never-fits prompt (413) —
        the _admit cleanup contract."""
        ids = req.serve_ids  # prompt + emitted tokens (preempt resume)
        total = len(ids)
        pc = self.paged_cache
        if pc is not None and pc.pages_needed(total) > pc.num_pages:
            raise ErrorRequestEntityTooLarge(
                f"prompt needs {pc.pages_needed(total)} KV pages; "
                f"pool has {pc.num_pages} in total"
            )

        # probe the prefix cache for the longest chain of cached
        # chunk-boundary prefixes (each entry holds that chunk's K/V delta
        # slab + the prefix's last-position logits). The boundary keys are
        # computed ONCE per tenancy and ride the cursor — the per-chunk
        # PUT at consume reuses them instead of re-digesting the prefix.
        hits: list[tuple[int, int, Any]] = []
        pos = 0
        cache_keys: dict[tuple[int, int], str] | None = None
        tiers: set[str] = set()
        if self._prefix_cache is not None and self._chunk_cache_enabled:
            boundaries = self._chunk_cache_keys(ids, req.adapter_id)
            cache_keys = {(s, e): k for s, e, k in boundaries}
            for start, end, key in boundaries:
                val, tier = self._cache_lookup(key)
                if val is None:
                    break
                if end >= total and val[0].shape[-1] != self.model_cfg.vocab_size:
                    # a preemption page-out stored this span with a
                    # PLACEHOLDER logits column (the paged-out row never
                    # had last-position logits to give). Its KV is good
                    # as a NON-final link, but it must never serve as the
                    # chain's final entry — the zero-dispatch admit below
                    # would sample this request's first token from
                    # garbage. Stop the walk; the tail chunk recomputes
                    # and samples fresh.
                    break
                hits.append((start, end, val))
                tiers.add(tier)
                pos = end
            if pos < total and self._kv_migrator is not None:
                # disaggregated handoff first: the router named the
                # prefill source, and the fetch runs under the kv.handoff
                # two-phase-commit discipline — a COMPLETE, contiguity-
                # audited chain or nothing (a torn handoff must never
                # commit a partial chain it believed complete). A source
                # or transport failure returns [] and the normal
                # advisory tiers below degrade to re-prefill.
                remaining = [b for b in boundaries if b[0] >= pos]
                fetched = []
                # bounded by the request's remaining deadline, exactly
                # like the monolithic path's handoff/advisory fetches
                budget = req.remaining(time.perf_counter())
                if req.handoff_from is not None:
                    fetched = self._kv_migrator.fetch_handoff(
                        remaining, req.handoff_from, deadline=budget
                    )
                if not fetched:
                    # cluster tier: migrate the longest advertised
                    # chunk-boundary chain from the owning replica. The
                    # fetch is advisory and contiguous-from-pos by
                    # contract — a torn transfer keeps the fetched prefix
                    # and the planner's chunk grants compute the rest
                    # (never a double-prefill: committed spans stay
                    # contiguous).
                    fetched = self._kv_migrator.fetch_chain(
                        remaining, deadline=budget
                    )
                # the fetch can block (remote transport timeout): a
                # retired thread must not put dead slabs into the
                # replacement engine's freshly-reset cache
                self._check_retired()
                if fetched:
                    from gofr_tpu.serving.kv_spill import _to_device

                    for start, end, val in fetched:
                        val = _to_device(val)  # async upload, no sync
                        if (end >= total and
                                val[0].shape[-1] != self.model_cfg.vocab_size):
                            break  # peer's preempt placeholder: same
                            # final-entry guard as the local walk above
                        hits.append((start, end, val))
                        pos = end
                        # pay the transfer once per replica: later
                        # requests sharing this prefix hit locally
                        self._prefix_cache.put(
                            cache_keys[(start, end)], val
                        )
                    tiers.add("remote")
            self._record_prefix_tier(
                req,
                "remote" if "remote" in tiers
                else "host" if "host" in tiers
                else "device" if hits else "miss",
            )

        from gofr_tpu.serving.kv_cache import OutOfBlocks

        if hits and pc is not None:
            try:
                pc.alloc_slot(slot, seq_id=req.id, prompt_len=0,
                              reserve_tokens=pos)
            except OutOfBlocks:
                raise _RequeueRequest() from None

        tl = req.timeline
        if tl is not None:
            tl.stamp("prefill_start")
        for start, end, (_logits, k_slab, v_slab) in hits:
            if pc is not None:
                pc.write_span(slot, start, k_slab, v_slab)
            else:
                dense = self.cache
                dense.k, dense.v = batch_ops.insert_chunk(
                    dense.k, dense.v, k_slab, v_slab,
                    jnp.int32(slot), jnp.int32(start),
                )
        if hits:
            if pc is not None:
                pc.advance_slot(slot, pos)
            if tl is not None:
                tl.chunk(0, pos, prefix_hit=True)
            if self._metrics:
                self._metrics.record_histogram(
                    "app_prefill_chunk_tokens", pos, kind="prefix_hit",
                )

        if pos >= total:
            # the WHOLE prompt was cached at chunk boundaries: sample the
            # first token from the cached last-position logits and admit
            # straight to decode — zero prefill dispatches (the admission-
            # path sync mirrors the monolithic prefix-hit path)
            span = self._req_span("prefill", "serve.prefill chunked (prefix hit)", req)
            if tl is not None:
                pspan = tl.spans.get("prefill")
                if pspan is not None:
                    pspan.set_attribute("prefill.prefix_hit", True)
                    pspan.set_attribute(
                        "prefix_tier", tl.prefix_tier or "device"
                    )
            with span:
                last_logits = hits[-1][2][0]
                key = jax.random.fold_in(self._rng_root, req.id)
                from gofr_tpu.ops.sampling import sample_logits

                first = sample_logits(
                    self._lora_adjusted(req, last_logits, ids[-1]), key,
                    temperature=jnp.float32(req.temperature),
                    top_k=jnp.int32(req.top_k),
                    top_p=jnp.float32(req.top_p),
                )
                first_id = int(first[0])
            self._check_retired()
            self._commit_prefilled(slot, req, first_id, total)
            return

        cursor = ChunkCursor(req=req, slot=slot, total=total,
                             seq=self._cursor_seq, priority=req.priority)
        self._cursor_seq += 1
        cursor.cache_keys = cache_keys
        cursor.committed = cursor.dispatched = pos
        cursor.prefix_hit = pos
        cursor.chunk_index = 1 if hits else 0
        cursor.allocated = bool(hits and pc is not None)
        req.slot = slot
        self.slots[slot] = req
        self.cache_len[slot] = pos
        self.last_token[slot] = 0
        self.temperature[slot] = req.temperature
        self.top_k[slot] = req.top_k
        self.top_p[slot] = req.top_p
        self.adapter_idx[slot] = req.adapter_slot
        self._cursors[slot] = cursor

    def _cursor_requeue(self, slot: int, req: _Request,
                        cursor: ChunkCursor) -> None:
        """Transient KV-pool pressure mid-chunked-prefill: give the pages
        back and requeue the request from chunk 0 at the head of its
        priority class — prefill pressure is a transient, not an error.
        Only legal with nothing in flight for the cursor (an in-flight
        ragged dispatch still writes through this slot's pages)."""
        self._cursors.pop(slot, None)
        self.slots[slot] = None
        self.cache_len[slot] = 0
        req.slot = None
        self._lora_release(req)
        if self.paged_cache is not None:
            try:
                self.paged_cache.free_slot(slot)
            except Exception:
                pass
        sched = self._sched
        try:
            sched.release(slot)
        except KeyError:
            pass
        try:
            # gofrlint: disable=retry-unbudgeted -- expiry is gated upstream: _cursor_health checks req.expired before every pressure requeue, and admission re-checks it next round (504)
            sched.submit(
                req.id, len(req.serve_ids), req.max_new_tokens,
                req.priority, front=True,
            )
        except Exception:
            with self._count_lock:
                self._by_id.pop(req.id, None)
            self._try_resolve(req, exc=ErrorTooManyRequests())

    def _cursor_health(self, slot: int, req: _Request, cursor: ChunkCursor,
                       now: float) -> None:
        """Mid-chunk retirement/requeue gate, run at each dispatch scan:
        cancel and deadline expiry retire the partially-prefilled row;
        pool pressure requeues it from chunk 0 — all deferred while a
        dispatched ragged chunk is still in flight for the slot (its
        writes ride the page tables snapshotted at dispatch; freeing the
        pages under it would hand them to another row)."""
        if cursor.in_flight > 0:
            return
        if req.canceled:
            self._retire(slot, "cancel")
        elif req.expired(now):
            self._retire(slot, "deadline_exceeded")
        elif cursor.blocked:
            self._cursor_requeue(slot, req, cursor)

    # -- tenant preemption (docs/serving.md "Multi-tenancy") -------------------
    def _maybe_preempt(self) -> bool:
        """The preemption ladder: when a STRICTLY higher class (lower
        priority number) waits and the batch cannot take it — no free
        slot, or (paged) the pool cannot cover its prompt — pause the
        lowest-priority decode row. Its committed KV pages out through
        the prefix-cache/host-spill tier (:meth:`_preempt`), the slot
        frees, and the row resumes warm later with its emitted tokens
        intact. Equal classes never preempt each other (no ping-pong: a
        resumed row keeps its priority, so it can never evict what
        evicted it). Engine-thread only; a few dict walks per iteration
        and only when something is actually waiting."""
        if not self.config.tenant_preempt or self._tenants is None:
            return False
        if self.config.spec_tokens > 0:
            return False  # spec rows carry un-resumable draft state
        with self._count_lock:
            waiting = [
                r for r in self._by_id.values()
                if r.slot is None and not r.canceled
            ]
        if not waiting:
            self._preempt_pending.clear()
            return False
        best = min(r.priority for r in waiting)
        slot_pressure = all(s is not None for s in self.slots)
        page_pressure = False
        if not slot_pressure and self.paged_cache is not None:
            need = min(
                self.paged_cache.pages_needed(len(r.serve_ids))
                for r in waiting if r.priority == best
            )
            page_pressure = (
                need > self.paged_cache.stats()["free_blocks"]
            )
        if not slot_pressure and not page_pressure:
            self._preempt_pending.clear()  # the pressure passed: resume
            return False
        # a pending victim preempts the moment its pipelined blocks drain
        # (the dispatch loop stopped feeding it when it went pending —
        # preempting under an in-flight block would free pages the
        # dispatched device work still writes through)
        for slot in sorted(self._preempt_pending):
            req = self.slots[slot]
            if req is None or req.priority <= best:
                self._preempt_pending.discard(slot)
                continue
            cursor = self._cursors.get(slot)
            if self._slot_in_flight(slot, req) or (
                cursor is not None and cursor.in_flight > 0
            ):
                return False  # draining: the consume side lands first
            self._preempt_pending.discard(slot)
            self._preempt(slot)
            return True
        victim = None
        for slot, req in enumerate(self.slots):
            if req is None or req.priority <= best:
                continue  # never preempt an equal-or-higher class
            if victim is None or (
                (req.priority, len(req.tokens))
                > (self.slots[victim].priority, len(self.slots[victim].tokens))
            ):
                # lowest class first; ties pick the row with MORE tokens
                # out (its resume is warmest — every committed chunk is
                # already in the cache chain)
                victim = slot
        if victim is None:
            return False
        cursor = self._cursors.get(victim)
        req = self.slots[victim]
        if self._slot_in_flight(victim, req) or (
            cursor is not None and cursor.in_flight > 0
        ):
            # stop feeding the row and preempt once the pipeline drains
            self._preempt_pending.add(victim)
            return False
        self._preempt(victim)
        return True

    def _preempt(self, slot: int, *, reclaim: bool = False) -> None:
        """Pause one decode row: page its committed whole-chunk KV spans
        out into the prefix cache (whence device-LRU pressure demotes
        them to the PR 11 host-RAM spill tier), free the slot + pages,
        and requeue the request. Resume is the ordinary re-admission of
        ``serve_ids`` (prompt + emitted tokens): the boundary walk finds
        the paged-out chunks and warm-restores them, the tail chunk
        recomputes, and the NEXT token samples — emitted tokens are
        preserved and never re-emitted. The ``tenant.preempt`` chaos
        point makes the policy advisory by construction: a fault there
        skips this preemption, never corrupts the row.

        ``reclaim=True`` is the reclamation-notice variant
        (:meth:`_reclaim_sweep`): same warm page-out — the spans then
        ride the bulk evacuation to a survivor — but the row settles
        RETRIABLE instead of requeueing (this replica is doomed; the
        router's retry lands on a survivor whose re-prefill the
        evacuated chunks make warm). Not advisory: the chaos point for
        the notice path is ``replica.reclaim`` at delivery."""
        req = self.slots[slot]
        if req is None:
            return
        if not reclaim:
            try:
                chaos.maybe_fail("tenant.preempt")
            except Exception:
                return  # advisory: a faulted preemption is a skipped one
        ids = req.serve_ids
        resident = int(self.cache_len[slot])
        # page out whole chunk-boundary spans below the resident length —
        # and strictly below the total, so the resume always computes at
        # least the final tail chunk (whose logits seed the next token).
        # int8 layouts skip the page-out (read_span would dequantize) and
        # simply recompute on resume — the chunk cache is off there anyway.
        if (self._prefix_cache is not None and self._chunk_cache_enabled
                and not req.prefill_only):
            boundaries = self._chunk_cache_keys(ids, req.adapter_id)
            for start, end, key in boundaries:
                if end > resident or end >= len(ids):
                    break
                if self.paged_cache is not None:
                    k_slab, v_slab = self.paged_cache.read_span(
                        slot, start, end
                    )
                else:
                    k_slab = self.cache.k[:, slot, start:end]
                    v_slab = self.cache.v[:, slot, start:end]
                # the span entry's logits column is never read: the walk
                # stops before the prompt's end by construction (see
                # above), so a placeholder keeps the (logits, k, v)
                # value shape without retaining a live buffer
                self._prefix_cache.put(
                    key, (jnp.zeros((1, 1), jnp.float32), k_slab, v_slab)
                )
        req.preemptions += 1
        tl = req.timeline
        if tl is not None:
            tl.stamp("reclaim-preempted" if reclaim
                     else f"preempted:{req.preemptions}")
        if self._metrics and not reclaim:
            self._metrics.increment_counter(
                "app_tenant_preemptions_total",
                tenant=req.tenant or "default",
            )
        if self._logger:
            self._logger.info(
                f"preempted request {req.id} (tenant "
                f"{req.tenant or 'default'}, priority {req.priority}) "
                f"after {len(req.tokens)} tokens; {resident} resident "
                "tokens paged out"
            )
        # nothing is in flight for the slot (the caller checked): free it
        # and requeue. The consume-side identity checks make any stale
        # record harmless, exactly like a cancel retire.
        self._cursors.pop(slot, None)
        self.slots[slot] = None
        self.cache_len[slot] = 0
        self.adapter_idx[slot] = 0
        req.slot = None
        req.dispatched = max(len(req.tokens) - 1, 0)
        self._lora_release(req)
        if self.paged_cache is not None:
            try:
                self.paged_cache.free_slot(slot)
            except Exception:
                pass
        sched = self._sched
        try:
            sched.release(slot)
        except KeyError:
            pass
        if reclaim:
            # doomed replica: never requeue here — settle retriable so
            # the router's candidate walk retries on a survivor (whose
            # boundary walk finds the evacuated spans)
            req.canceled = True
            self._settle_future(req, ErrorServiceUnavailable(
                "replica reclaiming; retry on another replica",
                retry_after=0.5,
            ))
            return
        try:
            sched.submit(
                req.id, len(req.serve_ids), req.max_new_tokens,
                req.priority,
            )
        except Exception:
            with self._count_lock:
                self._by_id.pop(req.id, None)
            self._try_resolve(req, exc=ErrorTooManyRequests())

    # -- decode (pipelined N-step blocks) --------------------------------------
    def _decode_step(self, plan: StepPlan | None = None) -> bool:
        """Dispatch the NEXT N-step device block — a plain decode block,
        or the unified ragged dispatch when the step plan granted prefill
        chunks — then materialize the OLDEST outstanding one. The dispatch
        feeds on the device-resident DecodeState carry directly, so the
        device never waits for host bookkeeping; the host's single block
        sync overlaps the next block's compute (double-buffered — depth =
        decode_sync_every)."""
        self._check_retired()  # replaced during a long _admit: unwind first
        if self.config.spec_tokens > 0:
            return self._spec_step()
        inflight = self._dispatch_decode(plan)
        if inflight is not None:
            self._inflight_q.append(inflight)
        did = inflight is not None
        if self._inflight_q and (
            inflight is None or len(self._inflight_q) > self._sync_every
        ):
            self._consume_block(self._inflight_q.popleft())
            did = True
        return did

    def _spec_step(self) -> bool:
        """Speculative decode step (VERDICT r4 item #3): host drafts up to
        K tokens per greedy row by prompt lookup over (prompt + output),
        one fused dispatch verifies the whole chunk across all slots and
        samples the bonus token, and the host commits each row's accepted
        prefix. LOSSLESS for greedy rows (acceptance is exact argmax
        equality); sampled rows ride the same executable as plain steps.
        Unpipelined by design — drafting needs the newest consumed tokens,
        and the chunk already amortizes dispatch latency the way
        multi_step does, multiplied by accepted drafts. Works on all four
        cache layouts (dense/paged x bf16/int8); ref
        models/llama.py:speculative_generate for the library-level twin.

        Declared unpack site (kernel_contracts.UNPACK_SITES): the
        [:, :-1] / [:, -1] slices below are checked against the 'spec'
        pack layout — out | n_accept — by kernelcheck."""
        cfg = self.model_cfg
        chaos.maybe_fail("decode.dispatch")
        self._maybe_device_loss()
        # a hang at the chaos point can outlive a warm restart: re-check
        # ownership BEFORE reading slots/pools that may since be rebuilt
        self._check_retired()
        K = self.config.spec_tokens
        T = K + 1
        max_seq = self.config.max_seq_len
        self._pending_admit.clear()  # host state is authoritative in spec mode

        rows: list[tuple[int, _Request]] = []
        now = time.perf_counter()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            if req.canceled:
                self._retire(slot, "cancel")
                continue
            if req.expired(now):
                # abandon mid-stream: free the slot for live requests and
                # resolve with the tokens produced so far
                self._retire(slot, "deadline_exceeded")
                continue
            if (len(req.tokens) >= req.max_new_tokens
                    or len(req.prompt_ids) + len(req.tokens) >= max_seq):
                continue  # retires at the next consume's limit checks
            rows.append((slot, req))
        if not rows:
            return False

        B = self.config.max_slots
        chunk = np.full((B, T), -1, np.int32)
        for slot, req in rows:
            chunk[slot, 0] = self.last_token[slot]
            room = min(
                req.max_new_tokens - len(req.tokens),
                max_seq - 1 - (len(req.prompt_ids) + len(req.tokens)),
            )
            if req.temperature == 0 and room > 1 and K > 0:
                draft = llama._prompt_lookup_draft(
                    req.prompt_ids + req.tokens, self.config.spec_ngram,
                    min(K, room - 1),
                )
                chunk[slot, 1 : 1 + len(draft)] = draft

        pc = self.paged_cache
        if pc is not None:
            slot_ids = [s for s, _ in rows]
            if not pc.try_reserve_chunk(slot_ids, T):
                # pool pressure: fall back to single-position coverage per
                # row (chunk tails spill to the trash page; zero drafts
                # still verify position 0 = a plain decode step). A row
                # that can't even cover one more token retires with what
                # it has, like the non-spec path.
                kept = []
                for slot, req in rows:
                    if pc.try_reserve_chunk([slot], 1):
                        chunk[slot, 1:] = -1
                        kept.append((slot, req))
                    else:
                        if self._logger:
                            self._logger.warn(
                                f"KV pool exhausted; retiring request "
                                f"{req.id} early"
                            )
                        req.kv_exhausted = True
                        self._retire(slot, "kv_exhausted")
                rows = kept
                if not rows:
                    return True

        mask = np.zeros(B, bool)
        for slot, _ in rows:
            mask[slot] = True
        # counted AFTER the reservation fallback may have cleared drafts
        drafted_total = int((chunk[mask, 1:] >= 0).sum())
        # spec mode re-uploads the [B] sampling params per chunk: three
        # tiny host→device copies (no sync) against a K+1-position verify
        # forward — not worth a dirty-tracking cache
        temp_d = jnp.asarray(self.temperature.copy())
        topk_d = jnp.asarray(self.top_k.copy())
        topp_d = jnp.asarray(self.top_p.copy())
        if self._mask_host is None or not np.array_equal(mask, self._mask_host):
            self._mask_dev = jnp.asarray(mask)
            self._mask_host = mask
        chunk_d = jnp.asarray(chunk)
        start_d = jnp.asarray(np.maximum(self.cache_len, 1))

        t0 = time.perf_counter()
        with self._cold_dispatch(
            "spec", "paged" if pc is not None else "dense",
            pc.quantized if pc is not None else self.cache.quantized,
        ):
            if pc is not None:
                cap = np.zeros(B, np.int32)
                for slot, _ in rows:
                    cap[slot] = pc.owned_capacity(slot)
                cap_d = jnp.asarray(cap)
                # unpack into LOCALS (and the pre-bound pc, which a
                # restart never mutates): a retired thread's unpack must
                # not clobber the replacement engine's state — self.*
                # commits happen only after the retirement check below
                if pc.quantized:
                    (packed, pc.k_pool, pc.v_pool, pc.ks_pool,
                     pc.vs_pool, new_rng) = batch_ops.verify_and_sample_paged_q(
                        cfg, self.params, pc.k_pool, pc.v_pool,
                        pc.ks_pool, pc.vs_pool, pc.tables_device(), chunk_d,
                        start_d, self._mask_dev, cap_d,
                        temp_d, topk_d, topp_d, self.rng,
                    )
                else:
                    (packed, pc.k_pool, pc.v_pool, new_rng) = (
                        batch_ops.verify_and_sample_paged(
                            cfg, self.params, pc.k_pool, pc.v_pool,
                            pc.tables_device(), chunk_d, start_d,
                            self._mask_dev, cap_d,
                            temp_d, topk_d, topp_d, self.rng,
                        )
                    )
                new_cache = self.cache  # dense path untouched
            else:
                packed, new_cache, new_rng = batch_ops.verify_and_sample(
                    cfg, self.params, self.cache, chunk_d, start_d,
                    temp_d, topk_d, topp_d, self.rng,
                )

            # accepted tokens + per-row accept count come back as ONE
            # packed [B, T+1] array: one sync per chunk, like the plain
            # path's one sync per block
            packed_np = _block_sync(packed)
        # the sync returned: a warm restart may have replaced this thread
        # while the chunk verified — commit nothing to rebuilt state if so
        self._check_retired()
        out_np = packed_np[:, :-1]
        na_np = packed_np[:, -1]
        self.cache, self.rng = new_cache, new_rng
        self.heartbeat = time.monotonic()  # the sync returned: progress
        step_time = time.perf_counter() - t0

        n_active = 0
        accepted_total = 0
        emitted_total = 0
        for slot, req in rows:
            n_active += 1
            accepted_total += int(na_np[slot])
            committed = 0
            for i in range(int(na_np[slot]) + 1):
                committed += 1
                self._commit_token(slot, req, int(out_np[slot, i]))
                if self.slots[slot] is not req:
                    break  # retired mid-chunk: discard the tail
            emitted_total += committed
            if req.timeline is not None:
                req.timeline.block(committed)
            # chunk position 0 (the previously emitted token) plus the
            # accepted drafts are now resident KV; the bonus token commits
            # as the NEXT chunk's position 0 — so residency advances by the
            # emitted count even when the row retired mid-chunk (harmless:
            # the slot was freed)
            if self.slots[slot] is req:
                self.cache_len[slot] += committed
                if pc is not None:
                    pc.advance_slot(slot, committed)

        self.spec_stats["dispatches"] += 1
        self.spec_stats["accepted"] += accepted_total
        self.spec_stats["emitted"] += emitted_total
        if self._metrics and n_active:
            self._metrics.record_histogram(
                "app_tpot_seconds", step_time / max(emitted_total / n_active, 1)
            )
            self._metrics.record_histogram(
                "app_decode_block_seconds", step_time
            )
            self._metrics.set_gauge(
                "app_batch_occupancy", n_active / self.config.max_slots
            )
            if drafted_total:
                # rate over tokens actually DRAFTED — sampled rows and
                # draft-less lookups must not dilute the tuning signal
                self._metrics.set_gauge(
                    "app_spec_accept_rate", accepted_total / drafted_total
                )
        return True

    def _slot_in_flight(self, slot: int, req: _Request) -> bool:
        """True when a dispatched-but-unmaterialized block may still carry
        tokens for this (slot, request) pair — retiring it now would drop
        tokens the client paid for; the consume path retires it instead."""
        return any(
            any(s == slot and r is req for s, r in rec.rows)
            for rec in self._inflight_q
        )

    def _make_device_state(self):
        """Build the device-resident DecodeState from the host mirrors —
        the cold path (first dispatch, post-_fail_all rebuild). Only valid
        with no blocks in flight: the mirrors ARE the truth then."""
        B = self.config.max_slots
        budget = np.zeros(B, np.int32)
        done = np.ones(B, bool)
        stop = np.full(B, -1, np.int32)
        for slot, req in enumerate(self.slots):
            if req is None or slot in self._cursors:
                # a mid-chunked-prefill row is not decoding: it stays
                # frozen (done) until its final chunk's on-device fold
                continue
            remaining = req.max_new_tokens - len(req.tokens)
            budget[slot] = max(remaining, 0)
            done[slot] = remaining <= 0
            if len(req.stop_ids) == 1:
                stop[slot] = next(iter(req.stop_ids))
        self.rng, sub = jax.random.split(self.rng)
        self._pending_admit.clear()  # the mirrors already cover these rows
        return batch_ops.make_decode_state(
            self.last_token, np.maximum(self.cache_len, 1), done, budget,
            stop, self.temperature, self.top_k, self.top_p, sub,
            self.adapter_idx,
        )

    def _dispatch_decode(self, plan: StepPlan | None = None) -> _Inflight | None:
        cfg = self.model_cfg
        chaos.maybe_fail("decode.dispatch")
        self._maybe_device_loss()
        # a hang at the chaos point can outlive a warm restart: re-check
        # ownership BEFORE reading slots/pools that may since be rebuilt
        self._check_retired()
        host_t0 = time.perf_counter()

        rows: list[tuple[int, _Request]] = []
        now = time.perf_counter()
        for slot, req in enumerate(self.slots):
            if req is None:
                continue
            cursor = self._cursors.get(slot)
            if cursor is not None:
                # mid-chunked-prefill: not a decode row. Cancel/deadline/
                # pool-pressure exits run here, deferred while a ragged
                # chunk is still in flight for the slot.
                self._cursor_health(slot, req, cursor, now)
                continue
            if req.canceled:
                # retire immediately; pending in-flight tokens (if any) are
                # discarded at consume via the snapshot identity check
                self._retire(slot, "cancel")
                continue
            if req.expired(now):
                # deadline passed mid-stream (possibly mid-block): abandon
                # the row at this sync boundary, free the slot
                self._retire(slot, "deadline_exceeded")
                continue
            if req.kv_exhausted:
                # pool-clamped: dispatch nothing further; the tokens still
                # in flight are delivered at the next sync, then the row
                # retires there with finish_reason kv_exhausted
                continue
            if slot in self._preempt_pending:
                # marked for preemption: stop feeding the row so its
                # pipelined blocks drain — the preemption ladder pages it
                # out the moment nothing is in flight for the slot
                continue
            rows.append((slot, req))

        N = self._block_steps
        pc = self.paged_cache
        if pc is not None and rows:
            # page coverage for the whole block up front, per row and
            # INCLUDING the dispatched-not-yet-consumed gap (the device
            # runs ahead of the committed host mirror by the in-flight
            # blocks). A row the pool cannot cover is clamped, not
            # stalled: the rest of the batch proceeds.
            kept = []
            for slot, req in rows:
                in_flight = req.dispatched - (len(req.tokens) - 1)
                if pc.try_reserve_slot(slot, in_flight + N):
                    kept.append((slot, req))
                else:
                    if self._logger:
                        self._logger.warn(
                            f"KV pool exhausted; retiring request {req.id} early"
                        )
                    req.kv_exhausted = True
                    if not self._slot_in_flight(slot, req):
                        self._retire(slot, "kv_exhausted")
                    # else: tokens the client paid for are still in flight —
                    # commit them at the next sync and retire there
            rows = kept

        # -- prefill-chunk rows: the step plan's grants, page coverage
        # reserved up front (including each cursor's dispatched-ahead gap,
        # like decode's). A cursor the pool cannot cover is BLOCKED, not
        # stalled — the rest of the plan proceeds and the blocked cursor
        # requeues from chunk 0 once nothing is in flight for it.
        chunk_rows: list[tuple[int, ChunkCursor, _Request, int, int]] = []
        if plan is not None and plan.grants and self._cursors:
            from gofr_tpu.serving.kv_cache import OutOfBlocks

            for slot, grant in plan.grants:
                cursor = self._cursors.get(slot)
                if cursor is None or cursor.blocked or cursor.done:
                    continue
                req = cursor.req
                if req.canceled or req.expired(now):
                    continue  # _cursor_health retires it at the next scan
                n = min(grant, cursor.remaining)
                if n <= 0:
                    continue
                if pc is not None:
                    if not cursor.allocated:
                        try:
                            pc.alloc_slot(slot, seq_id=req.id, prompt_len=0,
                                          reserve_tokens=n)
                            cursor.allocated = True
                        except OutOfBlocks:
                            cursor.blocked = True
                            continue
                    elif not pc.try_reserve_slot(
                        slot, cursor.in_flight + n
                    ):
                        cursor.blocked = True
                        continue
                chunk_rows.append((slot, cursor, req, cursor.dispatched, n))

        if not rows and not chunk_rows:
            return None

        mask = np.zeros(self.config.max_slots, bool)
        for slot, _ in rows:
            mask[slot] = True

        # the device-side carry: build cold, or fold admissions in with ONE
        # donated scatter — steady state uploads nothing per block
        state = self._dec_state
        if state is None:
            state = self._make_device_state()
        elif self._pending_admit:
            items = sorted(self._pending_admit.items())
            self._pending_admit.clear()
            idx = np.fromiter((s for s, _ in items), np.int32, len(items))
            state = batch_ops.admit_decode_state(
                state, jnp.asarray(idx),
                jnp.asarray(np.fromiter((v[0] for _, v in items), np.int32,
                                        len(items))),
                jnp.asarray(np.fromiter((v[1] for _, v in items), np.int32,
                                        len(items))),
                jnp.asarray(np.fromiter((v[2] for _, v in items), np.int32,
                                        len(items))),
                jnp.asarray(np.fromiter((v[3] for _, v in items), np.int32,
                                        len(items))),
                jnp.asarray(self.temperature[idx]),
                jnp.asarray(self.top_k[idx]),
                jnp.asarray(self.top_p[idx]),
                jnp.asarray(np.fromiter((v[4] for _, v in items), np.int32,
                                        len(items))),
            )
        # NOTE: self._dec_state is NOT updated here — the scatter donated
        # the old buffers, and the commit happens in one place after the
        # block dispatch (a failed dispatch resets it via _fail_all)

        if self._mask_host is None or not np.array_equal(mask, self._mask_host):
            self._mask_dev = jnp.asarray(mask)
            self._mask_host = mask
        mask_d = self._mask_dev

        t0 = time.perf_counter()
        # unpack into LOCALS (and the pre-bound pc, which a restart never
        # mutates): a retired thread returning from a hung dispatch must
        # not clobber the replacement engine's state at assignment time —
        # self.* commits happen only after the retirement check
        prefill_rows: list = []
        last_logits = None
        lora = self._lora.tables() if self._lora is not None else None
        if chunk_rows:
            (packed, last_logits, new_cache, new_state, prefill_rows) = (
                self._dispatch_ragged(cfg, pc, state, mask_d, chunk_rows, N)
            )
        elif pc is not None:
            tables_d = pc.tables_device()
            with self._cold_dispatch("decode", "paged", pc.quantized, N,
                                     lora is not None):
                if pc.quantized:
                    (packed, pc.k_pool, pc.v_pool, pc.ks_pool, pc.vs_pool,
                     new_state) = batch_ops.decode_block_paged_q(
                        cfg, self.params, pc.k_pool, pc.v_pool,
                        pc.ks_pool, pc.vs_pool, state, tables_d, mask_d, N,
                        lora=lora,
                    )
                else:
                    (packed, pc.k_pool, pc.v_pool, new_state) = (
                        batch_ops.decode_block_paged(
                            cfg, self.params, pc.k_pool, pc.v_pool, state,
                            tables_d, mask_d, N, lora=lora,
                        )
                    )
            new_cache = self.cache  # dense path untouched
        else:
            with self._cold_dispatch("decode", "dense",
                                     self.cache.quantized, N,
                                     lora is not None):
                packed, new_cache, new_state = batch_ops.decode_block(
                    cfg, self.params, self.cache, state, mask_d, N,
                    lora=lora,
                )
        self._check_retired()  # commit to self only as the loop's owner
        self.cache = new_cache
        self._dec_state = new_state
        for _, req in rows:
            req.dispatched += N
        # the last-position chunk logits are retained ONLY when the
        # chunk-prefix cache will store them at consume (device ref, no
        # sync); otherwise drop the reference so the buffer can free
        keep_logits = (
            last_logits
            if (prefill_rows and self._prefix_cache is not None
                and self._chunk_cache_enabled) else None
        )
        return _Inflight(
            packed, rows, t0, steps=N, host_s=t0 - host_t0,
            prefill_rows=prefill_rows, last_logits=keep_logits,
        )

    def _dispatch_ragged(self, cfg: Any, pc: Any, state: Any, mask_d: Any,
                         chunk_rows: list, N: int) -> tuple:
        """Assemble and launch ONE unified ragged dispatch: the granted
        prefill chunks (per-row slices of their prompts, ragged within the
        fixed [B, C] chunk buffer) plus the N-step decode block, against
        the same slot cache / page pool — batch_ops.ragged_step*. Rows
        whose chunk completes the prompt get their first token sampled on
        device and are folded into the donated DecodeState inside the
        dispatch; the host reads everything back at the block's single
        sync."""
        B = self.config.max_slots
        C = self._chunk_tokens
        chunk = np.full((B, C), -1, np.int32)
        # non-chunk rows aim their (masked/inactive) chunk writes past the
        # dense cache bound so the scatter drops them; paged rows divert
        # to the trash page via the active mask instead
        start = np.full(B, self.config.max_seq_len, np.int32)
        finish = np.zeros(B, bool)
        cactive = np.zeros(B, bool)
        new_len = np.zeros(B, np.int32)
        budgets = np.zeros(B, np.int32)
        stops = np.full(B, -1, np.int32)
        rids = np.zeros(B, np.int32)
        kvcap = np.zeros(B, np.int32)
        adapters = np.zeros(B, np.int32)
        for slot, cursor, req, start_pos, n in chunk_rows:
            serve = req.serve_ids  # prompt + emitted (preempt resume)
            chunk[slot, :n] = serve[start_pos : start_pos + n]
            start[slot] = start_pos
            cactive[slot] = True
            finish[slot] = start_pos + n >= cursor.total
            new_len[slot] = start_pos + n
            budgets[slot] = req.new_budget - 1
            stops[slot] = (
                next(iter(req.stop_ids)) if len(req.stop_ids) == 1 else -1
            )
            rids[slot] = req.id
            adapters[slot] = req.adapter_slot
            if pc is not None:
                kvcap[slot] = pc.owned_capacity(slot)
        chunk_d = jnp.asarray(chunk)
        start_d = jnp.asarray(start)
        finish_d = jnp.asarray(finish)
        newlen_d = jnp.asarray(new_len)
        budgets_d = jnp.asarray(budgets)
        stops_d = jnp.asarray(stops)
        rids_d = jnp.asarray(rids)
        # ragged dispatches re-upload the [B] sampling params (three tiny
        # host→device copies, no sync) — chunk traffic is a small fraction
        # of decode traffic, not worth a dirty-tracking cache
        temps_d = jnp.asarray(self.temperature.copy())
        topks_d = jnp.asarray(self.top_k.copy())
        topps_d = jnp.asarray(self.top_p.copy())
        adapters_d = jnp.asarray(adapters)
        lora = self._lora.tables() if self._lora is not None else None
        if pc is not None:
            tables_d = pc.tables_device()
            cactive_d = jnp.asarray(cactive)
            kvcap_d = jnp.asarray(kvcap)
            with self._cold_dispatch("ragged", "paged", pc.quantized, N,
                                     lora is not None):
                if pc.quantized:
                    (packed, last_logits, pc.k_pool, pc.v_pool, pc.ks_pool,
                     pc.vs_pool, new_state) = batch_ops.ragged_step_paged_q(
                        cfg, self.params, pc.k_pool, pc.v_pool,
                        pc.ks_pool, pc.vs_pool, state, tables_d, chunk_d,
                        start_d, cactive_d, kvcap_d, finish_d, newlen_d,
                        budgets_d, stops_d, temps_d, topks_d, topps_d,
                        rids_d, self._rng_root, mask_d, N,
                        adapters=adapters_d, lora=lora,
                    )
                else:
                    (packed, last_logits, pc.k_pool, pc.v_pool,
                     new_state) = batch_ops.ragged_step_paged(
                        cfg, self.params, pc.k_pool, pc.v_pool, state,
                        tables_d, chunk_d, start_d, cactive_d, kvcap_d,
                        finish_d, newlen_d, budgets_d, stops_d, temps_d,
                        topks_d, topps_d, rids_d, self._rng_root,
                        mask_d, N, adapters=adapters_d, lora=lora,
                    )
            new_cache = self.cache  # dense path untouched
        else:
            with self._cold_dispatch("ragged", "dense",
                                     self.cache.quantized, N,
                                     lora is not None):
                (packed, last_logits, new_cache,
                 new_state) = batch_ops.ragged_step(
                    cfg, self.params, self.cache, state, chunk_d, start_d,
                    finish_d, newlen_d, budgets_d, stops_d, temps_d,
                    topks_d, topps_d, rids_d, self._rng_root, mask_d, N,
                    adapters=adapters_d, lora=lora,
                )
        prefill_rows = []
        for slot, cursor, req, start_pos, n in chunk_rows:
            idx = cursor.chunk_index
            cursor.chunk_index += 1
            cursor.dispatched = start_pos + n
            fin = bool(finish[slot])
            prefill_rows.append((slot, req, cursor, start_pos, n, fin, idx))
            if self._tracer is not None and req.timeline is not None:
                span = self._req_span(
                    f"prefill_chunk:{idx}", "serve.prefill_chunk", req
                )
                span.set_attribute("chunk.index", idx)
                span.set_attribute("chunk.tokens", n)
                span.set_attribute("chunk.start", start_pos)
                span.set_attribute("chunk.final", fin)
                # warm-transfer attribution: which tier served this
                # request's cached prefix (miss = fully computed)
                span.set_attribute(
                    "prefix_tier", req.timeline.prefix_tier or "miss"
                )
        return packed, last_logits, new_cache, new_state, prefill_rows

    def _consume_block(self, rec: _Inflight) -> None:
        # declared unpack site (kernel_contracts.UNPACK_SITES): the
        # column offsets below are checked against the 'ragged' pack
        # layout — tokens | done | n_valid | first — by kernelcheck
        packed = _block_sync(rec.packed)  # THE one sync for N device steps
        # the sync returned: a warm restart may have replaced this thread
        # while it waited — its tokens belong to requests already settled
        # or requeued, so commit nothing (and don't stamp a heartbeat that
        # would mask the REPLACEMENT thread's health)
        self._check_retired()
        self.heartbeat = time.monotonic()  # the sync returned: progress
        now = time.perf_counter()
        step_time = now - (
            self._last_consume_t if self._last_consume_t is not None
            else rec.dispatched_at
        )
        self._last_consume_t = now

        n_active = 0
        for slot, req in rec.rows:
            if self.slots[slot] is not req:
                continue  # retired (and possibly re-admitted) since dispatch
            n_active += 1
            n_valid = int(packed[slot, rec.steps + 1])
            device_done = bool(packed[slot, rec.steps])
            committed = 0
            for i in range(n_valid):
                self._commit_token(slot, req, int(packed[slot, i]))
                committed += 1
                if self.slots[slot] is not req:
                    break  # retired mid-block: discard the tail tokens
            if req.timeline is not None:
                # flight-recorder stamp at the block's ONE host sync:
                # COMMITTED tokens only (a mid-block retire discards the
                # tail — the spec path's `committed` twin), no extra
                # device read, and no timestamp passed (`now` is
                # perf_counter; the timeline's clock is monotonic)
                req.timeline.block(committed)
            if self.slots[slot] is not req:
                continue
            # committed residency advances by what the device actually
            # emitted (the device carry already did)
            self.cache_len[slot] += n_valid
            if self.paged_cache is not None:
                self.paged_cache.advance_slot(slot, n_valid)
            if req.kv_exhausted:
                # clamped at dispatch time: retire with the pool-pressure
                # reason, but only once NO younger in-flight block still
                # carries tokens for this row (decode_sync_every >= 2 can
                # have several) — retiring earlier would discard tokens
                # the client paid for via the consume identity check
                if not self._slot_in_flight(slot, req):
                    self._retire(slot, "kv_exhausted")
            elif device_done:
                # defensive: _commit_token's own stop/limit chain normally
                # retired the row on its last committed token already —
                # this catches a host/device divergence rather than
                # leaving a device-frozen row parked in a slot forever
                self._retire(
                    slot,
                    "stop" if req.tokens and req.tokens[-1] in req.stop_ids
                    else "length",
                )

        # -- prefill-chunk rows (ragged dispatches only): commit each
        # chunk's residency, feed the chunk-prefix cache, and admit rows
        # whose prompt just finished — their device-sampled first token
        # rides the same packed sync in the trailing column
        for slot, req, cursor, start_pos, n, fin, idx in rec.prefill_rows:
            if (self.slots[slot] is not req
                    or self._cursors.get(slot) is not cursor):
                continue  # retired/requeued since dispatch: stale chunk
            n_active += 1
            cursor.committed = start_pos + n
            self.cache_len[slot] = cursor.committed
            if self.paged_cache is not None:
                self.paged_cache.advance_slot(slot, n)
            tl = req.timeline
            if tl is not None:
                tl.chunk(idx, n, prefix_hit=False, start=start_pos)
                tl.end_span(f"prefill_chunk:{idx}")
            if self._metrics:
                self._metrics.record_histogram(
                    "app_prefill_chunk_tokens", n, kind="compute",
                )
            # only whole-chunk-aligned spans have a precomputed key: the
            # lookup walk probes exactly (k*C, k*C+C|total), and the paged
            # extraction needs a page-aligned start — the planner
            # guarantees this shape; a missing key (future policy drift)
            # skips the put instead of failing the engine loop
            put_key = (
                cursor.cache_keys.get((start_pos, start_pos + n))
                if cursor.cache_keys is not None else None
            )
            if (self._prefix_cache is not None and self._chunk_cache_enabled
                    and rec.last_logits is not None and put_key is not None):
                # chunk-prefix cache PUT: the chunk's K/V just became
                # resident — extract its slab (pure device reads, no sync;
                # the slices/gathers are fresh buffers safe to retain) and
                # store it with the prefix's last-position logits, so a
                # later prompt sharing this prefix skips the chunk
                if self.paged_cache is not None:
                    k_slab, v_slab = self.paged_cache.read_span(
                        slot, start_pos, start_pos + n
                    )
                else:
                    k_slab = self.cache.k[:, slot, start_pos : start_pos + n]
                    v_slab = self.cache.v[:, slot, start_pos : start_pos + n]
                self._prefix_cache.put(
                    put_key,
                    (rec.last_logits[slot : slot + 1], k_slab, v_slab),
                )
            if fin:
                self._cursors.pop(slot, None)
                first_id = int(packed[slot, rec.steps + 2])
                self._commit_first_token(slot, req, first_id)

        if self._metrics and n_active:
            host_ms = (rec.host_s + (time.perf_counter() - now)) * 1e3
            self._metrics.record_histogram(
                "app_tpot_seconds", step_time / rec.steps
            )
            self._metrics.record_histogram(
                "app_decode_block_seconds", step_time
            )
            self._metrics.set_gauge(
                "app_batch_occupancy", n_active / self.config.max_slots
            )
            self._metrics.set_gauge(
                "app_kv_cache_pages_used",
                int(sum(int(self.cache_len[s]) for s, _ in rec.rows)),
            )
            # the tentpole's success metric: host time per decode step
            # (dispatch bookkeeping + this consume, excluding the sync
            # wait) must stay a small fraction of decode_step_ms
            self._metrics.set_gauge(
                "app_decode_host_ms_per_step", host_ms / rec.steps
            )
            self._metrics.set_gauge("app_decode_block_size", rec.steps)
            with self._detok_mu:
                depth = self._detok_depth
            self._metrics.set_gauge("app_detok_queue_depth", depth)

    def _commit_first_token(self, slot: int, req: _Request,
                            first_id: int) -> None:
        """THE first-token commit tail, shared by the monolithic prefill
        path (_commit_prefilled, which scatters the _pending_admit fold
        first) and the ragged chunked path (where the token was sampled
        on device and folded into the DecodeState inside the dispatch):
        TTFT stamps/metrics, emission, and the ONE stop/length retire
        chain — a divergence between the two admission routes is exactly
        the bug class sharing this prevents."""
        self.last_token[slot] = first_id
        resumed = req.first_token_at is not None  # preempt/resume round trip
        if not resumed:
            req.first_token_at = time.perf_counter()
            ttft = req.first_token_at - req.created
            self._shed.observe_ttft(ttft)
        tl = req.timeline
        if tl is not None:
            # prefill end + first token share the commit instant: the
            # sampled first token IS the prefill's last output. First
            # stamp wins, so a resumed request keeps its original TTFT.
            tl.stamp("prefill_end")
            tl.stamp("first_token")
            tl.end_span("prefill")  # no-op on the chunked path (per-chunk
            # spans end at their own consumes)
        if self._metrics and not resumed:
            self._metrics.record_histogram("app_ttft_seconds", ttft)
            # tenant rides as an EXTRA labeled series (tenant-less
            # traffic keeps the bare source=engine series, so existing
            # scrapes and the hedge-floor percentile read unchanged)
            labels = {"source": "engine"}
            if req.tenant:
                labels["tenant"] = req.tenant
            self._metrics.record_histogram(
                "app_request_ttft_seconds", ttft, **labels
            )
        if req.prefill_only:
            # disaggregated prefill phase: the prompt KV (and the cached
            # last-position logits) are what the caller wanted — they sit
            # in the prefix cache for the decode replica's handoff fetch.
            # Retire NOW, before any decode step or token emission: the
            # DECODE replica samples the identical first token from the
            # migrated logits, so emitting here would double-serve it.
            self._retire(slot, "handoff")
            return
        self._emit_token(req, first_id)
        self._check_retired()  # stream_cb may have blocked across a restart
        if first_id in req.stop_ids:
            self._retire(slot, "stop")
        elif len(req.tokens) >= req.max_new_tokens:
            self._retire(slot, "length")
        elif tl is not None and self._tracer is not None:
            self._req_span("decode", "serve.decode", req)

    # -- bookkeeping -----------------------------------------------------------
    def _commit_token(self, slot: int, req: _Request, token_id: int) -> None:
        """Deliver one decoded token and run the retire chain — the ONE
        place stop/limit semantics live for both the pipelined consume
        and the speculative commit paths."""
        self.last_token[slot] = token_id
        self._emit_token(req, token_id)
        # a stream_cb is client code and can block for minutes: a warm
        # restart may have replaced this thread while it sat inside the
        # emit — the retire chain below would free the REPLACEMENT
        # engine's slot/pages, so a retired thread unwinds here instead
        self._check_retired()
        if req.canceled:
            self._retire(slot, "cancel")
        elif req.expired(time.perf_counter()):
            self._retire(slot, "deadline_exceeded")
        elif token_id in req.stop_ids:
            self._retire(slot, "stop")
        elif len(req.tokens) >= req.max_new_tokens:
            # a pool-pressure clamp reports its own reason: "length" must
            # stay unambiguous — "the request's own token budget ran out"
            self._retire(slot, "kv_exhausted" if req.kv_exhausted else "length")
        elif len(req.prompt_ids) + len(req.tokens) >= self.config.max_seq_len:
            self._retire(slot, "length")

    def _emit_token(self, req: _Request, token_id: int) -> None:
        req.tokens.append(token_id)
        if req.stream_cb is not None and token_id not in req.stop_ids:
            self._emit_async(req, token_id)

    def _emit_async(self, req: _Request, token_id: int) -> None:
        """Queue detokenization + stream emission on the single-worker
        executor: a stream_cb is client code and can block for seconds —
        the decode loop must overlap the device block, never wait on the
        client (ROADMAP item 4). One worker keeps per-request frame order;
        a callback failure cancels the request like the inline path did."""

        def task() -> None:
            try:
                req.stream_cb(token_id, self.tokenizer.decode([token_id]), False)
            except Exception:
                req.canceled = True

        # executor already shut down (stop() raced the emit): the token
        # frame is dropped — nobody can read it from a stopped engine
        self._submit_detok(task)

    def _submit_detok(self, task: Callable[[], None]) -> bool:
        """Queue work on the detok executor with depth accounting (the
        backlog gauge + the idle event drain() waits on). Returns False
        when the executor is already shut down — the caller decides
        whether to run inline (terminal settlement) or drop (a stream
        frame nobody can read anymore)."""
        with self._detok_mu:
            self._detok_depth += 1
            self._detok_idle.clear()

        def run() -> None:
            try:
                task()
            finally:
                self._detok_done()

        try:
            self._detok.submit(run)
            return True
        except RuntimeError:
            self._detok_done()
            return False

    def _detok_done(self) -> None:
        with self._detok_mu:
            self._detok_depth -= 1
            if self._detok_depth == 0:
                self._detok_idle.set()

    def _retire(self, slot: int, reason: str) -> None:
        req = self.slots[slot]
        if req is not None and req.timeline is not None:
            # final residency facts for the decode span, read from the
            # host mirrors BEFORE the slot is reclaimed (zero device reads)
            dspan = req.timeline.spans.get("decode")
            if dspan is not None:
                resident = int(self.cache_len[slot])
                dspan.set_attribute(
                    "batch.size",
                    sum(1 for s in self.slots if s is not None),
                )
                dspan.set_attribute("kv.resident_tokens", resident)
                if self.paged_cache is not None:
                    page = self.config.kv_page_size
                    dspan.set_attribute(
                        "kv.pages", (resident + page - 1) // page
                    )
        self.slots[slot] = None
        self.cache_len[slot] = 0
        self.adapter_idx[slot] = 0
        self._preempt_pending.discard(slot)
        self._cursors.pop(slot, None)  # a mid-chunked-prefill retire
        if self.paged_cache is not None:
            self.paged_cache.free_slot(slot)
        try:
            self._sched.release(slot)
        except KeyError:
            pass
        if req is not None:
            self._lora_release(req)
            with self._count_lock:
                self._by_id.pop(req.id, None)
            self._finish(req, reason)

    def _try_resolve(self, req: _Request, value: Any = None,
                     exc: Exception | None = None) -> bool:
        """Settle a request's future, tolerant of a concurrent settler:
        done()-then-set is check-then-act, and BOTH sides race — the engine
        thread (_finish/_expire/_fail_all) against drain()/stop() sweeps.
        Losing must never raise InvalidStateError: on the engine thread
        that would escalate a benign lost race into _fail_all.

        This is ALSO the one terminal gate for the flight recorder: the
        settlement winner (and only the winner) marks the request's
        timeline terminal and force-ends its remaining spans — which is
        what makes "exactly one terminal phase per request" and "zero
        leaked spans after drain" chaos-auditable invariants instead of
        per-call-site discipline."""
        if req.future.done():
            return False
        try:
            if exc is not None:
                req.future.set_exception(exc)
            else:
                req.future.set_result(value)
        except Exception:
            return False  # the other settler won the race
        self._record_terminal(req, value, exc)
        # HA plane: the settlement winner (and only the winner) flips the
        # dedup registry. A successful result is retained for duplicate
        # replay; an exception terminal forgets the key so a genuine
        # client retry re-runs as a fresh request.
        if req.idem_key is not None:
            if exc is None and value is not None:
                self._dedup.settle(req.idem_key, value)
            else:
                self._dedup.forget(req.idem_key)
        return True

    @staticmethod
    def _terminal_reason(value: Any, exc: Exception | None) -> str:
        if value is not None:
            return getattr(value, "finish_reason", "stop")
        if isinstance(exc, ErrorDeadlineExceeded):
            return "deadline_exceeded"
        if isinstance(exc, ErrorTooManyRequests):
            return "shed"
        if isinstance(exc, ErrorServiceUnavailable):
            return "unavailable"
        if isinstance(exc, ErrorRequestEntityTooLarge):
            return "too_large"
        return "error"

    def _record_terminal(self, req: _Request, value: Any,
                         exc: Exception | None) -> None:
        tl = req.timeline
        if tl is None:
            return
        dspan = tl.spans.get("decode")
        if dspan is not None:
            dspan.set_attribute("tokens.out", len(req.tokens))
            dspan.set_attribute("decode.blocks", tl.decode_blocks)
        reason = self._terminal_reason(value, exc)
        # snapshot: the engine thread can be opening a span concurrently
        # with a sweep thread settling (the lost opener re-closes, above)
        for span in list(tl.spans.values()):
            if span.end_ns is None:  # ended spans are already exported
                span.set_attribute("request.finish_reason", reason)
        self.timeline.finish(tl, reason)

    def _settle_future(self, req: _Request, exc: Exception) -> None:
        """Fail a request's future from OUTSIDE the engine thread. Fires
        the stream's done callback so consumers blocked on the token queue
        wake up."""
        if self._try_resolve(req, exc=exc) and req.stream_cb is not None:
            try:
                req.stream_cb(-1, "", True)
            except Exception:
                pass

    def _expire(self, req: _Request) -> None:
        """Terminal state for a request whose deadline passed while still
        queued: it never prefilled, so there is no partial result — the
        caller gets 504 / DEADLINE_EXCEEDED."""
        if self._metrics:
            self._metrics.increment_counter("app_requests_deadline_exceeded_total")
        if req.stream_cb is not None:
            try:
                req.stream_cb(-1, "", True)
            except Exception:
                pass
        self._try_resolve(req, exc=ErrorDeadlineExceeded())

    def _finish(self, req: _Request, reason: str) -> None:
        # flush the running iteration's busy slice BEFORE the settlement
        # is queued: once the caller observes its result, the duty-cycle
        # counter must already show the work that produced it
        self._flush_busy()
        now = time.perf_counter()
        self._shed.observe_request(now - req.created)
        if reason == "deadline_exceeded" and self._metrics:
            self._metrics.increment_counter("app_requests_deadline_exceeded_total")
        if reason == "kv_exhausted" and self._metrics:
            self._metrics.increment_counter("app_requests_kv_exhausted_total")
        out_ids = [t for t in req.tokens if t not in req.stop_ids]
        ttft = (req.first_token_at - req.created) if req.first_token_at else 0.0
        duration = now - req.created
        if self._metrics:
            labels = {"tenant": req.tenant} if req.tenant else {}
            self._metrics.record_histogram(
                "app_request_e2e_seconds", duration, **labels
            )
        # the detok/settlement span covers the off-engine-thread tail:
        # full-text detokenization, the terminal stream frame, future
        # resolution — it ends at the terminal mark inside _try_resolve
        if self._tracer is not None:
            self._req_span("detok", "serve.detok", req)

        def settle() -> None:
            # full-text detokenization + terminal frame + future settlement
            # run behind any still-queued token frames (same single-worker
            # executor: the done frame can never overtake a token frame)
            result = GenerationResult(
                request_id=req.id,
                text=self.tokenizer.decode(out_ids),
                token_ids=out_ids,
                prompt_tokens=len(req.prompt_ids),
                completion_tokens=len(out_ids),
                finish_reason=reason,
                ttft_s=ttft,
                duration_s=duration,
            )
            if req.stream_cb is not None:
                try:
                    req.stream_cb(-1, "", True)
                except Exception:
                    pass
            if req.timeline is not None:
                req.timeline.stamp("detok_done")
            if not self._try_resolve(req, value=result) and \
                    req.timeline is not None:
                # a drain/stop sweep won the settlement race and closed
                # the spans BEFORE this path opened its decode/detok
                # spans — close again so nothing opened after the
                # sweep's pass can leak (close_spans is idempotent)
                req.timeline.close_spans()

        if not self._submit_detok(settle):
            # executor already shut down (stopping engine): settle inline —
            # a terminal state must never be lost to a lifecycle race
            settle()

    def _reset_prefix_cache(self) -> None:
        """A DEVICE-level failure may have poisoned cached prefill slabs
        the same way it poisoned the live KV (host-only exceptions can't,
        so the cache survives those); a cold prefix cache only costs
        recompute, a dead one fails every hit forever. Injected caches
        follow the container Cache protocol, which has no clear() — drop
        an unclearable cache rather than keep serving poisoned entries
        out of it."""
        if self._prefix_cache is None:
            return
        clear = getattr(self._prefix_cache, "clear", None)
        try:
            if clear is not None:
                clear()
            else:
                self._prefix_cache = None
        except Exception:
            self._prefix_cache = None

    def _maybe_device_loss(self) -> None:
        """The ``device.loss`` chaos point: when the schedule says this
        dispatch loses the device, the persistent KV buffers are POISONED
        for real (deleted, exactly what a failed-after-donation dispatch
        leaves behind) before the fault propagates — so recovery exercises
        the genuine rebuild path, not a pretend one."""
        try:
            chaos.maybe_fail("device.loss")
        except Exception:
            self._poison_device()
            raise

    def _poison_device(self) -> None:
        try:
            if self.cache is not None:
                self.cache.k.delete()
                self.cache.v.delete()
            elif self.paged_cache is not None:
                self.paged_cache.k_pool.delete()
                self.paged_cache.v_pool.delete()
        except Exception:
            pass  # already deleted / backend gone: the poison took either way

    def _kv_unhealthy(self) -> bool:
        """True when the persistent KV storage cannot serve another step:
        donated-and-deleted buffers (a dispatch that failed AFTER its
        donation committed), or error-state outputs (an async dispatch that
        failed after its output was already rebound — ``is_deleted()`` is
        False on those, so a one-element sync probe is the only reliable
        detector). Either way every subsequent step would raise forever.
        CPU runs delete donated buffers too (jax 0.9), so tests exercise
        the donation half for real."""
        arr = None
        if self.cache is not None:
            arr = self.cache.k
        elif self.paged_cache is not None:
            arr = self.paged_cache.k_pool
        if arr is None:
            return False
        try:
            if arr.is_deleted():
                return True
            float(arr[(0,) * arr.ndim])  # sync probe: poisoned arrays raise
            return False
        except Exception:
            return True

    def _make_dense_cache(self) -> llama.KVCache:
        """The one dense slot-cache constructor, shared by __init__ and
        donation-failure recovery so the rebuilt cache can never drift
        from the one the engine started with."""
        return llama.KVCache.create(
            self.model_cfg, self.config.max_slots,
            max_len=self.config.max_seq_len,
            kv_dtype="int8" if self.config.kv_dtype == "int8" else None,
        )

    def _make_paged_cache(self):
        """The one paged pool constructor, shared by __init__ and the
        supervisor's warm restart so a rebuilt pool can never drift from
        the one the engine started with."""
        from gofr_tpu.ops.paged_attention import INT8_MIN_PAGE
        from gofr_tpu.serving.kv_cache import PagedKVCache

        B, S = self.config.max_slots, self.config.max_seq_len
        page = self.config.kv_page_size
        if self.config.kv_dtype == "int8" and page < INT8_MIN_PAGE:
            import jax as _jax

            if _jax.default_backend() == "tpu":
                # below the int8 Mosaic tile the kernel would silently
                # fall back to the full-gather reference, INVERTING the
                # bandwidth win int8 exists for (code-review r4)
                raise ValueError(
                    f"TPU_KV_DTYPE=int8 with TPU_KV_LAYOUT=paged needs "
                    f"TPU_KV_PAGE_SIZE>={INT8_MIN_PAGE} on TPU (got "
                    f"{page}): smaller pages violate the int8 Mosaic "
                    "tile and lose the halved-bandwidth kernel path"
                )
        num_pages = self.config.kv_num_pages or (B * S + page - 1) // page
        return PagedKVCache(
            self.model_cfg, num_pages=num_pages, page_size=page,
            max_slots=B, max_seq_len=S,
            kv_dtype="int8" if self.config.kv_dtype == "int8" else None,
        )

    def _init_runtime_state(self) -> None:
        """Executable-level mutable state, built HERE and only here so
        __init__ and the supervisor's warm restart can never drift: the KV
        storage, the per-slot sampling/length arrays, the pipelined-decode
        device state, and the admission scheduler. A field added to one
        construction path but not the other would survive a restart with
        stale shape or contents and only fail on the first post-restart
        batch — sharing the constructor makes that class of bug impossible.

        Admission policy lives in the native scheduler (native/runtime/
        gofr_runtime.cc; Python fallback when no toolchain): priority +
        FIFO queue, free-slot assignment, per-step prefill token budget.

        CPU-free decode state (ROADMAP item 4, Blink arXiv:2604.07609):
        the device owns the per-row carry (batch_ops.DecodeState — last
        token, resident length, done flag, token budget, stop id, sampling
        params, RNG), sampling AND stop evaluation run inside the N-step
        block executable, and the host's single materialization per block
        (_block_sync) overlaps the next block's compute. The numpy arrays
        here are host MIRRORS: authoritative for admission/recovery
        rebuilds, advanced at each consume."""
        B = self.config.max_slots
        if self.config.kv_layout == "paged":
            self.paged_cache = self._make_paged_cache()
            self.cache = None
        else:
            self.paged_cache = None
            self.cache = self._make_dense_cache()
        self.cache_len = np.zeros(B, np.int32)  # host mirror (committed tokens)
        self.last_token = np.zeros(B, np.int32)
        self.temperature = np.ones(B, np.float32)
        self.top_k = np.zeros(B, np.int32)
        self.top_p = np.ones(B, np.float32)
        # per-slot LoRA adapter-table slot (0 = base): the host mirror of
        # DecodeState.adapter, authoritative for recovery rebuilds
        self.adapter_idx = np.zeros(B, np.int32)
        self.slots: list[_Request | None] = [None] * B
        # the pipelined-block queue: dispatched-but-unmaterialized blocks,
        # oldest first; depth bounded by decode_sync_every
        self._inflight_q: collections.deque[_Inflight] = collections.deque()
        # device-resident DecodeState carry (batch_ops.DecodeState): the
        # host never reads it; None = rebuild from the host mirrors at the
        # next dispatch (cold start / post-failure)
        self._dec_state: Any = None
        # slots prefilled since the last dispatch, folded into the device
        # state by ONE donated scatter: slot → (first token, resident len,
        # remaining budget, stop id, adapter slot)
        self._pending_admit: dict[int, tuple[int, int, int, int, int]] = {}
        self._mask_dev: Any = None  # cached device active mask
        self._mask_host: Any = None  # host copy the cache was built from
        self._last_consume_t: float | None = None
        # continuous batching: per-slot chunk cursors for prompts mid-
        # chunked-prefill (serving/stepplan.py). A slot with a live cursor
        # holds its request but is NOT a decode row yet; the cursor's
        # committed/dispatched carry the chunk position between
        # iterations. Rebuilt empty on warm restart — partially-prefilled
        # requests requeue from chunk 0 (their KV died with the pools).
        self._cursors: dict[int, ChunkCursor] = {}
        self._cursor_seq = 0
        # decode rows marked for preemption: no further blocks dispatch
        # for them; the ladder pages them out once their pipeline drains
        self._preempt_pending: set[int] = set()
        self._plan_gauges: tuple | None = None  # last-exported step-plan gauges
        self._sched = Scheduler(
            self.config.max_slots, self.config.max_queue,
            self.config.prefill_token_budget,
        )

    def _rebuild_kv(self) -> None:
        """Reallocate the persistent KV storage after donated buffers were
        lost mid-dispatch. Every slot's residency is gone, so this only
        runs on the _fail_all path where all active requests already
        failed; fresh zeroed storage restores a servable engine."""
        if self.cache is not None:
            self.cache = self._make_dense_cache()
        elif self.paged_cache is not None:
            self.paged_cache.reset_pools()
        if self._logger:
            self._logger.warn(
                "KV storage rebuilt after a failed dispatch deleted the "
                "donated device buffers"
            )

    def _fail_all(self, exc: Exception, kv_unhealthy: bool | None = None) -> None:
        # pipeline state is unrecoverable mid-step: drop the in-flight
        # record and force re-upload of device-resident state. Chunk
        # cursors die with it — their rows fail through the slot sweep
        # below like any other active request.
        self._inflight_q.clear()
        self._cursors.clear()
        self._preempt_pending.clear()
        self._pending_admit.clear()
        self._dec_state = None  # rebuilt from host mirrors at next dispatch
        self._mask_dev = None
        self._mask_host = None
        self._last_consume_t = None
        if kv_unhealthy is None:
            kv_unhealthy = self._kv_unhealthy()  # callers pass a fresh verdict
        if kv_unhealthy:
            # visible to the supervisor's watchdog: repeated poisonings in a
            # short window mean the in-place KV rebuild is not sticking —
            # escalate to a full warm restart instead of thrashing here
            self.device_poisonings += 1
            try:
                self._rebuild_kv()
            except Exception as rebuild_exc:
                # backend still down: keep the loop thread alive — the next
                # failure re-enters _fail_all and retries the rebuild
                if self._logger:
                    self._logger.error(f"KV rebuild failed: {rebuild_exc}")
            self._reset_prefix_cache()
        for slot, req in enumerate(self.slots):
            if req is not None:
                self.slots[slot] = None
                self.cache_len[slot] = 0
                self._lora_release(req)
                if self.paged_cache is not None:
                    try:
                        self.paged_cache.free_slot(slot)
                    except Exception:
                        pass
                try:
                    self._sched.release(slot)
                except KeyError:
                    pass
                with self._count_lock:
                    self._by_id.pop(req.id, None)
                self._try_resolve(req, exc=exc)

    def _buckets(self) -> tuple[int, ...]:
        return tuple(
            b for b in self.config.prefill_buckets if b <= self.config.max_seq_len
        ) or (self.config.max_seq_len,)

    def _observe_queue(self, depth: int | None = None) -> None:
        if self._metrics:
            if depth is None:
                depth = self._sched.stats()["queue_depth"]
            self._metrics.set_gauge("app_batch_queue_depth", depth)

    def _req_span(self, key: str, name: str, req: _Request) -> Any:
        """Open a lifecycle span for one request, parented on its queue
        span (the tree reads caller → engine.queue → prefill/decode/detok)
        or the caller's trace context. Registered on the request's
        timeline so terminal settlement force-ends whatever a fault path
        left open. Returns a context manager either way (nullcontext when
        tracing is off); ``activate=False`` keeps the engine thread's
        contextvars untouched."""
        if self._tracer is None:
            return contextlib.nullcontext()
        tl = req.timeline
        parent = (
            tl.spans.get("queue") if tl is not None else None
        ) or req.trace_ctx
        span = self._tracer.start_span(
            name, parent=parent, kind="internal", activate=False
        )
        span.set_attribute("request.id", req.id)
        if req.tenant:
            # per-tenant SLO attainment is scraped straight off the
            # serve.* spans (docs/serving.md "Multi-tenancy")
            span.set_attribute("tenant", req.tenant)
        if req.adapter_id:
            span.set_attribute("lora.adapter", req.adapter_id)
        if tl is not None:
            tl.open_span(key, span)
        return span

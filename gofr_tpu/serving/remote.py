"""The remote token-stream transport, client half (docs/serving.md).

Before this module, a remote replica behind ``HTTPReplica`` served unary
``POST /generate``: the router could not see a single token until the
WHOLE generation finished, so remote TTFT was capped at completion
latency, failover/hedging lost their pre-first-token semantics across
the wire, and a canceled hedge twin kept burning decode steps to the
end. This is the fix's transport layer (ROADMAP item 2; the
vLLM-vs-TGI methodology, arXiv:2511.17593, makes token-level streaming
latency the measurable axis):

- the server side is ``POST /generate/stream`` (serving/handlers.py):
  Server-Sent Events over chunked transfer — an ``{"id": N}`` frame
  first (the cancel wire's name for the request), one
  ``{"token", "text"}`` frame per decoded token, a terminal
  ``{"finish_reason", "usage"}`` frame, then ``data: [DONE]``;
- this module drives the client side over
  ``HTTPService.stream`` (service/client.py), dispatching each frame
  the moment it arrives, and maps the wire's terminal/error frames back
  to the same typed errors the in-process engine raises — the router's
  failover machinery cannot tell a remote replica from a local one;
- ``POST /generate/cancel {"id": N}`` stops the remote decode: the
  engine retires the row at the next block sync (within one block),
  and the stream ends with finish_reason ``cancel``.

The ``stream.remote`` chaos point sits on every frame read: a fault
there IS the transport tearing mid-stream — the reader raises
``ConnectionError`` and the router decides (pre-first-token: failover;
after: the typed error reaches the client, a stream is not idempotent).

This module runs on HTTPReplica's worker pool threads, never the event
loop — the frame reads BLOCK by design, exactly like the engine's
stream_cb contract expects.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable

from gofr_tpu import chaos
from gofr_tpu.http.errors import (
    ErrorDeadlineExceeded,
    ErrorServiceUnavailable,
    ErrorTooManyRequests,
)
from gofr_tpu.service.options import retry_after_from_headers

__all__ = ["iter_events", "run_stream", "error_from_status"]

STREAM_PATH = "/generate/stream"
CANCEL_PATH = "/generate/cancel"


def error_from_status(status: int, detail: str,
                      headers: dict[str, str] | None = None) -> Exception:
    """Map a remote replica's HTTP status (response head or in-stream
    error frame) to the typed error the router's RETRIABLE_ERRORS set
    keys on — the wire must not demote a retriable 503 to an opaque
    RuntimeError."""
    if status == 503:
        return ErrorServiceUnavailable(
            detail, retry_after=retry_after_from_headers(headers or {})
        )
    if status == 429:
        return ErrorTooManyRequests(
            retry_after=retry_after_from_headers(headers or {})
        )
    if status == 504:
        return ErrorDeadlineExceeded(detail)
    return RuntimeError(detail)


def iter_events(resp: Any, deadline_abs: float | None = None) -> Any:
    """Parse SSE ``data:`` frames off a streaming response, yielding
    each decoded JSON event as it arrives; returns at ``[DONE]`` or
    stream end. Unparseable frames are skipped (forward compatibility:
    a newer server may interleave event types this client predates).

    ``deadline_abs`` (``time.monotonic()`` terms) bounds the WHOLE
    stream, not just each socket read: the open-time ``timeout`` only
    caps per-read stalls, so without this gate an expired request keeps
    the remote decode — and this worker thread — running to the final
    frame. Checked between frames; the in-flight read still ends within
    one socket timeout."""
    for line in resp.lines():
        if deadline_abs is not None and time.monotonic() > deadline_abs:
            raise ErrorDeadlineExceeded(
                "remote stream exceeded the request deadline between frames"
            )
        if not line.startswith("data:"):
            continue  # SSE comments / keepalives
        payload = line[5:].strip()
        if payload == "[DONE]":
            return
        # the mid-stream tear seam: a fault here is the transport dying
        # between two frames
        chaos.maybe_fail("stream.remote")
        try:
            event = json.loads(payload)
        except ValueError:
            continue
        if isinstance(event, dict):
            yield event


def run_stream(
    svc: Any,
    payload: dict[str, Any],
    *,
    headers: dict[str, str] | None = None,
    timeout: float | None = None,
    on_id: Callable[[int], None] | None = None,
    on_token: Callable[[int, str], None] | None = None,
    path: str = STREAM_PATH,
) -> dict[str, Any]:
    """Drive one remote streaming generation to its terminal frame.

    Opens ``POST {path}`` through the (breaker-aware) service client's
    ``stream``, dispatches ``on_id`` with the remote request id (the
    cancel wire's handle) and ``on_token`` per token frame, and returns
    the terminal event (``finish_reason`` + ``usage``). Raises the
    typed error for admission-time statuses (503/429/504 — real
    statuses, the head was not 200), for in-stream error frames
    (late deadline/drain, delivered as events because the 200 head was
    already on the wire), and ``ConnectionError`` for a stream that
    tore before its terminal frame."""
    # the request's whole-stream budget: `timeout` is the caller's
    # remaining deadline (HTTPReplica passes its deadline through), so
    # it bounds the open AND the frame loop — per-read socket stalls
    # are capped by the transport, the total by this clock
    deadline_abs = (
        time.monotonic() + timeout if timeout is not None else None
    )
    resp = svc.stream(
        "POST", path, json=payload, headers=headers, timeout=timeout,
    )
    if not resp.ok:
        try:
            detail = resp.read_body().decode("utf-8", "replace")[:200]
        except Exception:
            detail = ""
        finally:
            resp.close()
        raise error_from_status(
            resp.status_code,
            f"remote stream: HTTP {resp.status_code} {detail}".strip(),
            resp.headers,
        )
    terminal: dict[str, Any] | None = None
    try:
        for event in iter_events(resp, deadline_abs=deadline_abs):
            if "error" in event:
                raise error_from_status(
                    int(event.get("status") or 0), str(event["error"])
                )
            if "finish_reason" in event:
                terminal = event
            elif "token" in event:
                if on_token is not None:
                    on_token(int(event["token"]), str(event.get("text", "")))
            elif "id" in event:
                if on_id is not None:
                    on_id(int(event["id"]))
    finally:
        resp.close()
    if terminal is None:
        # the transport died between frames (or the server aborted
        # without its terminal): a retriable transport error — the
        # router knows whether tokens already crossed
        raise ConnectionError("remote stream ended without a terminal frame")
    return terminal

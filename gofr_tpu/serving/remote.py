"""The remote token-stream transport, client half (docs/serving.md).

Before this module, a remote replica behind ``HTTPReplica`` served unary
``POST /generate``: the router could not see a single token until the
WHOLE generation finished, so remote TTFT was capped at completion
latency, failover/hedging lost their pre-first-token semantics across
the wire, and a canceled hedge twin kept burning decode steps to the
end. This is the fix's transport layer (ROADMAP item 2; the
vLLM-vs-TGI methodology, arXiv:2511.17593, makes token-level streaming
latency the measurable axis):

- the server side is ``POST /generate/stream`` (serving/handlers.py):
  Server-Sent Events over chunked transfer — an ``{"id": N}`` frame
  first (the cancel wire's name for the request), one
  ``{"token", "text"}`` frame per decoded token, a terminal
  ``{"finish_reason", "usage"}`` frame, then ``data: [DONE]``;
- this module drives the client side over
  ``HTTPService.stream`` (service/client.py), dispatching each frame
  the moment it arrives, and maps the wire's terminal/error frames back
  to the same typed errors the in-process engine raises — the router's
  failover machinery cannot tell a remote replica from a local one;
- ``POST /generate/cancel {"id": N}`` stops the remote decode: the
  engine retires the row at the next block sync (within one block),
  and the stream ends with finish_reason ``cancel``.

The ``stream.remote`` chaos point sits on every frame read: a fault
there IS the transport tearing mid-stream — the reader raises
``ConnectionError`` and the router decides (pre-first-token: failover;
after: the typed error reaches the client, a stream is not idempotent).

This module runs on HTTPReplica's worker pool threads, never the event
loop — the frame reads BLOCK by design, exactly like the engine's
stream_cb contract expects.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable

from gofr_tpu import chaos
from gofr_tpu.http.errors import (
    ErrorDeadlineExceeded,
    ErrorEntityNotFound,
    ErrorServiceUnavailable,
    ErrorStaleEpoch,
    ErrorTooManyRequests,
)
from gofr_tpu.service.options import retry_after_from_headers

__all__ = [
    "iter_events", "run_stream", "open_resume", "drain_resume",
    "resume_stream", "error_from_status",
]

STREAM_PATH = "/generate/stream"
CANCEL_PATH = "/generate/cancel"


def error_from_status(status: int, detail: str,
                      headers: dict[str, str] | None = None) -> Exception:
    """Map a remote replica's HTTP status (response head or in-stream
    error frame) to the typed error the router's RETRIABLE_ERRORS set
    keys on — the wire must not demote a retriable 503 to an opaque
    RuntimeError."""
    if status == 503:
        return ErrorServiceUnavailable(
            detail, retry_after=retry_after_from_headers(headers or {})
        )
    if status == 429:
        return ErrorTooManyRequests(
            retry_after=retry_after_from_headers(headers or {})
        )
    if status == 504:
        return ErrorDeadlineExceeded(detail)
    if status == 409:
        # the HA fence (docs/robustness.md "The HA plane"): this caller's
        # view of the replica is stale — refresh membership, don't retry
        return ErrorStaleEpoch(detail)
    if status == 404:
        # resume wire: unknown idempotency key or evicted replay window —
        # nothing to re-attach to; the client falls back to a keyed
        # submit (which dedups safely)
        return ErrorEntityNotFound("resume", detail)
    return RuntimeError(detail)


def iter_events(resp: Any, deadline_abs: float | None = None) -> Any:
    """Parse SSE ``data:`` frames off a streaming response, yielding
    each decoded JSON event as it arrives; returns at ``[DONE]`` or
    stream end. Unparseable frames are skipped (forward compatibility:
    a newer server may interleave event types this client predates).

    ``deadline_abs`` (``time.monotonic()`` terms) bounds the WHOLE
    stream, not just each socket read: the open-time ``timeout`` only
    caps per-read stalls, so without this gate an expired request keeps
    the remote decode — and this worker thread — running to the final
    frame. Checked between frames; the in-flight read still ends within
    one socket timeout.

    Sequence numbers (docs/serving.md "Resumable streams"): an ``id:``
    line preceding a frame is attached to the decoded event as ``seq`` —
    the client's ``Last-Event-ID`` re-attach currency. Streams from
    servers that predate sequencing simply yield events without it."""
    last_id: int | None = None
    for line in resp.lines():
        if deadline_abs is not None and time.monotonic() > deadline_abs:
            raise ErrorDeadlineExceeded(
                "remote stream exceeded the request deadline between frames"
            )
        if line.startswith("id:"):
            try:
                last_id = int(line[3:].strip())
            except ValueError:
                last_id = None
            continue
        if not line.startswith("data:"):
            continue  # SSE comments / keepalives
        payload = line[5:].strip()
        if payload == "[DONE]":
            return
        # the mid-stream tear seam: a fault here is the transport dying
        # between two frames
        chaos.maybe_fail("stream.remote")
        try:
            event = json.loads(payload)
        except ValueError:
            continue
        if isinstance(event, dict):
            if last_id is not None:
                event.setdefault("seq", last_id)
                last_id = None
            yield event


def run_stream(
    svc: Any,
    payload: dict[str, Any],
    *,
    headers: dict[str, str] | None = None,
    timeout: float | None = None,
    on_id: Callable[[int], None] | None = None,
    on_token: Callable[[int, str], None] | None = None,
    path: str = STREAM_PATH,
) -> dict[str, Any]:
    """Drive one remote streaming generation to its terminal frame.

    Opens ``POST {path}`` through the (breaker-aware) service client's
    ``stream``, dispatches ``on_id`` with the remote request id (the
    cancel wire's handle) and ``on_token`` per token frame, and returns
    the terminal event (``finish_reason`` + ``usage``). Raises the
    typed error for admission-time statuses (503/429/504 — real
    statuses, the head was not 200), for in-stream error frames
    (late deadline/drain, delivered as events because the 200 head was
    already on the wire), and ``ConnectionError`` for a stream that
    tore before its terminal frame."""
    # the request's whole-stream budget: `timeout` is the caller's
    # remaining deadline (HTTPReplica passes its deadline through), so
    # it bounds the open AND the frame loop — per-read socket stalls
    # are capped by the transport, the total by this clock
    deadline_abs = (
        time.monotonic() + timeout if timeout is not None else None
    )
    resp = svc.stream(
        "POST", path, json=payload, headers=headers, timeout=timeout,
    )
    if not resp.ok:
        try:
            detail = resp.read_body().decode("utf-8", "replace")[:200]
        except Exception:
            detail = ""
        finally:
            resp.close()
        raise error_from_status(
            resp.status_code,
            f"remote stream: HTTP {resp.status_code} {detail}".strip(),
            resp.headers,
        )
    terminal: dict[str, Any] | None = None
    try:
        for event in iter_events(resp, deadline_abs=deadline_abs):
            if "error" in event:
                raise error_from_status(
                    int(event.get("status") or 0), str(event["error"])
                )
            if "finish_reason" in event:
                terminal = event
            elif "token" in event:
                if on_token is not None:
                    on_token(int(event["token"]), str(event.get("text", "")))
            elif "id" in event:
                if on_id is not None:
                    on_id(int(event["id"]))
    finally:
        resp.close()
    if terminal is None:
        # the transport died between frames (or the server aborted
        # without its terminal): a retriable transport error — the
        # router knows whether tokens already crossed
        raise ConnectionError("remote stream ended without a terminal frame")
    return terminal


def open_resume(
    svc: Any,
    idempotency_key: str,
    *,
    last_seq: int = 0,
    fence_epoch: int | None = None,
    timeout: float | None = None,
    path: str = STREAM_PATH,
) -> Any:
    """Open (only) a keyed re-attach: ``POST {path}`` with
    ``Idempotency-Key`` + ``Last-Event-ID`` headers and no body. Raises
    the typed head errors SYNCHRONOUSLY — 404 (unknown key / evicted
    replay window), 409 (stale ``fence_epoch``), 503 — which is what
    lets the router's resume walk classify a replica that never saw the
    key and move to the next one, while the frame drain
    (``drain_resume``) runs on a pool worker. Returns the open streaming
    response (caller owns closing it)."""
    headers = {
        "Idempotency-Key": str(idempotency_key),
        "Last-Event-ID": str(int(last_seq)),
    }
    if fence_epoch:
        headers["X-Fence-Epoch"] = str(int(fence_epoch))
    resp = svc.stream("POST", path, json={}, headers=headers, timeout=timeout)
    if not resp.ok:
        try:
            detail = resp.read_body().decode("utf-8", "replace")[:200]
        except Exception:
            detail = ""
        finally:
            resp.close()
        raise error_from_status(
            resp.status_code,
            f"remote resume: HTTP {resp.status_code} {detail}".strip(),
            resp.headers,
        )
    return resp


def drain_resume(
    resp: Any,
    *,
    deadline_abs: float | None = None,
    on_frame: Callable[[int, int, str], None] | None = None,
) -> dict[str, Any]:
    """Drive an open resume response to its terminal frame.
    ``on_frame(seq, token_id, text)`` fires per replayed or live token
    frame; returns the terminal event. Closes the response."""
    terminal: dict[str, Any] | None = None
    try:
        for event in iter_events(resp, deadline_abs=deadline_abs):
            if "error" in event:
                raise error_from_status(
                    int(event.get("status") or 0), str(event["error"])
                )
            if "finish_reason" in event:
                terminal = event
            elif "token" in event:
                if on_frame is not None:
                    on_frame(
                        int(event.get("seq") or 0),
                        int(event["token"]),
                        str(event.get("text", "")),
                    )
    finally:
        resp.close()
    if terminal is None:
        raise ConnectionError("remote resume ended without a terminal frame")
    return terminal


def resume_stream(
    svc: Any,
    idempotency_key: str,
    *,
    last_seq: int = 0,
    fence_epoch: int | None = None,
    timeout: float | None = None,
    on_frame: Callable[[int, int, str], None] | None = None,
    path: str = STREAM_PATH,
) -> dict[str, Any]:
    """Re-attach to a keyed remote stream (docs/serving.md "Resumable
    streams"): ``open_resume`` + ``drain_resume`` on the caller thread —
    the server replays every frame past ``last_seq`` token-identically
    and rides the live generation. Typed raises: 404 (unknown key /
    evicted replay window — fall back to a keyed submit, which dedups
    safely), 409 (stale ``fence_epoch``), plus everything ``run_stream``
    can."""
    deadline_abs = (
        time.monotonic() + timeout if timeout is not None else None
    )
    resp = open_resume(
        svc, idempotency_key, last_seq=last_seq, fence_epoch=fence_epoch,
        timeout=timeout, path=path,
    )
    return drain_resume(resp, deadline_abs=deadline_abs, on_frame=on_frame)

"""Transport-agnostic handler execution.

Reference parity: pkg/gofr/handler.go — ``Handler func(*Context)(any,error)``
(handler.go:25) becomes "a callable taking Context returning a result (or
raising)". ``execute_handler`` reproduces ServeHTTP's semantics
(handler.go:55-113): the user function runs isolated (worker thread for sync
handlers — the analogue of the reference's per-request goroutine — or as an
awaitable for async handlers), raced against the request timeout
(``REQUEST_TIMEOUT``) and client disconnect; panics are caught and become
ErrorPanicRecovery with a logged stack. ``health_handler`` / ``alive`` and
the catch-all 404 mirror handler.go:115-151.
"""

from __future__ import annotations

import asyncio
import traceback
from typing import Any, Awaitable, Callable

from gofr_tpu.context import Context
from gofr_tpu.http.errors import (
    ErrorInvalidRoute,
    ErrorPanicRecovery,
    ErrorRequestTimeout,
)

Handler = Callable[[Context], Any]


class HandlerResult:
    __slots__ = ("data", "error")

    def __init__(self, data: Any = None, error: BaseException | None = None) -> None:
        self.data = data
        self.error = error


async def execute_handler(
    handler: Handler,
    ctx: Context,
    timeout: float | None = None,
) -> HandlerResult:
    """Run a handler with timeout + panic isolation (handler.go:55-113)."""

    async def invoke() -> Any:
        result = handler(ctx)
        if isinstance(result, Awaitable):
            result = await result
        return result

    loop = asyncio.get_running_loop()
    if asyncio.iscoroutinefunction(handler):
        task: Any = asyncio.ensure_future(invoke())
    else:
        # Sync handlers run in the default executor so a blocking TPU call
        # (or DB query) never stalls the event loop — the reference's
        # dedicated goroutine per request (handler.go:78-86).
        def call() -> Any:
            return handler(ctx)

        task = loop.run_in_executor(None, call)

    try:
        if timeout is not None and timeout > 0:
            data = await asyncio.wait_for(asyncio.shield(task), timeout)
        else:
            data = await task
        return HandlerResult(data=data)
    except asyncio.TimeoutError:
        ctx.cancel()
        # like the reference, the in-flight worker cannot be force-killed; it
        # is left to finish against a canceled context (handler.go:88-95)
        task.cancel()
        return HandlerResult(error=ErrorRequestTimeout())
    except asyncio.CancelledError:
        raise
    except Exception as exc:
        if _is_user_error(exc):
            return HandlerResult(error=exc)
        ctx.logger.error(
            f"panic recovered: {exc}",
            stack=traceback.format_exc(limit=20),
        )
        return HandlerResult(error=ErrorPanicRecovery())


def _is_user_error(exc: BaseException) -> bool:
    """Typed errors (anything carrying status_code or log_level) are
    deliberate handler returns; bare exceptions are treated as panics
    (handler.go:88-104 maps goroutine panics to 500)."""
    return hasattr(exc, "status_code") or hasattr(exc, "log_level")


def health_handler(ctx: Context) -> Any:
    """/.well-known/health (handler.go:115-117)."""
    return ctx.container.health()


def alive_handler(ctx: Context) -> Any:
    """/.well-known/alive (handler.go:119-123)."""
    return {"status": "UP"}


def catch_all_handler(ctx: Context) -> Any:
    """404 for unregistered routes (handler.go:137-151)."""
    raise ErrorInvalidRoute()

"""The handler Context: request + container + trace-aware logger.

Reference parity: pkg/gofr/context.go:18-38 — Context embeds the stdlib
context (here: the request + cancellation state), the Request, the
*Container, a trace-aware ContextLogger, and ``Out`` terminal access for CMD
apps. ``trace()`` opens user spans (context.go:62-72), ``bind`` binds the
body (context.go:74), ``get_auth_info`` exposes auth claims
(context.go:121-133), ``get_correlation_id`` returns the active trace id
(context.go:181-183). WebSocket write helpers (context.go:81-108) live on the
bound connection.

Datasource access is attribute-style, mirroring ``ctx.SQL`` / ``ctx.Redis``
/ ``ctx.TPU`` in the reference: ``ctx.sql``, ``ctx.redis``, ``ctx.tpu``,
``ctx.serving``, plus ``ctx.get_http_service(name)``.
"""

from __future__ import annotations

from typing import Any

from gofr_tpu.container.container import Container
from gofr_tpu.logging.logger import ContextLogger
from gofr_tpu.tracing.trace import Span, current_span


class AuthInfo:
    """context.go:121-133 / middleware auth results."""

    def __init__(self, method: str = "", username: str = "", api_key: str = "", claims: dict | None = None) -> None:
        self.method = method  # "basic" | "apikey" | "oauth" | ""
        self.username = username
        self.api_key = api_key
        self.claims = claims or {}

    def get_username(self) -> str:
        return self.username

    def get_apikey(self) -> str:
        return self.api_key

    def get_claims(self) -> dict:
        return self.claims


class Context:
    def __init__(
        self,
        request: Any,
        container: Container,
        responder: Any = None,
        out: Any = None,
    ) -> None:
        self.request = request
        self.container = container
        self.responder = responder
        self.out = out  # terminal for CMD apps (cmd/terminal)
        self.auth: AuthInfo = getattr(request, "auth", None) or AuthInfo()
        self.websocket: Any = None  # bound by the WS transport
        span = current_span()
        self.logger = ContextLogger(
            container.logger,
            trace_id=span.trace_id if span else None,
            span_id=span.span_id if span else None,
        )
        self._canceled = False

    # -- request passthroughs -------------------------------------------------
    def param(self, key: str) -> str:
        return self.request.param(key)

    def params(self, key: str) -> list[str]:
        return self.request.params(key)

    def path_param(self, key: str) -> str:
        return self.request.path_param(key)

    def bind(self, target: Any) -> Any:
        return self.request.bind(target)

    def header(self, key: str) -> str:
        getter = getattr(self.request, "header", None)
        return getter(key) if callable(getter) else ""

    # -- container accessors (ctx.SQL etc.) -----------------------------------
    @property
    def config(self) -> Any:
        return self.container.config

    @property
    def metrics(self) -> Any:
        return self.container.metrics_manager

    @property
    def tpu(self) -> Any:
        return self.container.tpu

    @property
    def sql(self) -> Any:
        return self.container.sql

    @property
    def redis(self) -> Any:
        return self.container.redis

    @property
    def kv_store(self) -> Any:
        return self.container.kv_store

    @property
    def file(self) -> Any:
        return self.container.file

    @property
    def cache(self) -> Any:
        return self.container.cache

    @property
    def serving(self) -> Any:
        return self.container.serving

    def get_http_service(self, name: str) -> Any:
        return self.container.get_http_service(name)

    def get_publisher(self) -> Any:
        return self.container.get_publisher()

    def get_subscriber(self) -> Any:
        return self.container.get_subscriber()

    def datasource(self, name: str) -> Any:
        return self.container.extra_datasources.get(name)

    # -- tracing / identity ----------------------------------------------------
    def trace(self, name: str) -> Span:
        """Open a user span as a child of the request span
        (context.go:62-72)."""
        return self.container.tracer.start_span(name)

    def get_correlation_id(self) -> str:
        span = current_span()
        return span.trace_id if span else ""

    def get_auth_info(self) -> AuthInfo:
        return self.auth

    # -- cancellation ----------------------------------------------------------
    def cancel(self) -> None:
        self._canceled = True

    def is_canceled(self) -> bool:
        return self._canceled

    # -- websocket write helpers (context.go:81-108) ---------------------------
    def write_message_to_socket(self, data: Any) -> None:
        if self.websocket is None:
            raise RuntimeError("no websocket bound to this context")
        self.websocket.send(data)

    def write_message_to_service(self, service_name: str, data: Any) -> None:
        manager = self.container.ws_manager
        if manager is None:
            raise RuntimeError("no websocket manager configured")
        manager.write_to_service(service_name, data)

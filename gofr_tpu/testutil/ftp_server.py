"""In-process FTP server for tests (RFC 959 + MLSD/MLST of RFC 3659),
rooted in a local directory with chroot-style containment — the FTP
service-container stand-in (SURVEY §4 tier 4).

Serves the verb subset the driver (and stdlib ftplib) uses: USER/PASS,
TYPE, PWD/CWD, PASV/EPSV passive data connections, RETR/STOR, DELE,
MKD/RMD, RNFR/RNTO, MLSD/MLST, SIZE, NOOP, QUIT.
"""

from __future__ import annotations

import os
import posixpath
import socket
import threading
from typing import Any


class MiniFTPServer:
    def __init__(self, root: str, port: int = 0, user: str = "gofr",
                 password: str = "secret") -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.user, self.password = user, password
        self._running = True
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(8)
        self.port = self._server.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="ftp-server").start()

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=_FTPSession(self, conn).run,
                             daemon=True).start()


class _FTPSession:
    def __init__(self, server: MiniFTPServer, conn: socket.socket) -> None:
        self.server = server
        self.conn = conn
        self.cwd = "/"
        self.authed = False
        self._pending_user = ""
        self._rename_from = ""
        self._data_listener: socket.socket | None = None

    # -- plumbing ------------------------------------------------------------
    def _send(self, code: int, text: str) -> None:
        self.conn.sendall(f"{code} {text}\r\n".encode())

    def _send_multi(self, code: int, lines: list[str], tail: str) -> None:
        out = "".join(f"{code}-{line}\r\n" for line in lines)
        self.conn.sendall(out.encode() + f"{code} {tail}\r\n".encode())

    def _real(self, vpath: str) -> str:
        joined = vpath if vpath.startswith("/") else posixpath.join(self.cwd, vpath)
        norm = posixpath.normpath(joined)
        full = os.path.normpath(os.path.join(self.server.root, norm.lstrip("/")))
        if not (full == self.server.root or full.startswith(self.server.root + os.sep)):
            raise PermissionError(vpath)
        return full

    def _open_data(self) -> socket.socket:
        if self._data_listener is None:
            # surfaces as a 550 protocol error, not a dead session
            raise OSError("use PASV first")
        data, _ = self._data_listener.accept()
        self._data_listener.close()
        self._data_listener = None
        return data

    def _facts(self, path: str, name: str) -> str:
        st = os.lstat(path)
        kind = "dir" if os.path.isdir(path) else "file"
        return f"type={kind};size={st.st_size};modify=20240101000000; {name}"

    # -- session loop --------------------------------------------------------
    def run(self) -> None:
        try:
            self._send(220, "gofr-mini-ftp ready")
            buf = b""
            while self.server._running:
                while b"\r\n" not in buf:
                    chunk = self.conn.recv(4096)
                    if not chunk:
                        return
                    buf += chunk
                line, buf = buf.split(b"\r\n", 1)
                verb, _, arg = line.decode().partition(" ")
                if not self._dispatch(verb.upper(), arg):
                    return
        except (OSError, PermissionError):
            pass
        finally:
            try:
                self.conn.close()
            except OSError:
                pass

    def _dispatch(self, verb: str, arg: str) -> bool:
        try:
            return self._dispatch_inner(verb, arg)
        except PermissionError:
            self._send(550, "path escapes root")
        except FileNotFoundError:
            self._send(550, "no such file or directory")
        except OSError as exc:
            self._send(550, str(exc))
        return True

    def _dispatch_inner(self, verb: str, arg: str) -> bool:
        if verb == "USER":
            self._pending_user = arg
            self._send(331, "password required")
            return True
        if verb == "PASS":
            if (self._pending_user == self.server.user
                    and arg == self.server.password):
                self.authed = True
                self._send(230, "logged in")
            else:
                self._send(530, "login incorrect")
            return True
        if verb == "QUIT":
            self._send(221, "bye")
            return False
        if not self.authed:
            self._send(530, "not logged in")
            return True

        if verb == "TYPE":
            self._send(200, f"type set to {arg}")
        elif verb == "NOOP":
            self._send(200, "ok")
        elif verb == "PWD":
            self._send(257, f'"{self.cwd}"')
        elif verb == "CWD":
            real = self._real(arg)
            if not os.path.isdir(real):
                raise FileNotFoundError(arg)
            joined = arg if arg.startswith("/") else posixpath.join(self.cwd, arg)
            self.cwd = posixpath.normpath(joined)
            self._send(250, "cwd ok")
        elif verb in ("PASV", "EPSV"):
            if self._data_listener is not None:
                # a transfer command that errored before opening its data
                # connection left the old listener behind — reap it
                self._data_listener.close()
                self._data_listener = None
            listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            listener.bind(("127.0.0.1", 0))
            listener.listen(1)
            self._data_listener = listener
            port = listener.getsockname()[1]
            if verb == "PASV":
                p1, p2 = port // 256, port % 256
                self._send(227, f"entering passive mode (127,0,0,1,{p1},{p2})")
            else:
                self._send(229, f"entering extended passive mode (|||{port}|)")
        elif verb == "MLSD":
            real = self._real(arg or self.cwd)
            if not os.path.isdir(real):
                raise FileNotFoundError(arg)
            self._send(150, "here comes the directory listing")
            data = self._open_data()
            try:
                for entry in sorted(os.listdir(real)):
                    data.sendall(
                        (self._facts(os.path.join(real, entry), entry) + "\r\n").encode()
                    )
            finally:
                data.close()
            self._send(226, "directory send ok")
        elif verb == "MLST":
            real = self._real(arg or self.cwd)
            if not os.path.exists(real):
                raise FileNotFoundError(arg)
            self._send_multi(250, [" " + self._facts(real, arg or self.cwd)], "end")
        elif verb == "SIZE":
            real = self._real(arg)
            if not os.path.isfile(real):
                raise FileNotFoundError(arg)
            self._send(213, str(os.path.getsize(real)))
        elif verb == "RETR":
            real = self._real(arg)
            if not os.path.isfile(real):
                raise FileNotFoundError(arg)
            self._send(150, "opening data connection")
            data = self._open_data()
            try:
                with open(real, "rb") as f:
                    while True:
                        chunk = f.read(65536)
                        if not chunk:
                            break
                        data.sendall(chunk)
            finally:
                data.close()
            self._send(226, "transfer complete")
        elif verb == "STOR":
            real = self._real(arg)
            self._send(150, "ok to send data")
            data = self._open_data()
            try:
                with open(real, "wb") as f:
                    while True:
                        chunk = data.recv(65536)
                        if not chunk:
                            break
                        f.write(chunk)
            finally:
                data.close()
            self._send(226, "transfer complete")
        elif verb == "DELE":
            real = self._real(arg)
            if not os.path.isfile(real):
                raise FileNotFoundError(arg)
            os.remove(real)
            self._send(250, "deleted")
        elif verb == "MKD":
            os.mkdir(self._real(arg))
            self._send(257, "created")
        elif verb == "RMD":
            os.rmdir(self._real(arg))
            self._send(250, "removed")
        elif verb == "RNFR":
            self._rename_from = arg
            self._send(350, "ready for RNTO")
        elif verb == "RNTO":
            if not self._rename_from:
                self._send(503, "RNFR first")
            else:
                os.rename(self._real(self._rename_from), self._real(arg))
                self._rename_from = ""
                self._send(250, "renamed")
        else:
            self._send(502, f"command {verb} not implemented")
        return True


def start_ftp_server(root: str, **kw: Any) -> MiniFTPServer:
    return MiniFTPServer(root, **kw)

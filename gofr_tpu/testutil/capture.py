"""stdout/stderr capture for log assertions (testutil/os.go:8-36)."""

from __future__ import annotations

import io
import sys
from typing import Callable


def stdout_output_for_func(fn: Callable[[], None]) -> str:
    buf = io.StringIO()
    old = sys.stdout
    sys.stdout = buf
    try:
        fn()
    finally:
        sys.stdout = old
    return buf.getvalue()


def stderr_output_for_func(fn: Callable[[], None]) -> str:
    buf = io.StringIO()
    old = sys.stderr
    sys.stderr = buf
    try:
        fn()
    finally:
        sys.stderr = old
    return buf.getvalue()

"""In-process PostgreSQL wire server for tests — the CI service-container
stand-in (SURVEY §4 tier 4), like kafka_broker.py / google_pubsub.py.

Speaks protocol v3 (datasource/sql/pg_wire.py): startup with **md5 auth**
(so the driver's real challenge/response path is exercised, not trust),
simple 'Q' queries, and the extended Parse/Bind/Describe/Execute/Sync
flow with text-format parameters. SQL executes on a shared in-memory
sqlite database ($n placeholders rewritten to ?), rows stream back as
RowDescription + DataRows with OIDs inferred from python values, errors
as ErrorResponse with SQLSTATE-ish codes. Per-connection transaction
status rides the ReadyForQuery byte (I/T/E) like a real backend.
"""

from __future__ import annotations

import re
import socket
import sqlite3
import struct
import threading
from typing import Any

from gofr_tpu.datasource.sql import pg_wire as wire

_DOLLAR = re.compile(r"\$(\d+)")


class MiniPostgresServer:
    def __init__(self, port: int = 0, user: str = "gofr", password: str = "secret",
                 database: str = "gofrdb") -> None:
        self.user, self.password, self.database = user, password, database
        # one shared in-memory DB across connections (like a real server)
        self._db = sqlite3.connect(":memory:", check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        self._db.isolation_level = None
        self._db_lock = threading.Lock()
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._running = True
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(8)
        self.port = self._server.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="pg-server").start()

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass

    def kill_connections(self) -> None:
        """Sever every live session (reconnect-after-kill tests)."""
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- connection handling ----------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            if not self._startup(conn):
                return
            self._session(conn)
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _startup(self, conn: socket.socket) -> bool:
        rx = lambda n: wire.recv_exact(conn, n)  # noqa: E731
        (size,) = struct.unpack(">i", rx(4))
        r = wire.Reader(rx(size - 4))
        version = r.int32()
        if version != wire.PROTOCOL_VERSION:
            conn.sendall(wire.encode_error(f"unsupported protocol {version}", "08P01"))
            return False
        params: dict[str, str] = {}
        while r.remaining() > 1:
            key = r.cstr()
            if not key:
                break
            params[key] = r.cstr()
        user = params.get("user", "")

        # md5 challenge/response — the real auth path, not trust
        salt = b"\x01\x02\x03\x04"
        conn.sendall(wire.encode_auth(wire.AUTH_MD5, salt))
        mtype, pr = wire.read_message(rx)
        if mtype != b"p":
            conn.sendall(wire.encode_error("expected password message", "08P01"))
            return False
        expected = wire.md5_password(self.user, self.password, salt)
        if user != self.user or pr.cstr() != expected:
            conn.sendall(wire.encode_error(
                f'password authentication failed for user "{user}"', "28P01"))
            return False
        conn.sendall(
            wire.encode_auth(wire.AUTH_OK)
            + wire.encode_param_status("server_version", "16.0 (gofr-mini)")
            + wire.encode_param_status("client_encoding", "UTF8")
            + wire.msg(wire.BACKEND_KEY, struct.pack(">ii", 1, 1))
            + wire.encode_ready(b"I")
        )
        return True

    # -- query session -----------------------------------------------------
    def _session(self, conn: socket.socket) -> None:
        rx = lambda n: wire.recv_exact(conn, n)  # noqa: E731
        stmts: dict[str, str] = {}
        portals: dict[str, tuple[str, list]] = {}
        txn = b"I"  # I idle, T in transaction, E failed transaction

        def run_sql(sql: str, params: list) -> bytes:
            nonlocal txn
            sqlite_sql = _DOLLAR.sub("?", sql)
            upper = sql.strip().upper()
            try:
                with self._db_lock:
                    cur = self._db.execute(sqlite_sql, params)
                    rows = cur.fetchall() if cur.description else []
            except sqlite3.Error as exc:
                if txn == b"T":
                    txn = b"E"  # statement failed: transaction is poisoned
                return wire.encode_error(str(exc), "42601")
            out = b""
            if cur.description:
                names = [d[0] for d in cur.description]

                def col_oid(i: int) -> int:
                    # first NON-NULL value decides the column type — a NULL
                    # in row 1 must not stringify the whole column
                    for row in rows:
                        if row[i] is not None:
                            return wire.oid_for_python(row[i])
                    return wire.OID_TEXT

                cols = [(name, col_oid(i)) for i, name in enumerate(names)]
                out += wire.encode_row_description(cols)
                for row in rows:
                    out += wire.encode_data_row(list(row))
                tag = f"SELECT {len(rows)}"
            else:
                verb = upper.split()[0] if upper.split() else "OK"
                n = cur.rowcount if cur.rowcount >= 0 else 0
                tag = f"INSERT 0 {n}" if verb == "INSERT" else f"{verb} {n}"
            if upper.startswith("BEGIN"):
                txn, tag = b"T", "BEGIN"
            elif upper.startswith("COMMIT"):
                txn, tag = b"I", "COMMIT"
            elif upper.startswith("ROLLBACK"):
                txn, tag = b"I", "ROLLBACK"
            return out + wire.encode_command_complete(tag)

        while self._running:
            mtype, r = wire.read_message(rx)
            if mtype == b"Q":  # simple query
                conn.sendall(run_sql(r.cstr(), []) + wire.encode_ready(txn))
            elif mtype == b"P":  # Parse
                name, query = r.cstr(), r.cstr()
                stmts[name] = query
                conn.sendall(wire.msg(wire.PARSE_COMPLETE))
            elif mtype == b"B":  # Bind
                portal, stmt = r.cstr(), r.cstr()
                for _ in range(r.int16()):
                    r.int16()  # param format codes
                params: list[Any] = []
                for _ in range(r.int16()):
                    size = r.int32()
                    params.append(None if size < 0 else r.take(size).decode())
                for _ in range(r.int16()):
                    r.int16()  # result format codes
                if stmt not in stmts:
                    conn.sendall(wire.encode_error(f"unknown statement {stmt!r}", "26000"))
                else:
                    portals[portal] = (stmts[stmt], params)
                    conn.sendall(wire.msg(wire.BIND_COMPLETE))
            elif mtype == b"D":  # Describe — row shape resolved at Execute
                r.take(1), r.cstr()
                conn.sendall(wire.msg(wire.NO_DATA))
            elif mtype == b"E":  # Execute
                portal = r.cstr()
                r.int32()  # max rows
                if portal not in portals:
                    conn.sendall(wire.encode_error(f"unknown portal {portal!r}", "34000"))
                else:
                    sql, params = portals[portal]
                    conn.sendall(run_sql(sql, params))
            elif mtype == b"S":  # Sync
                conn.sendall(wire.encode_ready(txn))
            elif mtype == b"C":  # Close
                r.take(1), r.cstr()
                conn.sendall(wire.msg(wire.CLOSE_COMPLETE))
            elif mtype == b"X":  # Terminate
                return
            else:
                conn.sendall(
                    wire.encode_error(f"unsupported message {mtype!r}", "0A000")
                    + wire.encode_ready(txn)
                )

    # -- test inspection ---------------------------------------------------
    def execute(self, sql: str, *args: Any) -> list[tuple]:
        with self._db_lock:
            cur = self._db.execute(sql, args)
            return [tuple(r) for r in cur.fetchall()] if cur.description else []


def start_postgres_server(**kw: Any) -> MiniPostgresServer:
    return MiniPostgresServer(**kw)

"""In-process Cassandra server: CQL binary protocol v4 over TCP, storage
via the embedded wide-column store.

Pairs with datasource/widecolumn/cassandra.py the way MiniMySQLServer
pairs with the MySQL dialect. STARTUP→READY, QUERY→RESULT (typed rows /
void), BATCH→RESULT with logged-batch atomicity, CAS statements (IF
NOT EXISTS / UPDATE ... IF) answered with the ``[applied]`` row shape
real servers use; errors come back as ERROR frames with CQL error codes.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any

from gofr_tpu.datasource.widecolumn import EmbeddedWideColumnStore
from gofr_tpu.datasource.widecolumn import cql_wire as wire
from gofr_tpu.testutil.ports import get_free_port


def _is_cas(stmt: str) -> bool:
    upper = stmt.upper()
    head = upper.lstrip()
    return ("IF NOT EXISTS" in upper and head.startswith("INSERT")) or (
        head.startswith("UPDATE") and " IF " in upper
    )


class _Conn:
    def __init__(self, server: "MiniCassandraServer", sock: socket.socket) -> None:
        self.server = server
        self.sock = sock
        self.rbuf = b""

    def _recv_exact(self, n: int) -> bytes | None:
        while len(self.rbuf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError:
                return None
            if not chunk:
                return None
            self.rbuf += chunk
        out, self.rbuf = self.rbuf[:n], self.rbuf[n:]
        return out

    def serve(self) -> None:
        try:
            while True:
                head = self._recv_exact(9)
                if head is None:
                    return
                _, stream, opcode, length = wire.parse_frame_header(head)
                body = self._recv_exact(length) if length else b""
                if body is None:
                    return
                try:
                    reply_op, reply_body = self.handle(opcode, body)
                except wire.CQLError as exc:
                    reply_op = wire.OP_ERROR
                    reply_body = wire.encode_error(exc.code, str(exc))
                except Exception as exc:  # noqa: BLE001 - surfaces on the wire
                    reply_op = wire.OP_ERROR
                    reply_body = wire.encode_error(0x0000, str(exc))
                self.sock.sendall(
                    wire.encode_frame(stream, reply_op, reply_body,
                                      response=True)
                )
        finally:
            self.sock.close()

    def handle(self, opcode: int, body: bytes) -> tuple[int, bytes]:
        if opcode == wire.OP_STARTUP:
            return wire.OP_READY, b""
        if opcode == wire.OP_OPTIONS:
            return wire.OP_SUPPORTED, wire.string_map({})
        if opcode == wire.OP_QUERY:
            query, pos = wire.read_long_string(body, 0)
            return wire.OP_RESULT, self._run_query(query)
        if opcode == wire.OP_BATCH:
            _, queries = wire.decode_batch(body)
            return wire.OP_RESULT, self._run_batch(queries)
        raise wire.CQLError(0x000A, f"unsupported opcode 0x{opcode:02x}")

    def _run_query(self, query: str) -> bytes:
        store = self.server.store
        head = query.strip().upper()
        if head.startswith("USE "):
            ks = query.strip()[4:].strip().strip('"')
            return struct.pack(">i", wire.RESULT_SET_KEYSPACE) + wire.string(ks)
        if _is_cas(query):
            prev: list[dict] = []
            applied = store.exec_cas(prev, query)
            rows = [{"[applied]": applied, **(prev[0] if prev else {})}]
            if not applied and not prev:
                rows = [{"[applied]": False}]
            return wire.encode_rows(rows)
        if head.startswith("SELECT"):
            if "SYSTEM.LOCAL" in head:  # canonical health probe
                return wire.encode_rows([{"release_version": "4.0-mini"}])
            rows: list[dict] = []
            store.query(rows, query)
            return wire.encode_rows(rows)
        store.exec(query)
        return struct.pack(">i", wire.RESULT_VOID)

    def _run_batch(self, queries: list[str]) -> bytes:
        store = self.server.store
        name = f"__wire_batch_{id(self)}_{threading.get_ident()}"
        if any(_is_cas(q) for q in queries):
            # CAS batch: Cassandra applies all-or-nothing; emulate by
            # checking each CAS first, then running the batch atomically
            probe: list[dict] = []
            for q in queries:
                if _is_cas(q) and not store.exec_cas(probe, q):
                    return wire.encode_rows([{"[applied]": False}])
            non_cas = [q for q in queries if not _is_cas(q)]
            if non_cas:
                store.new_batch(name)
                for q in non_cas:
                    store.batch_query(name, q)
                store.execute_batch(name)
            return wire.encode_rows([{"[applied]": True}])
        store.new_batch(name)
        for q in queries:
            store.batch_query(name, q)
        store.execute_batch(name)
        return struct.pack(">i", wire.RESULT_VOID)


class MiniCassandraServer:
    def __init__(self, port: int = 0) -> None:
        self.port = port or get_free_port()
        self.store = EmbeddedWideColumnStore(":memory:")
        self._listener: socket.socket | None = None
        self._closed = False

    def start(self) -> "MiniCassandraServer":
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", self.port))
        self._listener.listen(16)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=_Conn(self, sock).serve, daemon=True
            ).start()

    def close(self) -> None:
        self._closed = True
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        self.store.close()

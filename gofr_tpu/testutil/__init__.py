"""Test utilities.

Reference parity: pkg/gofr/testutil/ — free-port allocation (port.go:14-27),
server-config env setup (port.go:50-70), stdout/stderr capture
(os.go:8-36). Plus the mock container (container/mock_container.go:96) — the
central fake backend for handler tests.
"""

from gofr_tpu.testutil.ports import get_free_port, new_server_configs
from gofr_tpu.testutil.capture import stdout_output_for_func, stderr_output_for_func
from gofr_tpu.testutil.mock_container import MockContainer, new_mock_container
from gofr_tpu.testutil.replica import StubReplicaEngine, StubResult

__all__ = [
    "get_free_port",
    "new_server_configs",
    "stdout_output_for_func",
    "stderr_output_for_func",
    "MockContainer",
    "new_mock_container",
    "StubReplicaEngine",
    "StubResult",
]

"""In-process MQTT 3.1.1 mini-broker for tests and local dev.

Plays the role of the reference's CI service containers (kafka/redis in
.github/workflows/go.yml:38-77 — SURVEY §4 tier 4): a real TCP endpoint
speaking the real protocol, so the driver's wire codec, QoS-1 ack flow,
keepalive, and reconnect logic are tested end-to-end without docker.

Supported: CONNECT/CONNACK, SUBSCRIBE/SUBACK with +/# wildcard filters,
PUBLISH QoS 0/1 (PUBACK to the publisher; QoS-1 delivery redelivers with
DUP on reconnect until the subscriber PUBACKs), UNSUBSCRIBE, PINGREQ,
DISCONNECT.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any

from gofr_tpu.datasource.pubsub.mqtt import (
    CONNACK,
    CONNECT,
    DISCONNECT,
    PINGREQ,
    PINGRESP,
    PUBACK,
    PUBLISH,
    SUBACK,
    SUBSCRIBE,
    UNSUBACK,
    UNSUBSCRIBE,
    MQTTError,
    encode_string,
    packet,
    parse_publish,
    publish_packet,
    read_packet,
    topic_matches,
)


class _Session:
    """Per-client-id state that survives reconnects (clean_session=0)."""

    def __init__(self, client_id: str) -> None:
        self.client_id = client_id
        self.subscriptions: dict[str, int] = {}  # filter -> qos
        self.unacked: dict[int, tuple[str, bytes]] = {}  # pid -> (topic, payload)
        self.conn: socket.socket | None = None
        self.lock = threading.Lock()

    def send(self, data: bytes) -> None:
        with self.lock:
            if self.conn is not None:
                try:
                    self.conn.sendall(data)
                except OSError:
                    self.conn = None


class MiniMQTTBroker:
    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()
        self._sessions: dict[str, _Session] = {}
        self._mu = threading.Lock()
        self._next_pid = 0
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="mqtt-broker-accept"
        )

    def start(self) -> "MiniMQTTBroker":
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._mu:
            for sess in self._sessions.values():
                with sess.lock:
                    if sess.conn is not None:
                        try:
                            sess.conn.close()
                        except OSError:
                            pass

    # ------------------------------------------------------------- internals
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="mqtt-broker-conn",
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        sess: _Session | None = None
        try:
            ptype, _, body = read_packet(conn)
            if ptype != CONNECT:
                conn.close()
                return
            # body: proto name(6) + level(1) + flags(1) + keepalive(2) + client id
            idx = 2 + struct.unpack(">H", body[:2])[0] + 1 + 1 + 2
            cid_len = struct.unpack(">H", body[idx:idx + 2])[0]
            client_id = body[idx + 2: idx + 2 + cid_len].decode()
            with self._mu:
                sess = self._sessions.setdefault(client_id, _Session(client_id))
            session_present = bool(sess.subscriptions)
            with sess.lock:
                sess.conn = conn
            conn.sendall(packet(CONNACK, 0, bytes([1 if session_present else 0, 0])))
            # QoS-1 redelivery with DUP (MQTT-4.4)
            for pid, (topic, payload) in list(sess.unacked.items()):
                sess.send(publish_packet(topic, payload, 1, pid, dup=True))

            while not self._closed:
                ptype, flags, body = read_packet(conn)
                if ptype == PUBLISH:
                    self._handle_publish(sess, flags, body)
                elif ptype == SUBSCRIBE:
                    pid = struct.unpack(">H", body[:2])[0]
                    rest, granted = body[2:], []
                    while rest:
                        tlen = struct.unpack(">H", rest[:2])[0]
                        topic = rest[2:2 + tlen].decode()
                        qos = rest[2 + tlen]
                        sess.subscriptions[topic] = qos
                        granted.append(qos)
                        rest = rest[3 + tlen:]
                    sess.send(packet(SUBACK, 0, struct.pack(">H", pid) + bytes(granted)))
                elif ptype == UNSUBSCRIBE:
                    pid = struct.unpack(">H", body[:2])[0]
                    rest = body[2:]
                    while rest:
                        tlen = struct.unpack(">H", rest[:2])[0]
                        sess.subscriptions.pop(rest[2:2 + tlen].decode(), None)
                        rest = rest[2 + tlen:]
                    sess.send(packet(UNSUBACK, 0, struct.pack(">H", pid)))
                elif ptype == PUBACK:
                    pid = struct.unpack(">H", body[:2])[0]
                    sess.unacked.pop(pid, None)
                elif ptype == PINGREQ:
                    sess.send(packet(PINGRESP, 0, b""))
                elif ptype == DISCONNECT:
                    break
        except (MQTTError, OSError):
            pass
        finally:
            if sess is not None:
                with sess.lock:
                    if sess.conn is conn:
                        sess.conn = None
            try:
                conn.close()
            except OSError:
                pass

    def _handle_publish(self, publisher: _Session, flags: int, body: bytes) -> None:
        topic, payload, qos, pid = parse_publish(flags, body)
        if qos > 0:
            publisher.send(packet(PUBACK, 0, struct.pack(">H", pid)))
        with self._mu:
            sessions = list(self._sessions.values())
        for sess in sessions:
            for pattern, sub_qos in sess.subscriptions.items():
                if topic_matches(pattern, topic):
                    out_qos = min(qos, sub_qos)
                    if out_qos > 0:
                        with self._mu:
                            self._next_pid = (self._next_pid % 0xFFFF) + 1
                            out_pid = self._next_pid
                        sess.unacked[out_pid] = (topic, payload)
                        sess.send(publish_packet(topic, payload, 1, out_pid))
                    else:
                        sess.send(publish_packet(topic, payload, 0, 0))
                    break  # one delivery per session


__all__ = ["MiniMQTTBroker"]

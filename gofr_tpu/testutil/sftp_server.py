"""In-process SFTP server for tests — the sshd stand-in (SURVEY §4
tier 4), like kafka_broker.py / postgres_server.py.

A real SSH 2.0 endpoint on the shared transport (curve25519 kex,
ed25519 host key generated per server, aes128-ctr + hmac-sha2-256,
password auth) serving SFTP v3 over a local root directory with
chroot-style path containment. The client and server derive their
session keys independently from the exchange hash, so the handshake is
genuine cryptographic interop, not shared state.
"""

from __future__ import annotations

import os
import posixpath
import socket
import stat as stat_mod
import struct
import threading
from typing import Any

try:
    from cryptography.hazmat.primitives.asymmetric.ed25519 import Ed25519PrivateKey
except ImportError:  # guarded in MiniSFTPServer.__init__
    Ed25519PrivateKey = None  # type: ignore[assignment]

from gofr_tpu.datasource.file import sftp as fx
from gofr_tpu.datasource.file.ssh_transport import (
    Reader,
    SSHError,
    SSHServerSession,
    SSHTransport,
    sstr,
    u32,
)


class MiniSFTPServer:
    def __init__(self, root: str, port: int = 0, user: str = "gofr",
                 password: str = "secret") -> None:
        if Ed25519PrivateKey is None:
            raise RuntimeError(
                "MiniSFTPServer needs the cryptography package "
                "(ed25519 host key)"
            )
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.user, self.password = user, password
        self.host_key = Ed25519PrivateKey.generate()
        self._running = True
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(8)
        self.port = self._server.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="sftp-server").start()

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            transport = SSHTransport(conn, server_side=True, host_key=self.host_key)
            transport.handshake()
            session = SSHServerSession(
                transport,
                lambda u, p: u == self.user and p == self.password,
            )
            session.accept()
            _SFTPSubsystem(self.root, transport).run()
        except (SSHError, ConnectionError, OSError, struct.error):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass


class _SFTPSubsystem:
    """SFTP v3 request loop over one channel, rooted at ``root``."""

    def __init__(self, root: str, transport: SSHTransport) -> None:
        self.root = root
        self.stream = fx._PacketStream(transport)
        self._handles: dict[bytes, Any] = {}
        self._dirs: dict[bytes, list] = {}
        self._hcount = 0

    # -- path containment ---------------------------------------------------
    def _real(self, vpath: str) -> str:
        norm = posixpath.normpath("/" + vpath.replace("\\", "/"))
        full = os.path.normpath(os.path.join(self.root, norm.lstrip("/")))
        if not (full == self.root or full.startswith(self.root + os.sep)):
            raise PermissionError(f"path escapes root: {vpath}")
        return full

    def _virtual(self, vpath: str) -> str:
        norm = posixpath.normpath("/" + vpath.replace("\\", "/"))
        # POSIX normpath preserves exactly two leading slashes
        return "/" + norm.lstrip("/") if norm != "/" else "/"

    # -- responses ----------------------------------------------------------
    def _status(self, rid: int, code: int, message: str = "") -> None:
        self.stream.write_packet(
            fx.FXP_STATUS, u32(rid) + u32(code) + sstr(message.encode()) + sstr(b"en")
        )

    def _attrs_bytes(self, st: os.stat_result) -> bytes:
        return (
            u32(fx.ATTR_SIZE | fx.ATTR_PERMISSIONS | fx.ATTR_ACMODTIME)
            + struct.pack(">Q", st.st_size)
            + u32(st.st_mode)
            + u32(int(st.st_atime)) + u32(int(st.st_mtime))
        )

    def _new_handle(self, obj: Any) -> bytes:
        self._hcount += 1
        h = f"h{self._hcount}".encode()
        self._handles[h] = obj
        return h

    # -- the loop -----------------------------------------------------------
    def run(self) -> None:
        ptype, r = self.stream.read_packet()
        if ptype != fx.FXP_INIT:
            raise SSHError("expected FXP_INIT")
        self.stream.write_packet(fx.FXP_VERSION, u32(3))
        while True:
            ptype, r = self.stream.read_packet()
            rid = r.uint32()
            try:
                self._dispatch(ptype, rid, r)
            except FileNotFoundError as exc:
                self._status(rid, fx.FX_NO_SUCH_FILE, str(exc))
            except PermissionError as exc:
                self._status(rid, fx.FX_PERMISSION_DENIED, str(exc))
            except (OSError, ValueError) as exc:
                self._status(rid, fx.FX_FAILURE, str(exc))

    def _dispatch(self, ptype: int, rid: int, r: Reader) -> None:
        if ptype == fx.FXP_OPEN:
            path = self._real(r.string().decode())
            pflags = r.uint32()
            fx.decode_attrs(r)
            if pflags & fx.FXF_CREAT and not os.path.exists(path):
                open(path, "wb").close()
            if pflags & fx.FXF_TRUNC:
                open(path, "wb").close()
            f = open(path, "r+b" if pflags & fx.FXF_WRITE else "rb")
            h = self._new_handle(f)
            self.stream.write_packet(fx.FXP_HANDLE, u32(rid) + sstr(h))
        elif ptype == fx.FXP_CLOSE:
            h = r.string()
            obj = self._handles.pop(h, None)
            self._dirs.pop(h, None)
            if hasattr(obj, "close"):
                obj.close()
            self._status(rid, fx.FX_OK)
        elif ptype == fx.FXP_READ:
            h, offset, length = r.string(), r.uint64(), r.uint32()
            f = self._handles[h]
            f.seek(offset)
            data = f.read(min(length, 1 << 20))
            if not data:
                self._status(rid, fx.FX_EOF, "eof")
            else:
                self.stream.write_packet(fx.FXP_DATA, u32(rid) + sstr(data))
        elif ptype == fx.FXP_WRITE:
            h, offset, data = r.string(), r.uint64(), r.string()
            f = self._handles[h]
            f.seek(offset)
            f.write(data)
            f.flush()
            self._status(rid, fx.FX_OK)
        elif ptype in (fx.FXP_STAT, fx.FXP_LSTAT):
            statter = os.stat if ptype == fx.FXP_STAT else os.lstat
            st = statter(self._real(r.string().decode()))
            self.stream.write_packet(fx.FXP_ATTRS, u32(rid) + self._attrs_bytes(st))
        elif ptype == fx.FXP_REALPATH:
            v = self._virtual(r.string().decode())
            self.stream.write_packet(
                fx.FXP_NAME,
                u32(rid) + u32(1) + sstr(v.encode()) + sstr(v.encode()) + u32(0),
            )
        elif ptype == fx.FXP_OPENDIR:
            path = self._real(r.string().decode())
            if not os.path.isdir(path):
                raise FileNotFoundError(path)
            entries = sorted(os.listdir(path))
            h = self._new_handle(None)
            # lstat: dangling symlinks must list, not fail the directory
            self._dirs[h] = [(e, os.lstat(os.path.join(path, e))) for e in entries]
            self.stream.write_packet(fx.FXP_HANDLE, u32(rid) + sstr(h))
        elif ptype == fx.FXP_READDIR:
            h = r.string()
            entries = self._dirs.get(h)
            if entries is None:
                raise ValueError("bad directory handle")
            if not entries:
                self._status(rid, fx.FX_EOF, "eof")
                return
            batch, self._dirs[h] = entries[:64], entries[64:]
            body = u32(rid) + u32(len(batch))
            for name, st in batch:
                body += sstr(name.encode()) + sstr(name.encode())
                body += self._attrs_bytes(st)
            self.stream.write_packet(fx.FXP_NAME, body)
        elif ptype == fx.FXP_REMOVE:
            path = self._real(r.string().decode())
            # a symlink is removable even when it points at a directory
            if os.path.isdir(path) and not os.path.islink(path):
                raise OSError("is a directory")
            os.remove(path)
            self._status(rid, fx.FX_OK)
        elif ptype == fx.FXP_MKDIR:
            path = self._real(r.string().decode())
            if os.path.exists(path):
                raise OSError(f"already exists: {path}")
            os.mkdir(path)
            self._status(rid, fx.FX_OK)
        elif ptype == fx.FXP_RMDIR:
            os.rmdir(self._real(r.string().decode()))
            self._status(rid, fx.FX_OK)
        elif ptype == fx.FXP_RENAME:
            old = self._real(r.string().decode())
            new = self._real(r.string().decode())
            os.rename(old, new)
            self._status(rid, fx.FX_OK)
        elif ptype == fx.FXP_SETSTAT:
            self._real(r.string().decode())
            fx.decode_attrs(r)
            self._status(rid, fx.FX_OK)
        else:
            self._status(rid, fx.FX_OP_UNSUPPORTED, f"unsupported op {ptype}")


def start_sftp_server(root: str, **kw: Any) -> MiniSFTPServer:
    return MiniSFTPServer(root, **kw)

"""StubReplicaEngine: an in-process engine replica double for router
tests (tests/test_router.py, tests/test_router_chaos.py).

Implements the ``ServingEngine`` submit surface — ``submit(prompt,
deadline=, stream_cb=) -> Future``, ``cancel``, ``drain``,
``health_check`` — with a worker thread per request emitting tokens at a
fixed cadence, plus the fault controls the chaos tier drives:

- ``kill()``: the replica process dies — in-flight requests fail with
  the PR 5 warm-restart contract (503 retriable + Retry-After), new
  submits are refused retriable;
- ``wedge()``: the engine stops making progress; after
  ``supervisor_detect_s`` the (simulated) supervisor fails in-flight
  requests retriable and parks the replica WEDGED;
- ``drain()``: in-flight streams run to completion, new submits are
  refused retriable (the DRAINING contract);
- ``revive()``: back to UP (heartbeat-partition scenarios, where the
  replica was never actually unhealthy).

Every request's terminal transition is recorded in ``terminals`` and a
double settlement (the invariant violation the router chaos suite hunts)
is captured in ``double_terminals`` instead of racing an assert inside a
worker thread.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable

from gofr_tpu.http.errors import ErrorServiceUnavailable

UP = "UP"
DRAINING = "DRAINING"
WEDGED = "WEDGED"
DOWN = "DOWN"


@dataclasses.dataclass
class StubResult:
    """GenerationResult-shaped terminal payload."""

    request_id: int
    text: str
    token_ids: list[int]
    prompt_tokens: int
    completion_tokens: int
    finish_reason: str
    ttft_s: float
    duration_s: float


class _StubRequest:
    def __init__(self, rid: int, prompt: Any, max_new: int,
                 deadline_abs: float | None, stream_cb: Any) -> None:
        self.rid = rid
        self.prompt = prompt
        self.max_new = max_new
        self.deadline_abs = deadline_abs
        self.stream_cb = stream_cb
        self.future: Any = Future()
        self.future.request_id = rid
        self.canceled = threading.Event()
        self.tokens: list[int] = []


class StubReplicaEngine:
    def __init__(
        self,
        replica_id: str = "replica",
        *,
        tokens: int = 4,
        token_interval_s: float = 0.002,
        first_token_delay_s: float = 0.0,
        supervisor_detect_s: float = 0.05,
    ) -> None:
        self.replica_id = replica_id
        self.tokens = tokens
        self.token_interval_s = token_interval_s
        self.first_token_delay_s = first_token_delay_s
        self.supervisor_detect_s = supervisor_detect_s
        self.state = UP
        self._mu = threading.Lock()
        self._next_rid = 0
        self._live: dict[int, _StubRequest] = {}
        self._killed = threading.Event()
        self._wedged = threading.Event()
        # test-visible bookkeeping
        self.submissions: list[dict[str, Any]] = []
        self.terminals: dict[int, str] = {}
        self.double_terminals: list[int] = []
        self.cancels: list[int] = []
        # knobs tests poke to shape heartbeats (spill / least-wait tests)
        self.report_queue_wait_s = 0.0
        self.report_queue_depth = 0
        self.slots_total = 4

    # -- engine surface --------------------------------------------------------
    def submit(self, prompt: Any, *, max_new_tokens: int | None = None,
               deadline: float | None = None,
               stream_cb: Callable[[int, str, bool], None] | None = None,
               **kw: Any) -> Any:
        with self._mu:
            if self.state in (DOWN, WEDGED):
                raise ErrorServiceUnavailable(
                    f"replica {self.replica_id} {self.state.lower()}; retry "
                    "on another replica", retry_after=0.1,
                )
            if self.state == DRAINING:
                raise ErrorServiceUnavailable(
                    f"replica {self.replica_id} draining; retry on another "
                    "replica", retry_after=1.0,
                )
            self._next_rid += 1
            rid = self._next_rid
            deadline_abs = (
                time.monotonic() + deadline
                if deadline is not None and deadline > 0 else None
            )
            req = _StubRequest(
                rid, prompt, max_new_tokens or self.tokens, deadline_abs,
                stream_cb,
            )
            self._live[rid] = req
            self.submissions.append({
                "rid": rid, "prompt": prompt, "deadline": deadline,
                "t": time.monotonic(),
            })
        worker = threading.Thread(
            target=self._run, args=(req,), daemon=True,
            name=f"stub-{self.replica_id}-{rid}",
        )
        worker.start()
        return req.future

    def cancel(self, request_id: int) -> None:
        with self._mu:
            req = self._live.get(request_id)
            self.cancels.append(request_id)
        if req is not None:
            req.canceled.set()

    def drain(self, deadline_s: float | None = None) -> None:
        with self._mu:
            if self.state == UP:
                self.state = DRAINING

    def health_check(self) -> dict[str, Any]:
        with self._mu:
            live = len(self._live)
            depth = self.report_queue_depth
            wait = self.report_queue_wait_s
        # the announcer computes queue_wait = depth/slots × ewma; report
        # depth == slots so the hint passes through unchanged
        return {
            "status": self.state,
            "details": {
                "slots_total": self.slots_total,
                "slots_active": min(live, self.slots_total),
                "queue_depth": depth if depth else (self.slots_total if wait else 0),
                "shed": {"ewma_request_s": wait, "ewma_ttft_s": 0.0},
                "kv_pages": {"free_blocks": 64, "total_blocks": 64},
            },
        }

    # -- fault controls --------------------------------------------------------
    def kill(self) -> None:
        """Abrupt death: in-flight requests fail retriable NOW (the
        warm-restart 503 contract), new submits are refused."""
        with self._mu:
            self.state = DOWN
        self._killed.set()

    def wedge(self) -> None:
        """Progress stops; after ``supervisor_detect_s`` the simulated
        supervisor fails in-flight retriable and parks the replica."""
        with self._mu:
            self.state = WEDGED
        timer = threading.Timer(self.supervisor_detect_s, self._wedged.set)
        timer.daemon = True
        timer.start()

    def revive(self) -> None:
        with self._mu:
            self.state = UP
        self._killed.clear()
        self._wedged.clear()

    # -- worker ----------------------------------------------------------------
    def _record_terminal(self, req: _StubRequest, reason: str) -> bool:
        with self._mu:
            self._live.pop(req.rid, None)
            if req.rid in self.terminals:
                self.double_terminals.append(req.rid)
                return False
            self.terminals[req.rid] = reason
            return True

    def _settle_result(self, req: _StubRequest, reason: str,
                       started: float) -> None:
        if not self._record_terminal(req, reason):
            return
        if req.stream_cb is not None:
            req.stream_cb(0, "", True)
        req.future.set_result(StubResult(
            request_id=req.rid,
            text="tok" * len(req.tokens),
            token_ids=list(req.tokens),
            prompt_tokens=len(str(req.prompt)),
            completion_tokens=len(req.tokens),
            finish_reason=reason,
            ttft_s=self.first_token_delay_s,
            duration_s=time.monotonic() - started,
        ))

    def _settle_error(self, req: _StubRequest, exc: Exception,
                      reason: str) -> None:
        if not self._record_terminal(req, reason):
            return
        # mirror ServingEngine._settle_future's contract: the future
        # fails FIRST, the stream's terminal frame fires after — the
        # router must not let that trailing done-frame claim the stream
        # for a dead attempt (it would cancel the failover re-route)
        req.future.set_exception(exc)
        if req.stream_cb is not None:
            req.stream_cb(-1, "", True)

    def _run(self, req: _StubRequest) -> None:
        started = time.monotonic()
        if self.first_token_delay_s:
            self._interruptible_wait(req, self.first_token_delay_s)
        emitted = 0
        while True:
            if self._killed.is_set():
                self._settle_error(req, ErrorServiceUnavailable(
                    f"replica {self.replica_id} restarting; retry",
                    retry_after=0.1,
                ), "failed_retriable")
                return
            if self._wedged.is_set():
                self._settle_error(req, ErrorServiceUnavailable(
                    f"replica {self.replica_id} wedged; retry on another "
                    "replica", retry_after=1.0,
                ), "failed_retriable")
                return
            if req.canceled.is_set():
                self._settle_result(req, "cancel", started)
                return
            if (req.deadline_abs is not None
                    and time.monotonic() > req.deadline_abs):
                self._settle_result(req, "deadline_exceeded", started)
                return
            if self.state == WEDGED:
                # wedged but not yet detected: no progress, just wait
                self._interruptible_wait(req, self.token_interval_s)
                continue
            if emitted >= req.max_new:
                self._settle_result(req, "length", started)
                return
            token_id = 100 + emitted
            req.tokens.append(token_id)
            emitted += 1
            if req.stream_cb is not None:
                req.stream_cb(token_id, "tok", False)
            self._interruptible_wait(req, self.token_interval_s)

    def _interruptible_wait(self, req: _StubRequest, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            if (req.canceled.is_set() or self._killed.is_set()
                    or self._wedged.is_set()):
                return
            remaining = deadline - time.monotonic()
            req.canceled.wait(min(0.005, max(remaining, 0.0)))

"""In-process google.pubsub.v1 fake — the Pub/Sub emulator analogue.

Serves the wire subset in datasource/pubsub/protos/pubsub_v1.proto over a
real sync gRPC server (generic handlers + the same descriptor-set message
classes the driver uses): topic CRUD, per-subscription cursors,
**ack-deadline redelivery** (an unacked message returns to the pool when
its deadline lapses; ModifyAckDeadline(0) nacks immediately), Pull
long-polling. Stands in for the reference CI's service containers
(SURVEY §4 tier 4) like testutil/kafka_broker.py does for Kafka.
"""

from __future__ import annotations

import itertools
import threading
import time
from concurrent import futures
from typing import Any

import grpc

from gofr_tpu.datasource.pubsub.google import MESSAGES, PUBSUB_FDS, _P


class _Subscription:
    def __init__(self, name: str, topic: str, ack_deadline_s: int) -> None:
        self.name = name
        self.topic = topic
        self.ack_deadline_s = max(0, ack_deadline_s) or 10
        self.cursor = 0  # next topic-log index to deliver fresh
        self.outstanding: dict[str, tuple[int, float]] = {}  # ack_id → (idx, deadline)
        self.redeliver: list[int] = []  # nacked/expired indexes, FIFO
        self.acked: set[int] = set()


class GooglePubSubServer:
    def __init__(self, port: int = 0) -> None:
        self._topics: dict[str, list[Any]] = {}  # path → [PubsubMessage]
        self._subs: dict[str, _Subscription] = {}
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._data = threading.Condition(self._lock)

        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((self._handlers(),))
        self.port = self._server.add_insecure_port(f"127.0.0.1:{port}")
        self._server.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def close(self) -> None:
        self._server.stop(grace=0.2)

    # -- wiring ------------------------------------------------------------
    def _handlers(self) -> grpc.GenericRpcHandler:
        def unary(fn, in_type: str, out_type: str):
            in_cls = MESSAGES[f"{_P}.{in_type}"]
            return grpc.unary_unary_rpc_method_handler(
                fn,
                request_deserializer=in_cls.FromString,
                response_serializer=lambda m: m.SerializeToString(),
            )

        publisher = grpc.method_handlers_generic_handler(
            f"{_P}.Publisher",
            {
                "CreateTopic": unary(self._create_topic, "Topic", "Topic"),
                "DeleteTopic": unary(self._delete_topic, "DeleteTopicRequest", "Empty"),
                "ListTopics": unary(self._list_topics, "ListTopicsRequest", "ListTopicsResponse"),
                "Publish": unary(self._publish, "PublishRequest", "PublishResponse"),
            },
        )
        subscriber = grpc.method_handlers_generic_handler(
            f"{_P}.Subscriber",
            {
                "CreateSubscription": unary(self._create_sub, "Subscription", "Subscription"),
                "DeleteSubscription": unary(self._delete_sub, "DeleteSubscriptionRequest", "Empty"),
                "Pull": unary(self._pull, "PullRequest", "PullResponse"),
                "Acknowledge": unary(self._ack, "AcknowledgeRequest", "Empty"),
                "ModifyAckDeadline": unary(self._modify, "ModifyAckDeadlineRequest", "Empty"),
            },
        )

        class Both(grpc.GenericRpcHandler):
            def service(self, details):
                return publisher.service(details) or subscriber.service(details)

        return Both()

    # -- Publisher ---------------------------------------------------------
    def _create_topic(self, request: Any, context: Any) -> Any:
        with self._lock:
            if request.name in self._topics:
                context.abort(grpc.StatusCode.ALREADY_EXISTS, "topic exists")
            self._topics[request.name] = []
        return request

    def _delete_topic(self, request: Any, context: Any) -> Any:
        with self._lock:
            if self._topics.pop(request.topic, None) is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
        return MESSAGES[f"{_P}.Empty"]()

    def _list_topics(self, request: Any, context: Any) -> Any:
        resp = MESSAGES[f"{_P}.ListTopicsResponse"]()
        with self._lock:
            for name in sorted(self._topics):
                if not request.project or name.startswith(request.project + "/"):
                    resp.topics.add(name=name)
        return resp

    def _publish(self, request: Any, context: Any) -> Any:
        resp = MESSAGES[f"{_P}.PublishResponse"]()
        with self._data:
            log = self._topics.get(request.topic)
            if log is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
            for m in request.messages:
                mid = str(next(self._ids))
                stored = MESSAGES[f"{_P}.PubsubMessage"]()
                stored.CopyFrom(m)
                stored.message_id = mid
                stored.publish_time = time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                )
                log.append(stored)
                resp.message_ids.append(mid)
            self._data.notify_all()
        return resp

    # -- Subscriber --------------------------------------------------------
    def _create_sub(self, request: Any, context: Any) -> Any:
        with self._lock:
            if request.name in self._subs:
                context.abort(grpc.StatusCode.ALREADY_EXISTS, "subscription exists")
            if request.topic not in self._topics:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such topic")
            sub = _Subscription(request.name, request.topic, request.ack_deadline_seconds)
            # Pub/Sub semantics: a new subscription sees messages published
            # AFTER it exists
            sub.cursor = len(self._topics[request.topic])
            self._subs[request.name] = sub
        return request

    def _delete_sub(self, request: Any, context: Any) -> Any:
        with self._lock:
            if self._subs.pop(request.subscription, None) is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")
        return MESSAGES[f"{_P}.Empty"]()

    def _expire_locked(self, sub: _Subscription) -> None:
        now = time.monotonic()
        expired = [aid for aid, (_i, dl) in sub.outstanding.items() if dl <= now]
        for aid in expired:
            idx, _ = sub.outstanding.pop(aid)
            if idx not in sub.acked:
                sub.redeliver.append(idx)

    def _pull(self, request: Any, context: Any) -> Any:
        resp = MESSAGES[f"{_P}.PullResponse"]()
        with self._data:
            sub = self._subs.get(request.subscription)
            if sub is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")
            self._expire_locked(sub)
            log = self._topics.get(sub.topic, [])
            n = max(1, request.max_messages)
            while n > 0:
                if sub.redeliver:
                    idx = sub.redeliver.pop(0)
                elif sub.cursor < len(log):
                    idx = sub.cursor
                    sub.cursor += 1
                else:
                    break
                ack_id = f"{sub.name}:{idx}:{next(self._ids)}"
                sub.outstanding[ack_id] = (
                    idx, time.monotonic() + sub.ack_deadline_s
                )
                resp.received_messages.add(ack_id=ack_id, message=log[idx])
                n -= 1
        return resp

    def _ack(self, request: Any, context: Any) -> Any:
        with self._lock:
            sub = self._subs.get(request.subscription)
            if sub is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")
            for aid in request.ack_ids:
                entry = sub.outstanding.pop(aid, None)
                if entry is not None:
                    sub.acked.add(entry[0])
        return MESSAGES[f"{_P}.Empty"]()

    def _modify(self, request: Any, context: Any) -> Any:
        """deadline 0 = nack (immediate redelivery), else extend."""
        with self._data:
            sub = self._subs.get(request.subscription)
            if sub is None:
                context.abort(grpc.StatusCode.NOT_FOUND, "no such subscription")
            for aid in request.ack_ids:
                entry = sub.outstanding.pop(aid, None)
                if entry is None:
                    continue
                idx, _ = entry
                if request.ack_deadline_seconds <= 0:
                    if idx not in sub.acked:
                        sub.redeliver.append(idx)
                else:
                    sub.outstanding[aid] = (
                        idx, time.monotonic() + request.ack_deadline_seconds
                    )
            self._data.notify_all()
        return MESSAGES[f"{_P}.Empty"]()

    # -- test inspection ---------------------------------------------------
    def topic_size(self, topic_path: str) -> int:
        with self._lock:
            return len(self._topics.get(topic_path, []))


def start_google_pubsub(**kw: Any) -> GooglePubSubServer:
    return GooglePubSubServer(**kw)

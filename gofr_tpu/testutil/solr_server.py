"""In-process Solr-HTTP server for tests, adapting the embedded BM25
engine (datasource/search) behind the Solr wire (SURVEY §4 tier 4).

Serves the surface the Solr driver uses: ``/solr/admin/collections``
(CREATE/DELETE/LIST), ``/solr/<c>/select`` with a standard-query-parser
subset (``*:*``, ``field:value``, ``field:[a TO b]`` ranges, free text
→ BM25 match over all fields, ``AND``/``OR`` pairs), and
``/solr/<c>/update`` JSON commands (add array, delete by ids or query).
Responses use Solr's standard envelope.
"""

from __future__ import annotations

import json
import re
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from gofr_tpu.datasource.search import EmbeddedSearch, IndexNotFound, SearchError

_RANGE = re.compile(r"^(\w+):\[(\S+)\s+TO\s+(\S+)\]$")
_FIELD = re.compile(r"^(\w+):(.+)$")


def _term(value: str) -> Any:
    value = value.strip().strip('"')
    try:
        return int(value)
    except ValueError:
        try:
            return float(value)
        except ValueError:
            return value


def _bound(value: str) -> Any:
    return None if value == "*" else _term(value)


def solr_q_to_query(q: str) -> dict:
    """Standard-query-parser subset → the embedded engine's query DSL."""
    q = q.strip()
    if not q or q == "*:*":
        return {"match_all": {}}
    for joiner, key in ((" AND ", "must"), (" OR ", "should")):
        if joiner in q:
            parts = [solr_q_to_query(p) for p in q.split(joiner)]
            return {"bool": {key: parts}}
    m = _RANGE.match(q)
    if m:
        field, lo, hi = m.groups()
        bounds: dict[str, Any] = {}
        if _bound(lo) is not None:
            bounds["gte"] = _bound(lo)
        if _bound(hi) is not None:
            bounds["lte"] = _bound(hi)
        return {"range": {field: bounds}}
    m = _FIELD.match(q)
    if m:
        field, value = m.groups()
        term = _term(value)
        if isinstance(term, str) and " " in term:
            return {"match": {field: term}}
        return {"term": {field: term}}
    return {"match": {"_all": q}}


class MiniSolrServer:
    def __init__(self, port: int = 0) -> None:
        self._engine = EmbeddedSearch()
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a: Any) -> None:
                pass

            def do_GET(self) -> None:  # noqa: N802
                outer._handle(self, b"")

            def do_POST(self) -> None:  # noqa: N802
                length = int(self.headers.get("Content-Length") or 0)
                outer._handle(self, self.rfile.read(length) if length else b"")

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="solr-server").start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- plumbing ----------------------------------------------------------
    def _reply(self, req: BaseHTTPRequestHandler, status: int, body: dict) -> None:
        data = json.dumps(body).encode()
        req.send_response(status)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _error(self, req: BaseHTTPRequestHandler, status: int, msg: str) -> None:
        self._reply(req, status, {"error": {"code": status, "msg": msg}})

    def _handle(self, req: BaseHTTPRequestHandler, body: bytes) -> None:
        parsed = urllib.parse.urlparse(req.path)
        qs = dict(urllib.parse.parse_qsl(parsed.query))
        parts = [p for p in parsed.path.split("/") if p]
        try:
            if parts[:3] == ["solr", "admin", "collections"]:
                self._admin(req, qs)
            elif len(parts) == 3 and parts[0] == "solr" and parts[2] == "select":
                self._select(req, parts[1], qs)
            elif len(parts) == 3 and parts[0] == "solr" and parts[2] == "update":
                self._update(req, parts[1], body)
            else:
                self._error(req, 404, f"unknown path {parsed.path}")
        except IndexNotFound as exc:
            self._error(req, 404, f"Collection not found: {exc}")
        except (SearchError, ValueError) as exc:
            self._error(req, 400, str(exc))

    # -- endpoints ---------------------------------------------------------
    def _admin(self, req: BaseHTTPRequestHandler, qs: dict[str, str]) -> None:
        action = qs.get("action", "").upper()
        with self._lock:
            if action == "CREATE":
                self._engine.create_index(qs["name"])
                self._reply(req, 200, {"responseHeader": {"status": 0}})
            elif action == "DELETE":
                self._engine.delete_index(qs["name"])
                self._reply(req, 200, {"responseHeader": {"status": 0}})
            elif action == "LIST":
                self._reply(req, 200, {
                    "responseHeader": {"status": 0},
                    "collections": self._engine.indices(),
                })
            else:
                self._error(req, 400, f"unsupported action {action!r}")

    def _select(self, req: BaseHTTPRequestHandler, collection: str,
                qs: dict[str, str]) -> None:
        query = solr_q_to_query(qs.get("q", "*:*"))
        rows = int(qs.get("rows", "10"))
        start = int(qs.get("start", "0"))
        with self._lock:
            # sort applies to the FULL result set before start/rows (real
            # Solr semantics), so fetch everything when sorting
            size = 1_000_000 if qs.get("sort") else start + rows
            result = self._engine.search(collection, {"query": query}, size=size)
        docs = []
        for h in result["hits"]["hits"]:
            doc = dict(h["_source"])
            doc.setdefault("id", h["_id"])
            docs.append(doc)
        if qs.get("sort"):
            field, _, direction = qs["sort"].partition(" ")
            docs.sort(key=lambda d: d.get(field) or 0,
                      reverse=direction.strip() == "desc")
        docs = docs[start : start + rows]
        self._reply(req, 200, {
            "responseHeader": {"status": 0},
            "response": {
                "numFound": result["hits"]["total"]["value"],
                "start": start,
                "docs": docs,
            },
        })

    def _update(self, req: BaseHTTPRequestHandler, collection: str,
                body: bytes) -> None:
        payload = json.loads(body.decode() or "null")
        with self._lock:
            if collection not in self._engine.indices():
                self._engine.create_index(collection)
            if isinstance(payload, list):  # add/upsert documents
                for doc in payload:
                    if "id" not in doc:
                        raise ValueError("document missing required field: id")
                    self._engine.index_document(collection, str(doc["id"]), doc)
            elif isinstance(payload, dict) and "delete" in payload:
                spec = payload["delete"]
                if isinstance(spec, list):
                    for doc_id in spec:
                        try:
                            self._engine.delete_document(collection, str(doc_id))
                        except SearchError:
                            pass  # delete is idempotent in Solr
                elif isinstance(spec, dict) and "query" in spec:
                    query = solr_q_to_query(spec["query"])
                    result = self._engine.search(
                        collection, {"query": query}, size=1_000_000
                    )
                    for h in result["hits"]["hits"]:
                        self._engine.delete_document(collection, h["_id"])
                else:
                    raise ValueError("malformed delete command")
            else:
                raise ValueError("unsupported update payload")
        self._reply(req, 200, {"responseHeader": {"status": 0}})


def start_solr_server(**kw: Any) -> MiniSolrServer:
    return MiniSolrServer(**kw)

"""In-process MySQL wire server for tests — the CI service-container
stand-in (SURVEY §4 tier 4; the reference CI runs a real MySQL on :2001,
go.yml:38-77), like postgres_server.py.

Speaks protocol 4.1 (datasource/sql/mysql_wire.py): HandshakeV10 with
**mysql_native_password** challenge/response (so the driver's real
scramble path is exercised), COM_QUERY text resultsets, COM_PING,
COM_QUIT. SQL executes on a shared in-memory sqlite database; rows
stream back as column definitions + text rows, errors as ERR packets
with MySQL-ish codes. ``kill_connections()`` severs every live session
for reconnect tests.
"""

from __future__ import annotations

import os
import socket
import sqlite3
import threading
from typing import Any

from gofr_tpu.datasource.sql import mysql_wire as wire


class MiniMySQLServer:
    def __init__(self, port: int = 0, user: str = "gofr", password: str = "secret",
                 database: str = "gofrdb") -> None:
        self.user, self.password, self.database = user, password, database
        self._db = sqlite3.connect(":memory:", check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        self._db.isolation_level = None
        self._db_lock = threading.Lock()
        self._running = True
        self._conns: list[socket.socket] = []
        self._conns_lock = threading.Lock()
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(8)
        self.port = self._server.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="mysql-server").start()

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        self.kill_connections()

    def kill_connections(self) -> None:
        """Sever every live session (reconnect-after-kill tests)."""
        with self._conns_lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass

    # -- connection handling ----------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, sock: socket.socket) -> None:
        try:
            if not self._handshake(sock):
                return
            reader = wire.PacketReader(sock)
            while True:
                _, payload = reader.read_packet()
                if not payload:
                    return
                cmd = payload[0]
                if cmd == wire.COM_QUIT:
                    return
                if cmd == wire.COM_PING:
                    wire.send_packet(sock, 1, wire.ok_packet())
                elif cmd == wire.COM_QUERY:
                    self._query(sock, payload[1:].decode("utf-8", "replace"))
                else:
                    wire.send_packet(sock, 1, wire.err_packet(
                        1047, "08S01", f"unknown command 0x{cmd:02x}"
                    ))
        except (wire.MySQLError, OSError, IndexError, ValueError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _handshake(self, sock: socket.socket) -> bool:
        nonce = os.urandom(20).replace(b"\x00", b"\x01")
        seq = wire.send_packet(
            sock, 0,
            wire.handshake_v10("8.0.0-mini", 1, nonce,
                               wire.CLIENT_PROTOCOL_41
                               | wire.CLIENT_SECURE_CONNECTION
                               | wire.CLIENT_PLUGIN_AUTH
                               | wire.CLIENT_CONNECT_WITH_DB),
        )
        reader = wire.PacketReader(sock)
        _, payload = reader.read_packet()
        resp = wire.parse_handshake_response(payload)
        want = wire.native_password_scramble(self.password, nonce)
        if resp["user"] != self.user or resp["auth"] != want:
            wire.send_packet(sock, seq + 1, wire.err_packet(
                1045, "28000", f"Access denied for user '{resp['user']}'"
            ))
            return False
        wire.send_packet(sock, seq + 1, wire.ok_packet())
        return True

    # -- query execution ---------------------------------------------------
    def _query(self, sock: socket.socket, sql: str) -> None:
        stripped = sql.strip().rstrip(";")
        try:
            with self._db_lock:
                cur = self._db.execute(stripped)
                rows = cur.fetchall() if cur.description else []
                description = cur.description
                affected = cur.rowcount if cur.rowcount >= 0 else 0
                last_id = cur.lastrowid or 0
        except sqlite3.Error as exc:
            wire.send_packet(sock, 1, wire.err_packet(1064, "42000", str(exc)))
            return
        if description is None:
            wire.send_packet(sock, 1, wire.ok_packet(affected, last_id))
            return
        names = [d[0] for d in description]
        seq = wire.send_packet(sock, 1, wire.lenenc_int(len(names)))
        for name in names:
            seq = wire.send_packet(sock, seq, wire.column_definition(name))
        seq = wire.send_packet(sock, seq, wire.eof_packet())
        for row in rows:
            seq = wire.send_packet(sock, seq, wire.text_row(list(row)))
        wire.send_packet(sock, seq, wire.eof_packet())

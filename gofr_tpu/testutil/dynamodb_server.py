"""DynamoDB-shaped test server: x-amz-json-1.0 command endpoint that
VERIFIES SigV4 signatures (same discipline as the S3 test broker — the
driver's signing is exercised for real, not trusted)."""

from __future__ import annotations

import hashlib
import hmac
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, HTTPServer
from typing import Any

from gofr_tpu.datasource.file.s3 import (
    canonical_request,
    signing_key,
    string_to_sign,
)


class MiniDynamoDBServer:
    def __init__(self, access_key: str = "AK", secret_key: str = "SK",
                 region: str = "us-east-1") -> None:
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.tables: dict[str, dict[str, dict]] = {"kv": {}}
        self._httpd: HTTPServer | None = None

    @property
    def port(self) -> int:
        assert self._httpd is not None
        return self._httpd.server_port

    @property
    def endpoint(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "MiniDynamoDBServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a: Any) -> None:
                pass

            def _reply(self, code: int, body: dict) -> None:
                raw = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/x-amz-json-1.0")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _verify_sig(self, payload: bytes) -> bool:
                auth = self.headers.get("Authorization", "")
                if not auth.startswith("AWS4-HMAC-SHA256 "):
                    return False
                fields = dict(
                    part.strip().split("=", 1)
                    for part in auth[len("AWS4-HMAC-SHA256 ") :].split(",")
                )
                signed_headers = fields.get("SignedHeaders", "").split(";")
                try:
                    access_key, date, region, service, _ = fields.get(
                        "Credential", ""
                    ).split("/")
                except ValueError:
                    return False
                if (access_key != server.access_key
                        or region != server.region or service != "dynamodb"):
                    return False
                parsed = urllib.parse.urlparse(self.path)
                headers = {h: self.headers.get(h, "") for h in signed_headers}
                creq = canonical_request(
                    "POST", urllib.parse.unquote(parsed.path), parsed.query,
                    headers, signed_headers,
                    self.headers.get(
                        "x-amz-content-sha256",
                        hashlib.sha256(payload).hexdigest(),
                    ),
                )
                sts = string_to_sign(
                    self.headers.get("x-amz-date", ""),
                    f"{date}/{region}/{service}/aws4_request", creq,
                )
                want = hmac.new(
                    signing_key(server.secret_key, date, region, service),
                    sts.encode(), hashlib.sha256,
                ).hexdigest()
                return hmac.compare_digest(want, fields.get("Signature", ""))

            def do_POST(self) -> None:
                payload = self.rfile.read(
                    int(self.headers.get("Content-Length", 0))
                )
                if not self._verify_sig(payload):
                    self._reply(403, {
                        "__type": "UnrecognizedClientException",
                        "message": "signature mismatch",
                    })
                    return
                target = self.headers.get("X-Amz-Target", "")
                op = target.split(".")[-1]
                body = json.loads(payload or b"{}")
                table_name = body.get("TableName", "")
                table = server.tables.get(table_name)
                if table is None:
                    self._reply(400, {
                        "__type": "ResourceNotFoundException",
                        "message": f"table {table_name} not found",
                    })
                    return
                if op == "PutItem":
                    item = body["Item"]
                    # store by the FIRST attribute (the partition key by
                    # driver convention)
                    pk = next(iter(item))
                    table[item[pk]["S"]] = item
                    self._reply(200, {})
                elif op == "GetItem":
                    key = next(iter(body["Key"].values()))["S"]
                    item = table.get(key)
                    self._reply(200, {"Item": item} if item else {})
                elif op == "DeleteItem":
                    key = next(iter(body["Key"].values()))["S"]
                    table.pop(key, None)
                    self._reply(200, {})
                elif op == "DescribeTable":
                    self._reply(200, {"Table": {
                        "TableName": table_name,
                        "TableStatus": "ACTIVE",
                        "ItemCount": len(table),
                    }})
                else:
                    self._reply(400, {
                        "__type": "UnknownOperationException",
                        "message": op,
                    })

        self._httpd = HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None

"""In-process NATS broker for tests (SURVEY §4 tier 4 service-container
stand-in, like kafka_broker.py).

Core NATS text protocol: INFO/CONNECT/PING/PONG, PUB/HPUB, SUB with
**queue groups** (one delivery per group, round-robin within), UNSUB,
MSG/HMSG delivery with headers. At-least-once on top: every queue-group
delivery carries a reply inbox; a ``+ACK`` published there settles it,
and unsettled messages are redelivered to the group after ``ack_wait``
seconds (the JetStream ack model reduced to its observable contract).
"""

from __future__ import annotations

import itertools
import json
import socket
import threading
import time
from typing import Any

CRLF = b"\r\n"


class _Subscription:
    def __init__(self, conn: "_ClientConn", subject: str, queue_group: str,
                 sid: int) -> None:
        self.conn = conn
        self.subject = subject
        self.queue_group = queue_group
        self.sid = sid


class _Pending:
    __slots__ = ("subject", "headers", "body", "group", "deadline", "inbox")

    def __init__(self, subject: str, headers: dict, body: bytes, group: str,
                 deadline: float, inbox: str) -> None:
        self.subject = subject
        self.headers = headers
        self.body = body
        self.group = group
        self.deadline = deadline
        self.inbox = inbox


class MiniNatsBroker:
    def __init__(self, port: int = 0, ack_wait: float = 1.0) -> None:
        self.ack_wait = ack_wait
        self._subs: list[_Subscription] = []
        self._pending: dict[str, _Pending] = {}  # inbox → unacked delivery
        self._rr: dict[tuple[str, str], int] = {}
        self._conns: list["_ClientConn"] = []
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._running = True
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(16)
        self.port = self._server.getsockname()[1]
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="nats-broker").start()
        threading.Thread(target=self._redeliver_loop, daemon=True,
                         name="nats-redeliver").start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        # a real broker shutdown severs client connections too — clients
        # must observe the loss, not keep talking to a zombie socket.
        # shutdown() (not just close()) sends the FIN even while the conn
        # thread is blocked in recv.
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass

    # -- loops --------------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            client = _ClientConn(self, conn)
            with self._lock:
                self._conns.append(client)
            threading.Thread(target=client.run, daemon=True).start()

    def _redeliver_loop(self) -> None:
        while self._running:
            time.sleep(self.ack_wait / 4)
            now = time.monotonic()
            with self._lock:
                expired = [p for p in self._pending.values() if p.deadline <= now]
                for p in expired:
                    del self._pending[p.inbox]
            for p in expired:
                self._route(p.subject, p.headers, p.body, redelivered=True)

    # -- routing -------------------------------------------------------------
    def _match(self, pattern: str, subject: str) -> bool:
        pp, sp = pattern.split("."), subject.split(".")
        for i, tok in enumerate(pp):
            if tok == ">":
                return True
            if i >= len(sp):
                return False
            if tok != "*" and tok != sp[i]:
                return False
        return len(pp) == len(sp)

    def _route(self, subject: str, headers: dict, body: bytes,
               redelivered: bool = False) -> None:
        nak_pending: _Pending | None = None
        with self._lock:
            # ack inboxes bypass group delivery: +ACK/+TERM settle, -NAK
            # asks for immediate redelivery (the JetStream ack vocabulary)
            if subject.startswith("_ACK."):
                p = self._pending.pop(subject, None)
                if p is not None and body.strip() == b"-NAK":
                    nak_pending = p
                else:
                    return
        if nak_pending is not None:
            self._route(nak_pending.subject, nak_pending.headers,
                        nak_pending.body, redelivered=True)
            return
        with self._lock:
            by_group: dict[str, list[_Subscription]] = {}
            plain: list[_Subscription] = []
            for s in self._subs:
                if not self._match(s.subject, subject):
                    continue
                if s.queue_group:
                    by_group.setdefault(s.queue_group, []).append(s)
                else:
                    plain.append(s)
            targets: list[tuple[_Subscription, str]] = [(s, "") for s in plain]
            for group, members in by_group.items():
                idx = self._rr.get((subject, group), 0)
                self._rr[(subject, group)] = idx + 1
                chosen = members[idx % len(members)]
                inbox = f"_ACK.{next(self._ids)}"
                self._pending[inbox] = _Pending(
                    subject, headers, body, group,
                    time.monotonic() + self.ack_wait, inbox,
                )
                targets.append((chosen, inbox))
        for sub, inbox in targets:
            hdrs = dict(headers)
            if redelivered and inbox:
                hdrs["Nats-Redelivered"] = "true"
            sub.conn.deliver(sub, subject, inbox, hdrs, body)

    # -- test inspection -----------------------------------------------------
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)


class _ClientConn:
    def __init__(self, broker: MiniNatsBroker, sock: socket.socket) -> None:
        self.broker = broker
        self.sock = sock
        self._buf = b""
        self._wlock = threading.Lock()
        self._my_subs: list[_Subscription] = []

    # -- io ------------------------------------------------------------------
    def _read_line(self) -> bytes:
        while CRLF not in self._buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("client gone")
            self._buf += chunk
        line, self._buf = self._buf.split(CRLF, 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("client gone")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _send(self, data: bytes) -> None:
        with self._wlock:
            self.sock.sendall(data)

    def deliver(self, sub: _Subscription, subject: str, reply: str,
                headers: dict, body: bytes) -> None:
        try:
            if headers:
                from gofr_tpu.datasource.pubsub.nats import encode_headers

                hdr = encode_headers(headers)
                head = f"HMSG {subject} {sub.sid}"
                if reply:
                    head += f" {reply}"
                head += f" {len(hdr)} {len(hdr) + len(body)}"
                self._send(head.encode() + CRLF + hdr + body + CRLF)
            else:
                head = f"MSG {subject} {sub.sid}"
                if reply:
                    head += f" {reply}"
                head += f" {len(body)}"
                self._send(head.encode() + CRLF + body + CRLF)
        except OSError:
            pass

    # -- protocol ------------------------------------------------------------
    def run(self) -> None:
        broker = self.broker
        try:
            self._send(
                b"INFO "
                + json.dumps({
                    "server_name": "gofr-mini-nats", "version": "2.10-mini",
                    "headers": True, "max_payload": 1 << 20,
                }).encode()
                + CRLF
            )
            while broker._running:
                line = self._read_line()
                verb, _, rest = line.partition(b" ")
                verb = verb.upper()
                if verb == b"CONNECT":
                    pass
                elif verb == b"PING":
                    self._send(b"PONG" + CRLF)
                elif verb == b"PONG":
                    pass
                elif verb == b"SUB":
                    parts = rest.decode().split()
                    if len(parts) == 3:
                        subject, group, sid = parts
                    else:
                        subject, sid = parts
                        group = ""
                    sub = _Subscription(self, subject, group, int(sid))
                    self._my_subs.append(sub)
                    with broker._lock:
                        broker._subs.append(sub)
                elif verb == b"UNSUB":
                    sid = int(rest.decode().split()[0])
                    with broker._lock:
                        broker._subs = [
                            s for s in broker._subs
                            if not (s.conn is self and s.sid == sid)
                        ]
                elif verb in (b"PUB", b"HPUB"):
                    parts = rest.decode().split()
                    if verb == b"PUB":
                        # PUB <subject> [reply] <total>
                        subject = parts[0]
                        total = int(parts[-1])
                        hdr_len = 0
                    else:
                        # HPUB <subject> [reply] <hdr_len> <total>
                        subject = parts[0]
                        hdr_len, total = int(parts[-2]), int(parts[-1])
                    payload = self._read_exact(total)
                    self._read_exact(2)  # CRLF
                    headers = {}
                    if hdr_len:
                        from gofr_tpu.datasource.pubsub.nats import decode_headers

                        headers = decode_headers(payload[:hdr_len])
                    broker._route(subject, headers, payload[hdr_len:])
                else:
                    self._send(b"-ERR 'Unknown Protocol Operation'" + CRLF)
        except (ConnectionError, OSError, ValueError):
            pass
        finally:
            with broker._lock:
                broker._subs = [s for s in broker._subs if s.conn is not self]
            try:
                self.sock.close()
            except OSError:
                pass


def start_nats_broker(**kw: Any) -> MiniNatsBroker:
    return MiniNatsBroker(**kw)

"""In-process object-store fakes: a GCS JSON-API server and an S3 REST
server over one shared blob map — the reference CI's storage-emulator
pattern (fake-gcs-server / localstack) without docker.

The S3 fake *verifies* AWS SigV4 with the configured secret (recomputing
the canonical request from the received request), so the driver's signer
is tested for real, not just for header presence.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

from gofr_tpu.datasource.file.s3 import (
    canonical_request,
    signing_key,
    string_to_sign,
)


class _BlobStore:
    def __init__(self) -> None:
        self.blobs: dict[str, bytes] = {}
        self.lock = threading.Lock()

    def list(self, prefix: str, delimiter: str | None):
        """-> (objects [(name, size)], common prefixes)."""
        with self.lock:
            names = sorted(n for n in self.blobs if n.startswith(prefix))
            if not delimiter:
                return [(n, len(self.blobs[n])) for n in names], []
            objects, prefixes = [], []
            seen: set[str] = set()
            for n in names:
                rest = n[len(prefix) :]
                if delimiter in rest:
                    p = prefix + rest.split(delimiter, 1)[0] + delimiter
                    if p not in seen:
                        seen.add(p)
                        prefixes.append(p)
                else:
                    objects.append((n, len(self.blobs[n])))
            return objects, prefixes


def _parse_range(header: str | None, size: int) -> tuple[int, int]:
    if not header or not header.startswith("bytes="):
        return 0, size
    start_s, _, end_s = header[6:].partition("-")
    start = int(start_s or 0)
    end = int(end_s) + 1 if end_s else size
    return start, min(end, size)


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "FakeObjectStore/1"

    def log_message(self, *args: Any) -> None:
        pass

    def _read_body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(n) if n else b""

    def _reply(
        self, code: int, body: bytes = b"", content_type: str = "application/json",
        headers: dict | None = None,
    ) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if self.command != "HEAD":
            self.wfile.write(body)


# ---------------------------------------------------------------------- GCS
class _GCSHandler(_Handler):
    store: _BlobStore
    bucket: str

    def do_GET(self) -> None:
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))
        base = f"/storage/v1/b/{self.bucket}/o"
        if parsed.path == base:  # list
            objects, prefixes = self.store.list(
                params.get("prefix", ""), params.get("delimiter")
            )
            body = {
                "items": [{"name": n, "size": str(s)} for n, s in objects],
            }
            if prefixes:
                body["prefixes"] = prefixes
            self._reply(200, json.dumps(body).encode())
            return
        if parsed.path.startswith(base + "/"):
            name = urllib.parse.unquote(parsed.path[len(base) + 1 :])
            with self.store.lock:
                blob = self.store.blobs.get(name)
            if blob is None:
                self._reply(404, b'{"error": "not found"}')
                return
            if params.get("alt") == "media":
                start, end = _parse_range(self.headers.get("Range"), len(blob))
                data = blob[start:end]
                code = 206 if self.headers.get("Range") else 200
                self._reply(code, data, "application/octet-stream")
            else:
                self._reply(
                    200,
                    json.dumps(
                        {"name": name, "size": str(len(blob)), "generation": "1"}
                    ).encode(),
                )
            return
        self._reply(404, b"{}")

    def do_POST(self) -> None:
        parsed = urllib.parse.urlparse(self.path)
        params = dict(urllib.parse.parse_qsl(parsed.query))
        upload_base = f"/upload/storage/v1/b/{self.bucket}/o"
        if parsed.path == upload_base and params.get("uploadType") == "media":
            name = params.get("name", "")
            data = self._read_body()
            with self.store.lock:
                self.store.blobs[name] = data
            self._reply(
                200, json.dumps({"name": name, "size": str(len(data))}).encode()
            )
            return
        # copyTo: /storage/v1/b/{b}/o/{src}/copyTo/b/{b}/o/{dst}
        marker = f"/copyTo/b/{self.bucket}/o/"
        base = f"/storage/v1/b/{self.bucket}/o/"
        if parsed.path.startswith(base) and marker in parsed.path:
            src_enc, _, dst_enc = parsed.path[len(base) :].partition(marker)
            src = urllib.parse.unquote(src_enc)
            dst = urllib.parse.unquote(dst_enc)
            self._read_body()
            with self.store.lock:
                if src not in self.store.blobs:
                    self._reply(404, b'{"error": "not found"}')
                    return
                self.store.blobs[dst] = self.store.blobs[src]
            self._reply(200, json.dumps({"name": dst}).encode())
            return
        self._reply(404, b"{}")

    def do_DELETE(self) -> None:
        base = f"/storage/v1/b/{self.bucket}/o/"
        name = urllib.parse.unquote(
            urllib.parse.urlparse(self.path).path[len(base) :]
        )
        with self.store.lock:
            existed = self.store.blobs.pop(name, None)
        self._reply(204 if existed is not None else 404, b"")


# ----------------------------------------------------------------------- S3
class _S3Handler(_Handler):
    store: _BlobStore
    bucket: str
    region: str
    access_key: str
    secret_key: str

    def _verify_sig(self, payload: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256 "):
            return False
        fields = dict(
            part.strip().split("=", 1)
            for part in auth[len("AWS4-HMAC-SHA256 ") :].split(",")
        )
        credential = fields.get("Credential", "")
        signed_headers = fields.get("SignedHeaders", "").split(";")
        got_sig = fields.get("Signature", "")
        try:
            access_key, date, region, service, _ = credential.split("/")
        except ValueError:
            return False
        if access_key != self.access_key or region != self.region:
            return False
        parsed = urllib.parse.urlparse(self.path)
        headers = {h: self.headers.get(h, "") for h in signed_headers}
        creq = canonical_request(
            self.command,
            urllib.parse.unquote(parsed.path),
            parsed.query,
            headers,
            signed_headers,
            self.headers.get("x-amz-content-sha256", hashlib.sha256(payload).hexdigest()),
        )
        sts = string_to_sign(
            self.headers.get("x-amz-date", ""),
            f"{date}/{region}/{service}/aws4_request",
            creq,
        )
        want = hmac.new(
            signing_key(self.secret_key, date, region, service),
            sts.encode(),
            hashlib.sha256,
        ).hexdigest()
        return hmac.compare_digest(want, got_sig)

    def _key(self) -> str:
        path = urllib.parse.unquote(urllib.parse.urlparse(self.path).path)
        prefix = f"/{self.bucket}"
        if path == prefix or path == prefix + "/":
            return ""
        return path[len(prefix) + 1 :]

    def _handle(self) -> None:
        payload = self._read_body()
        if not self._verify_sig(payload):
            self._reply(403, b"<Error><Code>SignatureDoesNotMatch</Code></Error>",
                        "application/xml")
            return
        key = self._key()
        if self.command == "GET" and not key:
            self._list()
            return
        if self.command == "GET":
            with self.store.lock:
                blob = self.store.blobs.get(key)
            if blob is None:
                self._reply(404, b"<Error><Code>NoSuchKey</Code></Error>",
                            "application/xml")
                return
            start, end = _parse_range(self.headers.get("Range"), len(blob))
            code = 206 if self.headers.get("Range") else 200
            self._reply(code, blob[start:end], "application/octet-stream")
            return
        if self.command == "HEAD":
            with self.store.lock:
                blob = self.store.blobs.get(key)
            if blob is None:
                self._reply(404, b"")
                return
            self.send_response(200)
            self.send_header("Content-Length", str(len(blob)))
            self.send_header("Content-Type", "application/octet-stream")
            self.end_headers()
            return
        if self.command == "PUT":
            src = self.headers.get("x-amz-copy-source")
            if src:
                src_key = urllib.parse.unquote(src)[len(f"/{self.bucket}/") :]
                with self.store.lock:
                    if src_key not in self.store.blobs:
                        self._reply(404, b"<Error><Code>NoSuchKey</Code></Error>",
                                    "application/xml")
                        return
                    self.store.blobs[key] = self.store.blobs[src_key]
                self._reply(200, b"<CopyObjectResult/>", "application/xml")
            else:
                with self.store.lock:
                    self.store.blobs[key] = payload
                self._reply(200, b"")
            return
        if self.command == "DELETE":
            with self.store.lock:
                self.store.blobs.pop(key, None)
            self._reply(204, b"")
            return
        self._reply(405, b"")

    def _list(self) -> None:
        params = dict(
            urllib.parse.parse_qsl(urllib.parse.urlparse(self.path).query)
        )
        objects, prefixes = self.store.list(
            params.get("prefix", ""), params.get("delimiter")
        )
        parts = ["<?xml version='1.0'?><ListBucketResult>"]
        for name, size in objects:
            parts.append(
                f"<Contents><Key>{name}</Key><Size>{size}</Size></Contents>"
            )
        for p in prefixes:
            parts.append(f"<CommonPrefixes><Prefix>{p}</Prefix></CommonPrefixes>")
        parts.append("</ListBucketResult>")
        self._reply(200, "".join(parts).encode(), "application/xml")

    do_GET = do_PUT = do_DELETE = do_HEAD = _handle


class FakeObjectStore:
    """One shared blob map served over a GCS dialect and an S3 dialect."""

    def __init__(
        self, bucket: str = "test-bucket", region: str = "us-east-1",
        access_key: str = "AKIATEST", secret_key: str = "testsecret",
    ) -> None:
        self.bucket = bucket
        self.region = region
        self.access_key = access_key
        self.secret_key = secret_key
        self.store = _BlobStore()

        gcs_handler = type(
            "GCSHandler", (_GCSHandler,), {"store": self.store, "bucket": bucket}
        )
        s3_handler = type(
            "S3Handler",
            (_S3Handler,),
            {
                "store": self.store,
                "bucket": bucket,
                "region": region,
                "access_key": access_key,
                "secret_key": secret_key,
            },
        )
        self._gcs_server = ThreadingHTTPServer(("127.0.0.1", 0), gcs_handler)
        self._s3_server = ThreadingHTTPServer(("127.0.0.1", 0), s3_handler)
        for srv, name in ((self._gcs_server, "fake-gcs"), (self._s3_server, "fake-s3")):
            threading.Thread(target=srv.serve_forever, name=name, daemon=True).start()

    @property
    def gcs_endpoint(self) -> str:
        return f"http://127.0.0.1:{self._gcs_server.server_address[1]}"

    @property
    def s3_endpoint(self) -> str:
        return f"http://127.0.0.1:{self._s3_server.server_address[1]}"

    def close(self) -> None:
        for srv in (self._gcs_server, self._s3_server):
            srv.shutdown()
            srv.server_close()

"""Free-port allocation + server config env (testutil/port.go:14-70)."""

from __future__ import annotations

import dataclasses
import os
import socket


def get_free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class ServerConfigs:
    http_port: int
    metrics_port: int
    grpc_port: int

    @property
    def http_host(self) -> str:
        return f"http://localhost:{self.http_port}"

    @property
    def metrics_host(self) -> str:
        return f"http://localhost:{self.metrics_port}"


def new_server_configs(set_env: bool = True) -> ServerConfigs:
    """Allocate 3 kernel ports and (optionally) export HTTP_PORT /
    METRICS_PORT / GRPC_PORT (testutil/port.go:50-70)."""
    cfg = ServerConfigs(get_free_port(), get_free_port(), get_free_port())
    if set_env:
        os.environ["HTTP_PORT"] = str(cfg.http_port)
        os.environ["METRICS_PORT"] = str(cfg.metrics_port)
        os.environ["GRPC_PORT"] = str(cfg.grpc_port)
    return cfg

"""In-process ClickHouse-HTTP-interface server for tests, backed by
sqlite (SURVEY §4 tier 4 stand-in, like postgres_server.py).

Serves the interface subset the columnar driver uses: ``POST /?query=``
with ``FORMAT JSONEachRow`` output, ``param_<name>`` server-side binding
substituted into ``{name:Type}`` placeholders, JSONEachRow INSERT
bodies, X-ClickHouse-User/Key auth, async_insert settings accepted (and
applied synchronously — the observable contract), ClickHouse-style
exception text with HTTP 4xx/5xx on bad SQL.
"""

from __future__ import annotations

import json
import re
import sqlite3
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any

_PLACEHOLDER = re.compile(r"\{(\w+):[^}]+\}")


class MiniClickHouseServer:
    def __init__(self, port: int = 0, user: str = "default",
                 password: str = "") -> None:
        self.user, self.password = user, password
        self._db = sqlite3.connect(":memory:", check_same_thread=False)
        self._db.row_factory = sqlite3.Row
        self._db.isolation_level = None
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a: Any) -> None:
                pass

            def do_POST(self) -> None:  # noqa: N802 (stdlib naming)
                outer._handle(self)

            def do_GET(self) -> None:  # noqa: N802
                outer._handle(self)

        self._server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="clickhouse-server").start()

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # -- request handling --------------------------------------------------
    def _handle(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urllib.parse.urlparse(req.path)
        qs = dict(urllib.parse.parse_qsl(parsed.query))
        user = req.headers.get("X-ClickHouse-User", "default")
        key = req.headers.get("X-ClickHouse-Key", "")
        if user != self.user or key != self.password:
            self._reply(req, 403, "Code: 516. Authentication failed")
            return
        query = qs.get("query", "").strip()
        length = int(req.headers.get("Content-Length") or 0)
        body = req.rfile.read(length) if length else b""
        params = {
            k[len("param_"):]: v for k, v in qs.items() if k.startswith("param_")
        }
        try:
            out = self._execute(query, params, body)
        except sqlite3.Error as exc:
            self._reply(req, 400, f"Code: 62. DB::Exception: {exc}")
            return
        except ValueError as exc:
            self._reply(req, 400, f"Code: 36. DB::Exception: {exc}")
            return
        self._reply(req, 200, out)

    def _reply(self, req: BaseHTTPRequestHandler, status: int, text: str) -> None:
        data = text.encode()
        req.send_response(status)
        req.send_header("Content-Type", "text/plain; charset=UTF-8")
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    # -- query execution ---------------------------------------------------
    def _execute(self, query: str, params: dict[str, str], body: bytes) -> str:
        fmt_json = False
        m = re.search(r"\sFORMAT\s+(\w+)\s*$", query, re.IGNORECASE)
        if m:
            fmt = m.group(1).upper()
            query = query[: m.start()].strip()
            if fmt == "JSONEACHROW":
                fmt_json = True
            elif fmt not in ("TABSEPARATED", "TSV"):
                raise ValueError(f"unsupported FORMAT {fmt}")

        if query.upper().startswith("INSERT INTO") and body:
            return self._insert_json_rows(query, body)

        # {name:Type} → ? with ordered params
        ordered: list[str] = []

        def sub(match: "re.Match[str]") -> str:
            name = match.group(1)
            if name not in params:
                raise ValueError(f"missing query parameter {name!r}")
            ordered.append(params[name])
            return "?"

        sqlite_sql = _PLACEHOLDER.sub(sub, query)
        sqlite_sql = sqlite_sql.replace("version()", "'23.8-gofr-mini'")
        with self._lock:
            cur = self._db.execute(sqlite_sql, ordered)
            rows = cur.fetchall() if cur.description else []
        if not cur.description:
            return ""
        if fmt_json:
            return "\n".join(json.dumps(dict(r)) for r in rows) + ("\n" if rows else "")
        return "\n".join("\t".join(str(v) for v in tuple(r)) for r in rows)

    def _insert_json_rows(self, query: str, body: bytes) -> str:
        m = re.match(r"INSERT\s+INTO\s+([\w.]+)", query, re.IGNORECASE)
        if not m:
            raise ValueError("malformed INSERT")
        table = m.group(1)
        rows = [json.loads(line) for line in body.decode().splitlines() if line.strip()]
        if not rows:
            return ""
        cols = sorted({k for r in rows for k in r})
        with self._lock:
            self._db.execute(
                f"CREATE TABLE IF NOT EXISTS {table} "
                f"({', '.join(c for c in cols)})"
            )
            for r in rows:
                names = sorted(r)
                self._db.execute(
                    f"INSERT INTO {table} ({', '.join(names)}) "
                    f"VALUES ({', '.join('?' for _ in names)})",
                    [r[n] for n in names],
                )
        return ""

    # -- test inspection ---------------------------------------------------
    def rows(self, sql: str) -> list[tuple]:
        with self._lock:
            return [tuple(r) for r in self._db.execute(sql).fetchall()]


def start_clickhouse_server(**kw: Any) -> MiniClickHouseServer:
    return MiniClickHouseServer(**kw)

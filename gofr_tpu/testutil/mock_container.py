"""Mock container: every datasource faked, for handler tests.

Reference parity: pkg/gofr/container/mock_container.go:20-46,96-140 — one
call returns a Container whose datasources are in-memory fakes plus a
``mocks`` handle for assertions. The TPU datasource fake records compiled
functions and executes them eagerly on CPU — the analogue of the reference's
sqlmock/redismock harness, per SURVEY §4's implication (a) and (b).
"""

from __future__ import annotations

import dataclasses
from typing import Any

from gofr_tpu.config import MapConfig
from gofr_tpu.container.container import Container
from gofr_tpu.logging import Level, new_logger


class MockTPU:
    """Records compile/execute calls; executes on whatever backend JAX picks
    (CPU in tests)."""

    def __init__(self) -> None:
        self.compiled: dict[str, Any] = {}
        self.execute_calls: list[tuple[str, tuple, dict]] = []

    def use_logger(self, logger: Any) -> None:
        pass

    def use_metrics(self, metrics: Any) -> None:
        pass

    def use_tracer(self, tracer: Any) -> None:
        pass

    def connect(self) -> None:
        pass

    def compile(self, name: str, fn: Any, *abstract_args: Any, **options: Any) -> Any:
        self.compiled[name] = fn
        return fn

    def execute(self, name: str, *args: Any, **kwargs: Any) -> Any:
        self.execute_calls.append((name, args, kwargs))
        fn = self.compiled.get(name)
        if fn is None:
            raise KeyError(f"executable {name} not compiled")
        return fn(*args, **kwargs)

    def device_count(self) -> int:
        return 1

    def mesh(self) -> Any:
        return None

    def hbm_stats(self) -> dict[str, Any]:
        return {"bytes_in_use": 0, "bytes_limit": 0}

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP", "backend": "mock", "devices": 1}


class MockPubSub:
    """In-memory broker fake with published-message capture."""

    def __init__(self) -> None:
        self.published: list[tuple[str, bytes, dict]] = []
        self.queues: dict[str, list] = {}

    def publish(self, topic: str, message: bytes, metadata: dict | None = None) -> None:
        self.published.append((topic, message, metadata or {}))
        self.queues.setdefault(topic, []).append((message, metadata or {}))

    def subscribe(self, topic: str) -> Any:
        from gofr_tpu.datasource.pubsub.message import Message

        queue = self.queues.setdefault(topic, [])
        if not queue:
            return None
        value, metadata = queue.pop(0)

        def _nack(requeue: bool) -> None:
            if requeue:  # head of the queue: redelivered next subscribe
                queue.insert(0, (value, metadata))

        return Message(topic=topic, value=value, metadata=metadata, nacker=_nack)

    def create_topic(self, name: str) -> None:
        self.queues.setdefault(name, [])

    def delete_topic(self, name: str) -> None:
        self.queues.pop(name, None)

    def health_check(self) -> dict[str, Any]:
        return {"status": "UP", "backend": "mock"}

    def close(self) -> None:
        pass


@dataclasses.dataclass
class Mocks:
    tpu: MockTPU
    pubsub: MockPubSub
    sql: Any
    redis: Any
    kv_store: Any


class MockContainer(Container):
    pass


def new_mock_container(configs: dict[str, str] | None = None) -> tuple[MockContainer, Mocks]:
    """NewMockContainer(t) analogue (mock_container.go:96-140)."""
    config = MapConfig(configs or {}, use_env=False)
    logger = new_logger(Level.ERROR, exit_on_fatal=False)
    container = MockContainer(config, logger=logger)

    from gofr_tpu.datasource.kv import InMemoryKVStore
    from gofr_tpu.datasource.redis import InMemoryRedis
    from gofr_tpu.datasource.sql import SQLite

    tpu = MockTPU()
    pubsub = MockPubSub()
    sql = SQLite(":memory:")
    redis = InMemoryRedis()
    kv = InMemoryKVStore()
    for name, ds in (("tpu", tpu), ("pubsub", pubsub), ("sql", sql), ("redis", redis), ("kv_store", kv)):
        container.register_datasource(name, ds)
    return container, Mocks(tpu=tpu, pubsub=pubsub, sql=sql, redis=redis, kv_store=kv)

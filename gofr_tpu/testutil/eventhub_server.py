"""In-process Event Hubs stand-in for tests (SURVEY §4 tier 4 service-
container analogue, like kafka_broker.py / nats_broker.py).

Speaks the same AMQP 1.0 subset as the driver (datasource/pubsub/
amqp_wire.py): SASL PLAIN/ANONYMOUS, open/begin, attach (sender and
receiver roles), flow credit, transfer, disposition. Event Hub
semantics on top:

- a hub (topic) is a fixed set of partitions; publishes land on a
  partition by round-robin (or by the ``partition-key`` application
  property's hash when present);
- consumers attach per-partition receiver links at
  ``<hub>/ConsumerGroups/<group>/Partitions/<n>``;
- per (hub, group, partition) a cursor tracks the next undelivered
  offset; an ``accepted`` disposition checkpoints through the delivered
  offset (the reference SDK's blob-checkpoint reduced to its observable
  contract) — unacknowledged messages are redelivered to the next
  attaching receiver.
"""

from __future__ import annotations

import itertools
import socket
import struct
import threading
from typing import Any

from gofr_tpu.datasource.pubsub import amqp_wire as wire
from gofr_tpu.datasource.pubsub.amqp_wire import Described, Symbol, Ubyte, Uint


class _Partition:
    def __init__(self) -> None:
        self.messages: list[bytes] = []  # raw AMQP message sections
        self.cursors: dict[str, int] = {}  # group → next-undelivered offset
        self.acked: dict[str, int] = {}  # group → checkpointed offset (excl.)


class _ReceiverLink:
    __slots__ = ("handle", "server_handle", "topic", "group", "partition",
                 "credit", "delivered")

    def __init__(self, handle: int, server_handle: int, topic: str, group: str,
                 partition: int) -> None:
        self.handle = handle  # the CLIENT's handle (incoming frames carry it)
        self.server_handle = server_handle  # OUR handle (outgoing frames carry it)
        self.topic = topic
        self.group = group
        self.partition = partition
        self.credit = 0
        self.delivered: dict[int, int] = {}  # delivery_id → message offset


class MiniEventHubServer:
    def __init__(self, port: int = 0, partitions: int = 2) -> None:
        self.partitions = partitions
        self._topics: dict[str, list[_Partition]] = {}
        self._rr = itertools.count(0)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", port))
        self._listener.listen(16)
        self.port = self._listener.getsockname()[1]
        self._closed = False
        self._threads: list[threading.Thread] = []
        self.auth_attempts: list[tuple[str, str]] = []  # (mechanism, identity)

    def start(self) -> "MiniEventHubServer":
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name="eventhub-server")
        t.start()
        self._threads.append(t)
        return self

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        with self._cond:
            self._cond.notify_all()

    # -- introspection for tests ------------------------------------------
    def topic_depth(self, topic: str, group: str = "$Default") -> int:
        """Messages not yet checkpointed by ``group`` across partitions."""
        with self._lock:
            parts = self._topics.get(topic, [])
            return sum(len(p.messages) - p.acked.get(group, 0) for p in parts)

    def _partitions_for(self, topic: str) -> list[_Partition]:
        parts = self._topics.get(topic)
        if parts is None:
            parts = [_Partition() for _ in range(self.partitions)]
            self._topics[topic] = parts
        return parts

    # -- connection handling ----------------------------------------------
    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve, args=(conn,), daemon=True,
                name="eventhub-conn",
            )
            t.start()
            self._threads.append(t)

    def _serve(self, sock: socket.socket) -> None:
        state = _ConnState(self, sock)
        try:
            state.run()
        except (wire.AmqpError, OSError, struct.error, IndexError):
            pass
        finally:
            state.stop()
            try:
                sock.close()
            except OSError:
                pass


class _ConnState:
    """One client connection: protocol pumps + delivery thread."""

    def __init__(self, server: MiniEventHubServer, sock: socket.socket) -> None:
        self.server = server
        self.sock = sock
        self._rbuf = b""
        self._wlock = threading.Lock()
        self._receivers: dict[int, _ReceiverLink] = {}
        self._sender_addresses: dict[int, str] = {}  # sender handle → target
        # sender handle → (our echo handle, transfers since last grant):
        # brokers replenish link credit as transfers consume it (AMQP
        # §2.6.7); without this a client enforcing credit stalls at the
        # initial grant
        self._sender_grants: dict[int, tuple[int, int]] = {}
        # deliberately DIFFERENT numbering from any client (spec §2.6.2:
        # each endpoint assigns its own handles; frames carry the sender's)
        # — catches clients that route incoming frames by their own handle
        self._server_handles = itertools.count(100)
        self._delivery_ids = itertools.count(0)
        self._stop = threading.Event()

    # -- io ----------------------------------------------------------------
    def _recv_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise wire.AmqpError("client disconnected")
            self._rbuf += chunk
        out, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return out

    def _send(self, data: bytes) -> None:
        with self._wlock:
            self.sock.sendall(data)

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> None:
        header = self._recv_exact(8)
        if header == wire.PROTO_SASL:
            self._sasl()
            header = self._recv_exact(8)
        if header != wire.PROTO_AMQP:
            raise wire.AmqpError("expected AMQP protocol header")
        self._send(wire.PROTO_AMQP)
        pump = threading.Thread(target=self._delivery_pump, daemon=True,
                                name="eventhub-delivery")
        pump.start()
        while True:
            _, ftype, perf, payload = wire.read_frame(self._recv_exact)
            if perf is None:
                continue
            if not self._handle(perf, payload):
                return

    def stop(self) -> None:
        self._stop.set()
        with self.server._cond:
            # a dying connection's delivered-but-unacked messages must
            # redeliver: roll its partitions' cursors back to the
            # checkpoint so the next attach (or a racing live pump) sees
            # them — without this, a pump that reserved a message just as
            # its socket died swallows it forever
            for link in self._receivers.values():
                parts = self.server._topics.get(link.topic)
                if parts is not None:
                    part = parts[link.partition]
                    part.cursors[link.group] = part.acked.get(link.group, 0)
            self._receivers.clear()
            self.server._cond.notify_all()

    def _sasl(self) -> None:
        self._send(wire.PROTO_SASL)
        mechs = Described(wire.SASL_MECHANISMS, [[Symbol("PLAIN"), Symbol("ANONYMOUS")]])
        self._send(wire.encode_frame(0, mechs, frame_type=wire.FRAME_SASL))
        _, _, init, _ = wire.read_frame(self._recv_exact)
        if init is None or init.descriptor != wire.SASL_INIT:
            raise wire.AmqpError("expected sasl-init")
        mech = str(init.value[0])
        identity = ""
        if mech == "PLAIN" and len(init.value) > 1 and init.value[1]:
            parts = bytes(init.value[1]).split(b"\x00")
            identity = parts[1].decode() if len(parts) > 1 else ""
        self.server.auth_attempts.append((mech, identity))
        outcome = Described(wire.SASL_OUTCOME, [Ubyte(0), None])
        self._send(wire.encode_frame(0, outcome, frame_type=wire.FRAME_SASL))

    # -- frame handling ----------------------------------------------------
    def _handle(self, perf: Described, payload: bytes) -> bool:
        fields = perf.value if isinstance(perf.value, list) else []
        d = perf.descriptor
        if d == wire.OPEN:
            self._send(wire.encode_frame(
                0, Described(wire.OPEN, ["mini-eventhub", None, Uint(1 << 20)])
            ))
        elif d == wire.BEGIN:
            self._send(wire.encode_frame(
                0, Described(wire.BEGIN, [Uint(0), Uint(0), Uint(2048), Uint(2048)])
            ))
        elif d == wire.ATTACH:
            self._attach(fields)
        elif d == wire.FLOW:
            if len(fields) > 6 and fields[4] is not None:
                link = self._receivers.get(int(fields[4]))
                if link is not None:
                    with self.server._cond:
                        link.credit = int(fields[6] or 0)
                        self.server._cond.notify_all()
        elif d == wire.TRANSFER:
            self._transfer(fields, payload)
        elif d == wire.DISPOSITION:
            self._disposition(fields)
        elif d == wire.DETACH:
            handle = int(fields[0]) if fields else -1
            self._receivers.pop(handle, None)
            self._send(wire.encode_frame(0, Described(wire.DETACH, [Uint(handle), True])))
        elif d == wire.END:
            self._send(wire.encode_frame(0, Described(wire.END, [])))
        elif d == wire.CLOSE:
            self._send(wire.encode_frame(0, Described(wire.CLOSE, [])))
            return False
        return True

    def _attach(self, fields: list) -> None:
        name = fields[0]
        handle = int(fields[1])
        client_is_receiver = bool(fields[2])
        if client_is_receiver:
            # client receives: source address names hub/group/partition
            source = fields[5]
            address = source.value[0] if isinstance(source, Described) else str(source)
            topic, group, partition = _parse_partition_address(str(address))
            server_handle = next(self._server_handles)
            with self.server._lock:
                self.server._partitions_for(topic)
                link = _ReceiverLink(handle, server_handle, topic, group, partition)
                # delivery resumes from the checkpoint, not the old cursor:
                # unacked-but-delivered messages redeliver to this link
                part = self.server._topics[topic][partition]
                part.cursors[group] = part.acked.get(group, 0)
                self._receivers[handle] = link
            echo = Described(wire.ATTACH, [
                name, Uint(server_handle), False, Ubyte(0), Ubyte(0),
                Described(wire.SOURCE, [address]),
                Described(wire.TARGET, [None]),
            ])
            self._send(wire.encode_frame(0, echo))
        else:
            # client sends into the hub node: record handle → target address
            target = fields[6] if len(fields) > 6 else None
            address = (
                str(target.value[0])
                if isinstance(target, Described) and target.value else ""
            )
            self._sender_addresses[handle] = address
            server_handle = next(self._server_handles)
            self._sender_grants[handle] = (server_handle, 0)
            echo = Described(wire.ATTACH, [
                name, Uint(server_handle), True, Ubyte(0), Ubyte(0),
                Described(wire.SOURCE, [None]),
                Described(wire.TARGET, [address or None]),
            ])
            self._send(wire.encode_frame(0, echo))
            flow = Described(wire.FLOW, [
                Uint(0), Uint(2048), Uint(0), Uint(2048),
                Uint(server_handle), Uint(0), Uint(1000),
            ])
            self._send(wire.encode_frame(0, flow))

    def _transfer(self, fields: list, payload: bytes) -> None:
        # find the sender link's target address by handle: we echoed the
        # client's attach, so reconstruct from the transfer handle registry.
        handle = int(fields[0])
        address = self._sender_addresses.get(handle)
        if address is None:
            return
        body, props = wire.decode_message(payload)
        with self.server._cond:
            parts = self.server._partitions_for(address)
            pkey = props.get("partition-key") or props.get(Symbol("partition-key"))
            if pkey is not None:
                idx = hash(str(pkey)) % len(parts)
            else:
                idx = next(self.server._rr) % len(parts)
            parts[idx].messages.append(payload)
            self.server._cond.notify_all()
        grant = self._sender_grants.get(handle)
        if grant is not None:
            server_handle, received = grant[0], grant[1] + 1
            if received % 500 == 0:  # top the window back up before it drains
                # delivery-count (field 5) carries OUR receive count so the
                # client's §2.6.7 arithmetic (count + credit - sent) lands
                # on a fresh window of 1000
                flow = Described(wire.FLOW, [
                    Uint(0), Uint(2048), Uint(0), Uint(2048),
                    Uint(server_handle), Uint(received), Uint(1000),
                ])
                self._send(wire.encode_frame(0, flow))
            self._sender_grants[handle] = (server_handle, received)

    def _disposition(self, fields: list) -> None:
        first = int(fields[1])
        last = int(fields[2]) if len(fields) > 2 and fields[2] is not None else first
        state = fields[4] if len(fields) > 4 else None
        released = (
            isinstance(state, Described) and state.descriptor == wire.RELEASED
        )
        with self.server._cond:
            for link in self._receivers.values():
                for did in range(first, last + 1):
                    offset = link.delivered.pop(did, None)
                    if offset is None:
                        continue
                    part = self.server._topics[link.topic][link.partition]
                    if released:
                        # AMQP RELEASED: the delivery goes back to the node —
                        # rewind the group cursor so the pump redelivers it
                        cur = part.cursors.get(link.group, 0)
                        part.cursors[link.group] = min(cur, offset)
                    else:
                        prev = part.acked.get(link.group, 0)
                        part.acked[link.group] = max(prev, offset + 1)
            self.server._cond.notify_all()

    # -- delivery ----------------------------------------------------------
    def _delivery_pump(self) -> None:
        """Push undelivered messages to receiver links with credit."""
        server = self.server
        while not self._stop.is_set():
            sends: list[tuple[_ReceiverLink, int, int, bytes]] = []
            with server._cond:
                for link in self._receivers.values():
                    part = server._topics.get(link.topic, [None] * (link.partition + 1))[link.partition]
                    if part is None:
                        continue
                    cursor = part.cursors.get(link.group, 0)
                    while link.credit > 0 and cursor < len(part.messages):
                        did = next(self._delivery_ids)
                        sends.append((link, did, cursor, part.messages[cursor]))
                        link.delivered[did] = cursor
                        cursor += 1
                        link.credit -= 1
                    part.cursors[link.group] = cursor
                if not sends:
                    server._cond.wait(timeout=0.1)
                    continue
            for link, did, _offset, payload in sends:
                transfer = Described(wire.TRANSFER, [
                    Uint(link.server_handle), Uint(did),
                    struct.pack(">I", did), Uint(0), False,
                ])
                try:
                    self._send(wire.encode_frame(0, transfer, payload))
                except OSError:
                    return


def _parse_partition_address(address: str) -> tuple[str, str, int]:
    """``<hub>/ConsumerGroups/<group>/Partitions/<n>`` → (hub, group, n)."""
    parts = address.strip("/").split("/")
    try:
        cg = parts.index("ConsumerGroups")
        topic = "/".join(parts[:cg])
        group = parts[cg + 1]
        partition = int(parts[parts.index("Partitions") + 1])
        return topic, group, partition
    except (ValueError, IndexError):
        return address, "$Default", 0

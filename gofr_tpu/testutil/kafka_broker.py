"""In-process Kafka mini-broker for tests.

Speaks the wire subset the driver uses (datasource/pubsub/kafka_wire.py):
**Produce v3 / Fetch v4 with record-batch v2 only** — like a real
Kafka ≥0.11 broker it answers legacy Produce/Fetch versions with
UNSUPPORTED_VERSION and magic-0/1 payloads with CORRUPT_MESSAGE, so the
driver is no longer validated by its own mirror (VERDICT r2 item 5) —
plus ListOffsets, Metadata, OffsetCommit/OffsetFetch (consumer-group
offsets), CreateTopics/DeleteTopics. Single-node, any number of
single-partition topics, append-only in-memory logs. Stands in for the
reference CI's Kafka service container (SURVEY §4 tier 4) the way
testutil/mqtt_broker.py does for MQTT.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Any

from gofr_tpu.datasource.pubsub import kafka_wire as wire

# (key, value, headers) triples
LogEntry = tuple  # type alias for readability


class MiniKafkaBroker:
    def __init__(self, port: int = 0, auto_create_topics: bool = True) -> None:
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("127.0.0.1", port))
        self._server.listen(16)
        self.port = self._server.getsockname()[1]
        self.auto_create_topics = auto_create_topics

        self._logs: dict[str, list[tuple[bytes | None, bytes, list]]] = {}
        self._group_offsets: dict[tuple[str, str, int], int] = {}
        self._lock = threading.Lock()
        self._data = threading.Condition(self._lock)
        self._running = True
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="kafka-broker", daemon=True
        )
        self._accept_thread.start()

    @property
    def address(self) -> str:
        return f"127.0.0.1:{self.port}"

    def close(self) -> None:
        self._running = False
        try:
            self._server.close()
        except OSError:
            pass
        with self._data:
            self._data.notify_all()

    # -- server loops ------------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            while self._running:
                frame = wire.read_frame(lambda n: wire.recv_exact(conn, n))
                r = wire.Reader(frame)
                api_key = r.int16()
                api_version = r.int16()
                correlation_id = r.int32()
                r.string()  # client_id
                body = self._dispatch(api_key, api_version, r)
                resp = wire.int32(correlation_id) + body
                conn.sendall(wire.int32(len(resp)) + resp)
        except (ConnectionError, OSError, struct.error, wire.KafkaError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, api_key: int, api_version: int, r: wire.Reader) -> bytes:
        # record-batch-v2 era strictness: a real ≥0.11 broker does not
        # accept the legacy produce/fetch framings this repo used to speak
        if api_key == wire.PRODUCE and api_version < wire.PRODUCE_API_VERSION:
            return self._produce_error_response(r, wire.UNSUPPORTED_VERSION,
                                                legacy_version=api_version)
        if api_key == wire.FETCH and api_version < wire.FETCH_API_VERSION:
            return self._fetch_error_response_legacy(r)
        handler = {
            wire.PRODUCE: self._handle_produce,
            wire.FETCH: self._handle_fetch,
            wire.LIST_OFFSETS: self._handle_list_offsets,
            wire.METADATA: self._handle_metadata,
            wire.OFFSET_COMMIT: self._handle_offset_commit,
            wire.OFFSET_FETCH: self._handle_offset_fetch,
            wire.CREATE_TOPICS: self._handle_create_topics,
            wire.DELETE_TOPICS: self._handle_delete_topics,
        }.get(api_key)
        if handler is None:
            raise wire.KafkaError(-1, f"unsupported api {api_key}")
        return handler(r)

    def _produce_error_response(
        self, r: wire.Reader, code: int, legacy_version: int
    ) -> bytes:
        """UNSUPPORTED_VERSION for a legacy (v0-v2) produce, framed the
        way that client expects so it surfaces as a typed error, not a
        hang."""
        r.int16(), r.int32()  # acks, timeout (no transactional_id pre-v3)
        topics_out = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            parts_out = []
            for _ in range(r.int32()):
                partition = r.int32()
                r._take(r.int32())  # payload, ignored
                part = wire.int32(partition) + wire.int16(code) + wire.int64(-1)
                if legacy_version >= 2:
                    part += wire.int64(-1)
                parts_out.append(part)
            topics_out.append(wire.string(topic) + wire.array(parts_out))
        return wire.array(topics_out)

    def _fetch_error_response_legacy(self, r: wire.Reader) -> bytes:
        """UNSUPPORTED_VERSION per partition in v0 response shape."""
        r.int32(), r.int32(), r.int32()  # replica, max_wait, min_bytes
        topics_out = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            parts_out = []
            for _ in range(r.int32()):
                partition = r.int32()
                r.int64(), r.int32()  # offset, max_bytes
                parts_out.append(
                    wire.int32(partition)
                    + wire.int16(wire.UNSUPPORTED_VERSION)
                    + wire.int64(-1)
                    + wire.bytes_(b"")
                )
            topics_out.append(wire.string(topic) + wire.array(parts_out))
        return wire.array(topics_out)

    # -- api handlers --------------------------------------------------------------
    def _topic_exists_or_create(self, topic: str) -> bool:
        if topic in self._logs:
            return True
        if self.auto_create_topics:
            self._logs[topic] = []
            return True
        return False

    def _handle_produce(self, r: wire.Reader) -> bytes:
        """Produce v3: record-batch v2 payloads only; magic 0/1 →
        CORRUPT_MESSAGE (what a modern broker does)."""
        r.string()  # transactional_id
        r.int16()  # acks
        r.int32()  # timeout
        topics_out = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            parts_out = []
            for _ in range(r.int32()):
                partition = r.int32()
                record_set = r._take(r.int32())

                def part_resp(err: int, base: int) -> bytes:
                    return (
                        wire.int32(partition)
                        + wire.int16(err)
                        + wire.int64(base)
                        + wire.int64(-1)  # log append time (v2+)
                    )

                try:
                    entries = wire.decode_record_batches(record_set)
                except wire.KafkaError as exc:
                    parts_out.append(part_resp(
                        exc.code if exc.code > 0 else wire.CORRUPT_MESSAGE, -1
                    ))
                    continue
                with self._data:
                    if not self._topic_exists_or_create(topic):
                        parts_out.append(
                            part_resp(wire.UNKNOWN_TOPIC_OR_PARTITION, -1)
                        )
                        continue
                    log = self._logs[topic]
                    base = len(log)
                    for _, key, value, headers in entries:
                        log.append((key, value, headers))
                    self._data.notify_all()
                parts_out.append(part_resp(wire.NONE, base))
            topics_out.append(wire.string(topic) + wire.array(parts_out))
        return wire.array(topics_out)

    def _handle_fetch(self, r: wire.Reader) -> bytes:
        """Fetch v4: record-batch v2 record sets, v4 partition headers."""
        r.int32()  # replica_id
        max_wait_ms = r.int32()
        r.int32()  # min_bytes
        r.int32()  # max_bytes (response-wide, v3+)
        r.int8()  # isolation_level (v4+)
        requests = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                max_bytes = r.int32()
                requests.append((topic, partition, offset, max_bytes))

        def part_v4(partition: int, err: int, high: int, records: bytes) -> bytes:
            return (
                wire.int32(partition)
                + wire.int16(err)
                + wire.int64(high)
                + wire.int64(high)  # last stable offset
                + wire.array([])  # aborted transactions
                + wire.bytes_(records)
            )

        # long-poll: wait up to max_wait for any requested topic to grow
        deadline = max_wait_ms / 1000.0
        with self._data:
            if not any(
                offset < len(self._logs.get(topic, []))
                for topic, _, offset, _ in requests
            ):
                self._data.wait(deadline)

            topics_out = []
            for topic, partition, offset, max_bytes in requests:
                log = self._logs.get(topic)
                if log is None and not self._topic_exists_or_create(topic):
                    topics_out.append(
                        wire.string(topic)
                        + wire.array([
                            part_v4(partition, wire.UNKNOWN_TOPIC_OR_PARTITION, -1, b"")
                        ])
                    )
                    continue
                log = self._logs[topic]
                high = len(log)
                if offset > high:
                    topics_out.append(
                        wire.string(topic)
                        + wire.array([
                            part_v4(partition, wire.OFFSET_OUT_OF_RANGE, high, b"")
                        ])
                    )
                    continue
                entries, size = [], 0
                for idx in range(offset, high):
                    key, value, headers = log[idx]
                    size += 70 + len(key or b"") + len(value)
                    if entries and size > max_bytes:
                        break
                    entries.append((key, value, headers))
                records = (
                    wire.encode_record_batch(offset, entries) if entries else b""
                )
                topics_out.append(
                    wire.string(topic)
                    + wire.array([part_v4(partition, wire.NONE, high, records)])
                )
            return wire.int32(0) + wire.array(topics_out)  # throttle_time + topics

    def _handle_list_offsets(self, r: wire.Reader) -> bytes:
        r.int32()  # replica_id
        topics_out = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            parts_out = []
            for _ in range(r.int32()):
                partition = r.int32()
                timestamp = r.int64()
                r.int32()  # max offsets
                with self._lock:
                    log = self._logs.get(topic, [])
                    offset = 0 if timestamp == wire.EARLIEST_TIMESTAMP else len(log)
                parts_out.append(
                    wire.int32(partition)
                    + wire.int16(wire.NONE)
                    + wire.array([wire.int64(offset)])
                )
            topics_out.append(wire.string(topic) + wire.array(parts_out))
        return wire.array(topics_out)

    def _handle_metadata(self, r: wire.Reader) -> bytes:
        requested = [r.string() or "" for _ in range(r.int32())]
        with self._lock:
            names = requested or sorted(self._logs)
            topics_out = []
            for name in names:
                exists = name in self._logs
                err = wire.NONE if exists else wire.UNKNOWN_TOPIC_OR_PARTITION
                topics_out.append(
                    wire.int16(err)
                    + wire.string(name)
                    + wire.array([
                        wire.int16(wire.NONE)
                        + wire.int32(0)  # partition id
                        + wire.int32(0)  # leader: this node
                        + wire.array([wire.int32(0)])
                        + wire.array([wire.int32(0)])
                    ])
                )
        brokers = wire.array([
            wire.int32(0) + wire.string("127.0.0.1") + wire.int32(self.port)
        ])
        return brokers + wire.array(topics_out)

    def _handle_offset_commit(self, r: wire.Reader) -> bytes:
        group = r.string() or ""
        topics_out = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            parts_out = []
            for _ in range(r.int32()):
                partition = r.int32()
                offset = r.int64()
                r.string()  # metadata
                with self._lock:
                    self._group_offsets[(group, topic, partition)] = offset
                parts_out.append(wire.int32(partition) + wire.int16(wire.NONE))
            topics_out.append(wire.string(topic) + wire.array(parts_out))
        return wire.array(topics_out)

    def _handle_offset_fetch(self, r: wire.Reader) -> bytes:
        group = r.string() or ""
        topics_out = []
        for _ in range(r.int32()):
            topic = r.string() or ""
            parts_out = []
            for _ in range(r.int32()):
                partition = r.int32()
                with self._lock:
                    offset = self._group_offsets.get((group, topic, partition), -1)
                parts_out.append(
                    wire.int32(partition)
                    + wire.int64(offset)
                    + wire.string("")
                    + wire.int16(wire.NONE)
                )
            topics_out.append(wire.string(topic) + wire.array(parts_out))
        return wire.array(topics_out)

    def _handle_create_topics(self, r: wire.Reader) -> bytes:
        topics_out = []
        for _ in range(r.int32()):
            name = r.string() or ""
            r.int32()  # num_partitions (single-partition broker)
            r.int16()  # replication factor
            for _ in range(r.int32()):  # assignments
                r.int32()
                for _ in range(r.int32()):
                    r.int32()
            for _ in range(r.int32()):  # configs
                r.string(), r.string()
            with self._lock:
                err = wire.TOPIC_ALREADY_EXISTS if name in self._logs else wire.NONE
                self._logs.setdefault(name, [])
            topics_out.append(wire.string(name) + wire.int16(err))
        r.int32()  # timeout (trailing in v0 request — already consumed topics)
        return wire.array(topics_out)

    def _handle_delete_topics(self, r: wire.Reader) -> bytes:
        names = [r.string() or "" for _ in range(r.int32())]
        r.int32()  # timeout
        topics_out = []
        with self._lock:
            for name in names:
                err = (
                    wire.NONE
                    if self._logs.pop(name, None) is not None
                    else wire.UNKNOWN_TOPIC_OR_PARTITION
                )
                topics_out.append(wire.string(name) + wire.int16(err))
        return wire.array(topics_out)

    # -- test inspection -----------------------------------------------------------
    def log(self, topic: str) -> list[tuple[bytes | None, bytes, list]]:
        """[(key, value, headers)] appended to the topic."""
        with self._lock:
            return list(self._logs.get(topic, []))

    def committed(self, group: str, topic: str, partition: int = 0) -> int:
        with self._lock:
            return self._group_offsets.get((group, topic, partition), -1)


def start_kafka_broker(**kw: Any) -> MiniKafkaBroker:
    return MiniKafkaBroker(**kw)

"""In-process Mongo server: OP_MSG wire protocol over TCP, storage via
the embedded document store.

Pairs with datasource/document/mongo.py the way MiniMySQLServer pairs
with the MySQL dialect: the driver's tests exercise real frames end to
end, no external mongod. Commands covered: hello/isMaster, ping,
buildInfo, insert, find (+limit), update, delete, count, drop, create,
startTransaction-bearing ops, commitTransaction, abortTransaction,
endSessions.

BSON-only values (ObjectId, datetime, bytes) bridge to the JSON-backed
embedded store through MongoDB Extended-JSON shapes ($oid/$date/$binary),
so ids round-trip: insert an ObjectId, find it back as an ObjectId.
"""

from __future__ import annotations

import base64
import datetime as _dt
import socket
import struct
import threading
from typing import Any

from gofr_tpu.datasource.document.bson import (
    ObjectId,
    decode_document,
    encode_document,
)
from gofr_tpu.datasource.document.embedded import EmbeddedDocumentStore
from gofr_tpu.testutil.ports import get_free_port

OP_MSG = 2013


def to_jsonable(value: Any) -> Any:
    if isinstance(value, ObjectId):
        return {"$oid": str(value)}
    if isinstance(value, _dt.datetime):
        return {"$date": int(value.timestamp() * 1000)}
    if isinstance(value, (bytes, bytearray)):
        return {"$binary": base64.b64encode(bytes(value)).decode()}
    if isinstance(value, dict):
        return {k: to_jsonable(v) for k, v in value.items()}
    if isinstance(value, list):
        return [to_jsonable(v) for v in value]
    return value


def from_jsonable(value: Any) -> Any:
    if isinstance(value, dict):
        if set(value) == {"$oid"}:
            return ObjectId(value["$oid"])
        if set(value) == {"$date"}:
            return _dt.datetime.fromtimestamp(
                value["$date"] / 1000, _dt.timezone.utc
            )
        if set(value) == {"$binary"}:
            return base64.b64decode(value["$binary"])
        return {k: from_jsonable(v) for k, v in value.items()}
    if isinstance(value, list):
        return [from_jsonable(v) for v in value]
    return value


class _Conn:
    def __init__(self, server: "MiniMongoServer", sock: socket.socket) -> None:
        self.server = server
        self.sock = sock
        self.rbuf = b""
        # lsid bytes → open embedded-store session (transaction scope)
        self.sessions: dict[bytes, Any] = {}
        # cursor id → undelivered docs (find batches cap at 101 like a
        # real server, so drivers must implement getMore to pass)
        self.cursors: dict[int, list] = {}
        self._next_cursor = 1

    def _recv_exact(self, n: int) -> bytes | None:
        while len(self.rbuf) < n:
            try:
                chunk = self.sock.recv(65536)
            except OSError:
                return None
            if not chunk:
                return None
            self.rbuf += chunk
        out, self.rbuf = self.rbuf[:n], self.rbuf[n:]
        return out

    def serve(self) -> None:
        try:
            while True:
                head = self._recv_exact(16)
                if head is None:
                    return
                length, req_id, _, opcode = struct.unpack("<iiii", head)
                body = self._recv_exact(length - 16)
                if body is None or opcode != OP_MSG:
                    return
                cmd, _ = decode_document(body, 5)  # flagBits + kind byte
                try:
                    reply = self.handle(cmd)
                except Exception as exc:  # noqa: BLE001 - surfaces as errmsg
                    reply = {"ok": 0.0, "errmsg": str(exc), "code": 8}
                payload = (
                    struct.pack("<I", 0) + b"\x00" + encode_document(reply)
                )
                header = struct.pack(
                    "<iiii", 16 + len(payload),
                    self.server._next_id(), req_id, OP_MSG,
                )
                self.sock.sendall(header + payload)
        finally:
            for sess in self.sessions.values():
                try:
                    sess.abort_transaction()
                except Exception:
                    pass
            self.sock.close()

    # -- command dispatch ------------------------------------------------------
    def _target(self, cmd: dict) -> Any:
        """The store or, inside a wire transaction, its session."""
        lsid = cmd.get("lsid")
        if not isinstance(lsid, dict) or "id" not in lsid:
            return self.server.store
        key = bytes(lsid["id"])
        if cmd.get("startTransaction"):
            sess = self.server.store.start_session().start_transaction()
            self.sessions[key] = sess
            return sess
        return self.sessions.get(key, self.server.store)

    def _end_txn(self, cmd: dict, commit: bool) -> dict:
        lsid = cmd.get("lsid") or {}
        key = bytes(lsid.get("id", b""))
        sess = self.sessions.pop(key, None)
        if sess is None:
            raise RuntimeError("no transaction in progress for this session")
        if commit:
            sess.commit_transaction()
        else:
            sess.abort_transaction()
        return {"ok": 1.0}

    def handle(self, cmd: dict) -> dict:
        name = next(iter(cmd))
        db = cmd.get("$db", "test")
        if name in ("hello", "isMaster", "ismaster"):
            return {
                "ok": 1.0, "isWritablePrimary": True,
                "maxWireVersion": 17, "minWireVersion": 0,
            }
        if name == "ping":
            return {"ok": 1.0}
        if name == "buildInfo":
            return {"ok": 1.0, "version": "7.0.0-mini"}
        if name == "endSessions":
            return {"ok": 1.0}
        if name == "commitTransaction":
            return self._end_txn(cmd, commit=True)
        if name == "abortTransaction":
            return self._end_txn(cmd, commit=False)

        store = self._target(cmd)
        coll = cmd[name]
        if name == "insert":
            docs = [to_jsonable(d) for d in cmd["documents"]]
            for d in docs:
                store.insert_one(coll, d)
            return {"ok": 1.0, "n": len(docs)}
        if name == "find":
            hits = store.find(coll, to_jsonable(cmd.get("filter") or {}))
            limit = int(cmd.get("limit", 0) or 0)
            if limit:
                hits = hits[:limit]
            docs = [from_jsonable(h) for h in hits]
            cursor_id = 0
            if len(docs) > 101 and not cmd.get("singleBatch"):
                cursor_id = self._next_cursor
                self._next_cursor += 1
                self.cursors[cursor_id] = docs[101:]
                docs = docs[:101]
            return {
                "ok": 1.0,
                "cursor": {
                    "id": cursor_id,
                    "ns": f"{db}.{coll}",
                    "firstBatch": docs,
                },
            }
        if name == "getMore":
            rest = self.cursors.pop(int(cmd["getMore"]), [])
            ns = f"{db}.{cmd.get('collection', '')}"
            return {
                "ok": 1.0,
                "cursor": {"id": 0, "ns": ns, "nextBatch": rest},
            }
        if name == "count":
            n = store.count_documents(coll, to_jsonable(cmd.get("query") or {}))
            return {"ok": 1.0, "n": n}
        if name == "update":
            modified = 0
            for spec in cmd["updates"]:
                q = to_jsonable(spec.get("q") or {})
                u = to_jsonable(spec.get("u") or {})
                if spec.get("multi"):
                    modified += store.update_many(coll, q, u)
                else:
                    modified += store.update_one(coll, q, u)
            return {"ok": 1.0, "n": modified, "nModified": modified}
        if name == "delete":
            n = 0
            for spec in cmd["deletes"]:
                q = to_jsonable(spec.get("q") or {})
                if int(spec.get("limit", 0)) == 1:
                    n += store.delete_one(coll, q)
                else:
                    n += store.delete_many(coll, q)
            return {"ok": 1.0, "n": n}
        if name == "drop":
            store.drop(coll)
            return {"ok": 1.0}
        if name == "create":
            # the embedded store creates tables lazily; touching it is enough
            store.count_documents(coll, {})
            return {"ok": 1.0}
        raise RuntimeError(f"unsupported command {name!r}")


class MiniMongoServer:
    def __init__(self, port: int = 0) -> None:
        self.port = port or get_free_port()
        self.store = EmbeddedDocumentStore(":memory:")
        self._listener: socket.socket | None = None
        self._threads: list[threading.Thread] = []
        self._id_lock = threading.Lock()
        self._ids = 0
        self._closed = False

    def _next_id(self) -> int:
        with self._id_lock:
            self._ids += 1
            return self._ids

    def start(self) -> "MiniMongoServer":
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", self.port))
        self._listener.listen(16)
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def _accept_loop(self) -> None:
        assert self._listener is not None
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            t = threading.Thread(
                target=_Conn(self, sock).serve, daemon=True
            )
            t.start()
            self._threads.append(t)

    def close(self) -> None:
        self._closed = True
        if self._listener is not None:
            try:
                self._listener.close()
            finally:
                self._listener = None
        self.store.close()

"""Framework version (reference: pkg/gofr/version/version.go:3)."""

FRAMEWORK = "dev"

"""Byte-level BPE tokenizer reading the HF ``tokenizer.json`` format.

Own implementation (no ``tokenizers`` dependency in the product path):
parses vocab + merges, applies the file's pre-tokenization regex, and
round-trips text through the GPT-2 byte↔unicode table. Llama-3, GPT-2,
and Qwen-family assets all load through this. The installed ``tokenizers``
wheel is used in tests as the conformance oracle only.
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any

import regex as _regex

# GPT-2 pre-tokenization pattern — the default when the asset doesn't
# carry its own Split pattern.
GPT2_PATTERN = (
    r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
)


def bytes_to_unicode() -> dict[int, str]:
    """The GPT-2 printable-byte table: maps every byte 0..255 to a unicode
    char such that 'visible' bytes map to themselves."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, (chr(c) for c in cs)))


_BYTE_ENCODER = bytes_to_unicode()
_BYTE_DECODER = {c: b for b, c in _BYTE_ENCODER.items()}


def _find_pattern(pre_tokenizer: dict | None) -> str:
    """Extract the Split regex from a (possibly nested) pre_tokenizer."""
    if not pre_tokenizer:
        return GPT2_PATTERN
    kind = pre_tokenizer.get("type")
    if kind == "Split":
        pat = pre_tokenizer.get("pattern", {})
        return pat.get("Regex") or pat.get("String") or GPT2_PATTERN
    if kind == "Sequence":
        for sub in pre_tokenizer.get("pretokenizers", []):
            if sub.get("type") == "Split":
                return _find_pattern(sub)
    return GPT2_PATTERN


class BPETokenizer:
    """Serving-engine Tokenizer (serving/tokenizer.py Protocol)."""

    def __init__(
        self,
        vocab: dict[str, int],
        merges: list[tuple[str, str]],
        *,
        pattern: str = GPT2_PATTERN,
        special_tokens: dict[str, int] | None = None,
        bos_token: str | None = None,
        eos_token: str | None = None,
        pad_token: str | None = None,
        add_bos: bool = False,
    ) -> None:
        self.vocab = vocab
        self.ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.pattern = _regex.compile(pattern)
        self.special_tokens = dict(special_tokens or {})
        self.id_to_token = {i: t for t, i in vocab.items()}
        for tok, i in self.special_tokens.items():
            self.id_to_token.setdefault(i, tok)
        self.vocab_size = max(self.id_to_token, default=-1) + 1
        self.add_bos = add_bos

        def _sid(token: str | None, *fallbacks: str) -> int | None:
            for cand in (token, *fallbacks):
                if cand is not None:
                    i = self.special_tokens.get(cand)
                    if i is None:
                        i = self.vocab.get(cand)
                    if i is not None:
                        return i
            return None

        def _by_pattern(pat: str) -> int | None:
            rx = _regex.compile(pat)
            for tok, i in sorted(self.special_tokens.items(), key=lambda kv: kv[1]):
                if rx.search(tok):
                    return i
            return None

        bos = _sid(bos_token, "<|begin_of_text|>", "<s>", "<|endoftext|>")
        eos = _sid(eos_token, "<|end_of_text|>", "</s>", "<|endoftext|>")
        pad = _sid(pad_token, "<pad>")
        if bos is None:
            bos = _by_pattern(r"(?i)bos|begin")
        if eos is None:
            eos = _by_pattern(r"(?i)eos|end|im_end")
        # eos = -1 is the never-stop sentinel: no real vocab id may double
        # as a stop token (defaulting to 0 would make the engine stop on a
        # legitimate token). pad at 0 is only used to fill masked positions.
        self.bos_id = bos if bos is not None else 0
        self.eos_id = eos if eos is not None else -1
        self.pad_id = pad if pad is not None else (self.eos_id if self.eos_id >= 0 else 0)
        self._special_ids = frozenset(self.special_tokens.values())
        self._cache: dict[str, list[int]] = {}
        self._cache_lock = threading.Lock()
        if self.special_tokens:
            # one alternation that splits text on special-token literals
            alts = "|".join(
                _regex.escape(t)
                for t in sorted(self.special_tokens, key=len, reverse=True)
            )
            self._special_re = _regex.compile(f"({alts})")
        else:
            self._special_re = None

    # ------------------------------------------------------------ loading
    @classmethod
    def from_file(cls, path: str) -> "BPETokenizer":
        with open(path) as f:
            spec = json.load(f)
        tok_cfg = None
        cfg_path = os.path.join(os.path.dirname(path), "tokenizer_config.json")
        if os.path.exists(cfg_path):
            with open(cfg_path) as f:
                tok_cfg = json.load(f)
        return cls.from_spec(spec, tok_cfg)

    @classmethod
    def from_spec(
        cls, spec: dict, tokenizer_config: dict | None = None
    ) -> "BPETokenizer":
        """Build from a parsed tokenizer.json dict (and optionally the
        sibling tokenizer_config.json dict naming bos/eos/pad)."""
        model = spec.get("model", {})
        if model.get("type") not in (None, "BPE"):
            raise ValueError(f"unsupported tokenizer model type {model.get('type')}")
        vocab = model.get("vocab", {})
        merges_raw = model.get("merges", [])
        merges: list[tuple[str, str]] = []
        for m in merges_raw:
            if isinstance(m, str):
                a, _, b = m.partition(" ")
                merges.append((a, b))
            else:
                merges.append((m[0], m[1]))
        special = {
            t["content"]: t["id"]
            for t in spec.get("added_tokens", [])
            if t.get("special")
        }
        pattern = _find_pattern(spec.get("pre_tokenizer"))
        # tokenizer_config.json names the bos/eos tokens
        bos = eos = pad = None
        if tokenizer_config:

            def _name(v: Any) -> str | None:
                return v.get("content") if isinstance(v, dict) else v

            bos = _name(tokenizer_config.get("bos_token"))
            eos = _name(tokenizer_config.get("eos_token"))
            pad = _name(tokenizer_config.get("pad_token"))
        return cls(
            vocab,
            merges,
            pattern=pattern,
            special_tokens=special,
            bos_token=bos,
            eos_token=eos,
            pad_token=pad,
        )

    # ------------------------------------------------------------ encoding
    def _bpe_word(self, word: str) -> list[int]:
        """Merge loop for one pre-tokenized word (already byte-mapped)."""
        with self._cache_lock:
            cached = self._cache.get(word)
        if cached is not None:
            return cached
        parts = list(word)
        while len(parts) > 1:
            best_rank = None
            best_i = -1
            for i in range(len(parts) - 1):
                rank = self.ranks.get((parts[i], parts[i + 1]))
                if rank is not None and (best_rank is None or rank < best_rank):
                    best_rank, best_i = rank, i
            if best_rank is None:
                break
            parts[best_i : best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        ids = []
        for p in parts:
            i = self.vocab.get(p)
            if i is None:  # unmergeable byte with no vocab entry
                ids.extend(self.vocab.get(ch, 0) for ch in p)
            else:
                ids.append(i)
        with self._cache_lock:
            if len(self._cache) > 65536:
                self._cache.clear()
            self._cache[word] = ids
        return ids

    def _encode_plain(self, text: str) -> list[int]:
        out: list[int] = []
        for word in self.pattern.findall(text):
            mapped = "".join(_BYTE_ENCODER[b] for b in word.encode("utf-8"))
            out.extend(self._bpe_word(mapped))
        return out

    def encode(self, text: str, *, add_bos: bool | None = None) -> list[int]:
        out: list[int] = []
        if add_bos if add_bos is not None else self.add_bos:
            out.append(self.bos_id)
        if self._special_re is None:
            out.extend(self._encode_plain(text))
            return out
        for chunk in self._special_re.split(text):
            if not chunk:
                continue
            sid = self.special_tokens.get(chunk)
            if sid is not None:
                out.append(sid)
            else:
                out.extend(self._encode_plain(chunk))
        return out

    # ------------------------------------------------------------ decoding
    def decode(self, ids: list[int]) -> str:
        data = bytearray()
        for i in ids:
            i = int(i)
            tok = self.id_to_token.get(i)
            if tok is None or i in self._special_ids:
                continue
            for ch in tok:
                b = _BYTE_DECODER.get(ch)
                if b is not None:
                    data.append(b)
                else:  # non-byte-level token (added non-special)
                    data.extend(ch.encode("utf-8"))
        return data.decode("utf-8", "replace")

"""Real-model tokenizers, dependency-free.

The serving engine's tokenizer boundary (serving/tokenizer.py Protocol)
accepts any encode/decode implementation; this package provides the two
families real Llama checkpoints ship with:

- ``BPETokenizer`` (bpe.py) — byte-level BPE parsing the HF
  ``tokenizer.json`` format (Llama-3 / GPT-2 lineage).
- ``SentencePieceTokenizer`` (spm.py) — unigram Viterbi over a
  SentencePiece ``.model`` protobuf (Llama-2 lineage), parsed with a
  built-in wire-format reader.

Reference parity: the reference loads external assets through its file
datasource abstraction (/root/reference/pkg/gofr/datasource/file/
interface.go:48-61); tokenizer assets load through plain paths here and
through the object-store datasource once mounted.
"""

from __future__ import annotations

import os

from gofr_tpu.tokenizer.bpe import BPETokenizer
from gofr_tpu.tokenizer.spm import SentencePieceTokenizer

__all__ = ["BPETokenizer", "SentencePieceTokenizer", "load_tokenizer"]


def load_tokenizer(path: str, fs=None):
    """Auto-detect a tokenizer asset: a ``tokenizer.json`` (HF byte-level
    BPE) file or directory containing one, or a SentencePiece ``.model``
    file (or directory containing ``tokenizer.model``). ``fs``: optional
    file datasource (``open``/``exists``) so assets load from object
    stores the same way weights do."""
    import json

    if fs is not None:
        exists = getattr(fs, "exists", None)

        def _read(p: str) -> bytes | None:
            if exists is not None and not exists(p):
                return None
            try:
                with fs.open(p, "rb") as f:
                    return f.read()
            except (FileNotFoundError, OSError):
                return None

        candidates = (
            [path]
            if path.endswith((".json", ".model"))
            else [os.path.join(path, n) for n in ("tokenizer.json", "tokenizer.model")]
        )
        for candidate in candidates:
            data = _read(candidate)
            if data is None:
                continue
            if candidate.endswith(".json"):
                cfg_raw = _read(
                    os.path.join(os.path.dirname(candidate), "tokenizer_config.json")
                )
                tok_cfg = json.loads(cfg_raw) if cfg_raw else None
                return BPETokenizer.from_spec(json.loads(data), tok_cfg)
            return SentencePieceTokenizer.from_bytes(data)
        raise FileNotFoundError(
            f"no tokenizer.json or tokenizer.model under {path}"
        )

    if os.path.isdir(path):
        for name in ("tokenizer.json", "tokenizer.model"):
            candidate = os.path.join(path, name)
            if os.path.exists(candidate):
                path = candidate
                break
        else:
            raise FileNotFoundError(
                f"no tokenizer.json or tokenizer.model under {path}"
            )
    if path.endswith(".json"):
        return BPETokenizer.from_file(path)
    return SentencePieceTokenizer.from_file(path)

"""SentencePiece tokenizer: own ``.model`` protobuf parser + encoder.

Llama-2/Mistral-family checkpoints ship a SentencePiece ``tokenizer.model``
(a serialized ``ModelProto``). The image has no ``sentencepiece`` wheel, so
this module reads the protobuf wire format directly (varint field walker —
no generated code) and implements both encode algorithms SP models use:

- **unigram**: Viterbi segmentation maximizing the sum of piece log-probs;
- **BPE**: greedy merge of the adjacent pair whose concatenation has the
  highest piece score (scores encode merge rank) — the Llama-2 model type.

Whitespace is escaped to ▁ (U+2581) with the standard dummy-prefix rule;
characters outside the vocab fall back to ``<0xNN>`` byte pieces when the
model carries them, else the unk id.
"""

from __future__ import annotations

import struct

_SPACE = "▁"  # ▁

# piece types (sentencepiece.ModelProto.SentencePiece.Type)
NORMAL, UNKNOWN, CONTROL, USER_DEFINED, UNUSED, BYTE = 1, 2, 3, 4, 5, 6


def _walk(buf: bytes, pos: int, end: int):
    """Yield (field_no, wire_type, value, new_pos) over a message body."""
    while pos < end:
        tag, pos = _varint(buf, pos)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            val, pos = _varint(buf, pos)
        elif wire == 1:
            val, pos = buf[pos : pos + 8], pos + 8
        elif wire == 2:
            ln, pos = _varint(buf, pos)
            val, pos = buf[pos : pos + ln], pos + ln
        elif wire == 5:
            val, pos = buf[pos : pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, val, pos


def _signed(v: int) -> int:
    """Interpret a decoded varint as a two's-complement int64 (proto
    int32/-1 encodes as ten 0xFF-heavy bytes)."""
    return v - (1 << 64) if v >= (1 << 63) else v


def _varint(buf: bytes, pos: int) -> tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


class SentencePieceTokenizer:
    def __init__(
        self,
        pieces: list[tuple[str, float, int]],  # (piece, score, type)
        *,
        model_type: int = 1,  # 1=unigram, 2=BPE
        unk_id: int = 0,
        bos_id: int = 1,
        eos_id: int = 2,
        pad_id: int = -1,
        add_dummy_prefix: bool = True,
        add_bos: bool = False,
    ) -> None:
        self.pieces = pieces
        self.model_type = model_type
        self.unk_id = unk_id
        self.bos_id = bos_id
        self.eos_id = eos_id
        self.pad_id = pad_id if pad_id >= 0 else 0
        self.add_dummy_prefix = add_dummy_prefix
        self.add_bos = add_bos
        self.vocab_size = len(pieces)
        self.piece_to_id = {p: i for i, (p, _, _) in enumerate(pieces)}
        self.scores = {p: s for p, s, _ in pieces}
        self._max_piece_len = max((len(p) for p, _, t in pieces), default=1)
        self._byte_ids = {}
        for i, (p, _, t) in enumerate(pieces):
            if t == BYTE and len(p) == 6 and p.startswith("<0x"):
                self._byte_ids[int(p[3:5], 16)] = i
        self._control_ids = {i for i, (_, _, t) in enumerate(pieces) if t == CONTROL}
        self._rev_bytes = {i: b for b, i in self._byte_ids.items()}

    # ------------------------------------------------------------ loading
    @classmethod
    def from_file(cls, path: str) -> "SentencePieceTokenizer":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    @classmethod
    def from_bytes(cls, data: bytes) -> "SentencePieceTokenizer":
        pieces: list[tuple[str, float, int]] = []
        model_type, unk_id, bos_id, eos_id, pad_id = 1, 0, 1, 2, -1
        add_dummy_prefix = True
        for field, wire, val, _ in _walk(data, 0, len(data)):
            if field == 1 and wire == 2:  # repeated SentencePiece
                piece, score, ptype = "", 0.0, NORMAL
                for f2, w2, v2, _ in _walk(val, 0, len(val)):
                    if f2 == 1:
                        piece = v2.decode("utf-8")
                    elif f2 == 2:
                        score = struct.unpack("<f", v2)[0]
                    elif f2 == 3:
                        ptype = v2
                pieces.append((piece, score, ptype))
            elif field == 2 and wire == 2:  # TrainerSpec
                for f2, w2, v2, _ in _walk(val, 0, len(val)):
                    if f2 == 3:
                        model_type = v2
                    elif f2 == 40:
                        unk_id = v2
                    elif f2 == 41:
                        bos_id = _signed(v2)
                    elif f2 == 42:
                        eos_id = _signed(v2)
                    elif f2 == 43:
                        pad_id = _signed(v2)
            elif field == 3 and wire == 2:  # NormalizerSpec
                for f2, w2, v2, _ in _walk(val, 0, len(val)):
                    if f2 == 3:
                        add_dummy_prefix = bool(v2)
        return cls(
            pieces,
            model_type=model_type,
            unk_id=unk_id,
            bos_id=max(bos_id, 0),
            eos_id=max(eos_id, 0),
            pad_id=pad_id,
            add_dummy_prefix=add_dummy_prefix,
        )

    # ------------------------------------------------------------ encoding
    def _normalize(self, text: str) -> str:
        text = text.replace(" ", _SPACE)
        if self.add_dummy_prefix and not text.startswith(_SPACE):
            text = _SPACE + text
        return text

    def _char_fallback(self, ch: str) -> list[int]:
        if self._byte_ids:
            return [
                self._byte_ids.get(b, self.unk_id) for b in ch.encode("utf-8")
            ]
        return [self.unk_id]

    def _encode_unigram(self, text: str) -> list[int]:
        """Viterbi: best[i] = max-score segmentation of text[:i]."""
        n = len(text)
        NEG = float("-inf")
        best = [NEG] * (n + 1)
        back: list[tuple[int, int] | None] = [None] * (n + 1)  # (start, id)
        best[0] = 0.0
        unk_penalty = min(self.scores.values(), default=0.0) - 10.0
        for end in range(1, n + 1):
            for start in range(max(0, end - self._max_piece_len), end):
                if best[start] == NEG:
                    continue
                piece = text[start:end]
                pid = self.piece_to_id.get(piece)
                if pid is not None and pid not in self._control_ids:
                    s = best[start] + self.scores[piece]
                    if s > best[end]:
                        best[end], back[end] = s, (start, pid)
            if best[end] == NEG:  # unknown char: byte-fallback or unk
                start = end - 1
                if best[start] > NEG:
                    best[end] = best[start] + unk_penalty
                    back[end] = (start, -1)
        ids: list[int] = []
        pos = n
        while pos > 0:
            start, pid = back[pos]
            if pid == -1:
                ids[:0] = self._char_fallback(text[start:pos])
            else:
                ids.insert(0, pid)
            pos = start
        return ids

    def _encode_bpe(self, text: str) -> list[int]:
        """SP-BPE: repeatedly merge the adjacent pair whose concatenation
        is a piece with the highest score."""
        parts = list(text)
        while len(parts) > 1:
            best_score, best_i = None, -1
            for i in range(len(parts) - 1):
                cand = parts[i] + parts[i + 1]
                s = self.scores.get(cand)
                if s is not None and (best_score is None or s > best_score):
                    best_score, best_i = s, i
            if best_score is None:
                break
            parts[best_i : best_i + 2] = [parts[best_i] + parts[best_i + 1]]
        ids: list[int] = []
        for p in parts:
            pid = self.piece_to_id.get(p)
            if pid is None or pid in self._control_ids:
                ids.extend(self._char_fallback(p))
            else:
                ids.append(pid)
        return ids

    def encode(self, text: str, *, add_bos: bool | None = None) -> list[int]:
        text = self._normalize(text)
        if self.model_type == 2:
            ids = self._encode_bpe(text)
        else:
            ids = self._encode_unigram(text)
        if add_bos if add_bos is not None else self.add_bos:
            ids.insert(0, self.bos_id)
        return ids

    # ------------------------------------------------------------ decoding
    def decode(self, ids: list[int]) -> str:
        out: list[str] = []
        byte_run = bytearray()

        def flush() -> None:
            if byte_run:
                out.append(byte_run.decode("utf-8", "replace"))
                byte_run.clear()

        for i in ids:
            i = int(i)
            if i in self._rev_bytes:
                byte_run.append(self._rev_bytes[i])
                continue
            flush()
            if i in self._control_ids or not (0 <= i < len(self.pieces)):
                continue
            out.append(self.pieces[i][0])
        flush()
        text = "".join(out).replace(_SPACE, " ")
        if self.add_dummy_prefix:
            text = text.removeprefix(" ")  # undo the encode-side dummy prefix
        return text

"""Compatibility shims across the jax versions the deployment images span.

The code targets the modern ``jax.shard_map`` API. Older images (< 0.5)
only ship ``jax.experimental.shard_map.shard_map`` with the pre-rename
keywords, so this module maps the new surface onto it:

- ``axis_names={...}`` (the MANUAL axes) becomes ``auto = mesh axes -
  axis_names`` (everything not manual);
- ``check_vma=`` is the renamed ``check_rep=``.

Import ``shard_map`` from here instead of ``jax`` anywhere the code must
run on both families.
"""

from __future__ import annotations

from typing import Any

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f: Any = None, **kw: Any) -> Any:
        axis_names = kw.pop("axis_names", None)
        if axis_names is not None:
            mesh = kw.get("mesh")
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
            if auto:
                kw["auto"] = auto
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        if f is None:  # decorator form: jax.shard_map(mesh=..., ...)(fn)
            return lambda g: _experimental_shard_map(g, **kw)
        return _experimental_shard_map(f, **kw)


if hasattr(jax.lax, "pcast"):
    pcast = jax.lax.pcast
else:
    def pcast(x: Any, axes: Any, *, to: str | None = None) -> Any:
        """Identity on jax < 0.8: the old shard_map has no
        varying/replicated aval typing, so there is nothing to cast."""
        return x


try:
    from jax.experimental.pallas import tpu as _pltpu
except ImportError:  # image without pallas
    _pltpu = None

# jax renamed pltpu.TPUCompilerParams -> pltpu.CompilerParams
if _pltpu is None:
    PallasTPUCompilerParams = None
elif hasattr(_pltpu, "CompilerParams"):
    PallasTPUCompilerParams = _pltpu.CompilerParams
else:
    PallasTPUCompilerParams = _pltpu.TPUCompilerParams

__all__ = ["PallasTPUCompilerParams", "pcast", "shard_map"]

"""CRUD auto-handlers.

Reference parity: pkg/gofr/crud_handlers.go — ``add_rest_handlers(app,
Entity)`` scans a dataclass (scanEntity :67-113: first field is the primary
key, field names become column names), registers POST/GET/GET-id/PUT/DELETE
routes with SQL-backed default implementations (:151-333, via the query
builders in datasource/sql/query_builder.py), each overridable by defining
``create/get_all/get_by_id/update/delete`` methods on the entity class
(:116-149 interface checks).
"""

from __future__ import annotations

import dataclasses
import typing
from typing import Any

from gofr_tpu.datasource.sql import query_builder as qb
from gofr_tpu.http.errors import ErrorEntityNotFound, ErrorInvalidParam


@dataclasses.dataclass
class _EntityMeta:
    name: str
    cls: type
    fields: list[str]
    primary_key: str
    hints: dict[str, Any]


def scan_entity(cls: type) -> _EntityMeta:
    """crud_handlers.go:67-113."""
    if not dataclasses.is_dataclass(cls):
        raise TypeError("add_rest_handlers requires a dataclass entity")
    fields = [f.name for f in dataclasses.fields(cls)]
    if not fields:
        raise TypeError("entity must have at least one field (primary key)")
    return _EntityMeta(
        name=cls.__name__.lower(),
        cls=cls,
        fields=fields,
        primary_key=fields[0],
        hints=typing.get_type_hints(cls),
    )


def _coerce_id(meta: _EntityMeta, raw: str) -> Any:
    hint = meta.hints.get(meta.primary_key, str)
    try:
        return hint(raw) if hint in (int, float) else raw
    except ValueError:
        raise ErrorInvalidParam(meta.primary_key)


def add_rest_handlers(app: Any, cls: type, table: str | None = None) -> None:
    meta = scan_entity(cls)
    table = table or meta.name
    route = f"/{meta.name}"

    def handler_or_default(name: str, default: Any) -> Any:
        custom = getattr(cls, name, None)
        return custom if callable(custom) and not dataclasses.is_dataclass(custom) else default

    def create(ctx: Any) -> Any:
        entity = ctx.bind(cls)
        values = [getattr(entity, f) for f in meta.fields]
        ctx.sql.exec(qb.insert_query(table, meta.fields), *values)
        return f"{cls.__name__} successfully created with id: {getattr(entity, meta.primary_key)}"

    def get_all(ctx: Any) -> Any:
        return ctx.sql.select(cls, qb.select_all_query(table))

    def get_by_id(ctx: Any) -> Any:
        entity_id = _coerce_id(meta, ctx.path_param("id"))
        rows = ctx.sql.select(cls, qb.select_by_id_query(table, meta.primary_key), entity_id)
        if not rows:
            raise ErrorEntityNotFound(meta.primary_key, str(entity_id))
        return rows[0]

    def update(ctx: Any) -> Any:
        entity_id = _coerce_id(meta, ctx.path_param("id"))
        entity = ctx.bind(cls)
        values = [getattr(entity, f) for f in meta.fields if f != meta.primary_key]
        cursor = ctx.sql.exec(
            qb.update_by_id_query(table, meta.fields, meta.primary_key), *values, entity_id
        )
        if getattr(cursor, "rowcount", 1) == 0:
            raise ErrorEntityNotFound(meta.primary_key, str(entity_id))
        return f"{cls.__name__} successfully updated with id: {entity_id}"

    def delete(ctx: Any) -> Any:
        entity_id = _coerce_id(meta, ctx.path_param("id"))
        cursor = ctx.sql.exec(qb.delete_by_id_query(table, meta.primary_key), entity_id)
        if getattr(cursor, "rowcount", 1) == 0:
            raise ErrorEntityNotFound(meta.primary_key, str(entity_id))
        return f"{cls.__name__} successfully deleted with id: {entity_id}"

    app.post(route, handler_or_default("create", create))
    app.get(route, handler_or_default("get_all", get_all))
    app.get(route + "/{id}", handler_or_default("get_by_id", get_by_id))
    if len(meta.fields) > 1 or getattr(cls, "update", None) is not None:
        # a PK-only entity has nothing to update; the default UPDATE would
        # be a syntax error (empty SET clause)
        app.put(route + "/{id}", handler_or_default("update", update))
    app.delete(route + "/{id}", handler_or_default("delete", delete))

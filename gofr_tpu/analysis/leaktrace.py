"""Runtime reclaim tracer: the dynamic twin of leakcheck's static table.

``make chaos`` asserts the reclaim invariant end-to-end (every request
terminal, slots + pages freed, zero leaked spans) — but only through the
counters each subsystem happens to expose. This shim instruments the
acquire/release surfaces of the serving control plane directly while
installed:

- ``BlockAllocator`` / ``Scheduler`` construction → ``close()`` /
  ``leak()`` (the ``native-wrapper`` kind);
- ``BlockAllocator.alloc`` → ``free`` (``kv-seq``);
- ``PagedKVCache.alloc_slot``/``try_reserve_slot``/``try_reserve_chunk``
  → ``free_slot`` (``kv-slot``);
- ``TimelineRecorder.begin`` → ``finish`` (``timeline``).

Every observed event is recorded as ``(kind, acquire|release, name)``
and every live resource is tracked by identity, so two invariants become
directly assertable on a REAL engine workload (tests/test_leakcheck.py):

1. **Balance** — after stop/drain, no live resource remains
   (:meth:`LeakTraceMonitor.check`): the dynamic reclaim audit.
2. **Coverage** — every runtime-observed acquire/release site is in
   leakcheck's static resource table
   (:func:`gofr_tpu.analysis.leakcheck.check_coverage`): the analyzer
   has no blind spot for a resource the runtime actually cycles. A
   ``leak()`` release is matched through the table's transfer-annotated
   methods — a declared quarantine leak IS a documented disposition.

Usage (the chaos tier exports its observed pairs when
``GOFR_LEAK_EXPORT`` names a file — see tests/test_chaos.py):

    mon = leaktrace.install()
    try:
        ...  # real engine workload
    finally:
        leaktrace.uninstall()
    mon.check()                      # raises LeakTraceError on a leak
    leaktrace.export_to(mon, path)   # merge-write the observed pairs
"""

from __future__ import annotations

import json
import threading
from typing import Any

__all__ = [
    "LeakTraceError", "LeakTraceMonitor", "install", "uninstall",
    "export_to",
]


class LeakTraceError(AssertionError):
    pass


class LeakTraceMonitor:
    """Observed acquire/release events + the live-resource ledger."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # unique observed (kind, op, name) triples — the coverage set
        self._events: set[tuple[str, str, str]] = set()
        # (kind, identity-key) -> acquire name — the balance ledger
        self._live: dict[tuple[str, Any], str] = {}

    def on_acquire(self, kind: str, name: str, key: Any) -> None:
        with self._mu:
            self._events.add((kind, "acquire", name))
            # re-acquire on a live key (try_reserve_slot growing an
            # already-allocated slot) keeps the original acquisition
            self._live.setdefault((kind, key), name)

    def on_release(self, kind: str, name: str, key: Any) -> None:
        with self._mu:
            self._events.add((kind, "release", name))
            self._live.pop((kind, key), None)

    def events(self) -> list[dict[str, str]]:
        with self._mu:
            return [
                {"kind": k, "op": op, "name": n}
                for k, op, n in sorted(self._events)
            ]

    def unreclaimed(self) -> list[str]:
        with self._mu:
            return sorted(
                f"{kind} acquired via {name} (key {key!r}) never released"
                for (kind, key), name in self._live.items()
            )

    def export(self) -> dict:
        return {
            "version": 1,
            "events": self.events(),
            "unreclaimed": self.unreclaimed(),
        }

    def check(self) -> None:
        leaked = self.unreclaimed()
        if leaked:
            raise LeakTraceError(
                "leaktrace: resources acquired but never released "
                f"({len(leaked)}):\n  " + "\n  ".join(leaked)
            )


_active: LeakTraceMonitor | None = None
_originals: list[tuple[Any, str, Any]] = []


def _wrap(cls: Any, method: str, hook: Any) -> None:
    """Patch ``cls.method`` so ``hook(mon, self, result, *args)`` runs
    after the original (only on success — a raising acquire acquired
    nothing)."""
    original = getattr(cls, method)

    def wrapper(self: Any, *args: Any, **kwargs: Any) -> Any:
        result = original(self, *args, **kwargs)
        mon = _active
        if mon is not None:
            hook(mon, self, result, *args, **kwargs)
        return result

    wrapper.__name__ = method
    wrapper.__wrapped__ = original  # type: ignore[attr-defined]
    _originals.append((cls, method, original))
    setattr(cls, method, wrapper)


def install() -> LeakTraceMonitor:
    """Instrument the serving reclaim surfaces; returns the monitor.
    Raises if already installed (a nested install's uninstall would
    strip the outer tier's instrumentation)."""
    global _active
    if _active is not None:
        raise LeakTraceError("leaktrace already installed")
    from gofr_tpu.native import runtime as native_runtime
    from gofr_tpu.serving import kv_cache, timeline

    mon = LeakTraceMonitor()

    for cls in (native_runtime.BlockAllocator, native_runtime.Scheduler):
        name = cls.__name__
        _wrap(cls, "__init__", lambda m, self, _r, *a, _n=name, **k:
              m.on_acquire("native-wrapper", _n, id(self)))
        _wrap(cls, "close", lambda m, self, _r, *a, **k:
              m.on_release("native-wrapper", "close", id(self)))
        _wrap(cls, "leak", lambda m, self, _r, *a, **k:
              m.on_release("native-wrapper", "leak", id(self)))

    _wrap(native_runtime.BlockAllocator, "alloc",
          lambda m, self, _r, seq_id, *a, **k:
          m.on_acquire("kv-seq", "alloc", (id(self), seq_id)))
    _wrap(native_runtime.BlockAllocator, "free",
          lambda m, self, _r, seq_id, *a, **k:
          m.on_release("kv-seq", "free", (id(self), seq_id)))

    def _slot_acquire(name: str) -> Any:
        def hook(m: LeakTraceMonitor, self: Any, result: Any,
                 slot: Any = None, *a: Any, **k: Any) -> None:
            if result is False:
                return  # try_reserve_* refused: nothing acquired
            key = slot if slot is not None else k.get("slot")
            if isinstance(key, list):  # try_reserve_chunk takes a list
                for s in key:
                    m.on_acquire("kv-slot", name, (id(self), s))
            else:
                m.on_acquire("kv-slot", name, (id(self), key))
        return hook

    _wrap(kv_cache.PagedKVCache, "alloc_slot", _slot_acquire("alloc_slot"))
    _wrap(kv_cache.PagedKVCache, "try_reserve_slot",
          _slot_acquire("try_reserve_slot"))
    _wrap(kv_cache.PagedKVCache, "try_reserve_chunk",
          _slot_acquire("try_reserve_chunk"))
    _wrap(kv_cache.PagedKVCache, "free_slot",
          lambda m, self, _r, slot, *a, **k:
          m.on_release("kv-slot", "free_slot", (id(self), slot)))

    _wrap(timeline.TimelineRecorder, "begin",
          lambda m, self, result, request_id, *a, **k:
          m.on_acquire("timeline", "begin", (id(self), request_id)))
    _wrap(timeline.TimelineRecorder, "finish",
          lambda m, self, _r, tl, *a, **k:
          m.on_release("timeline", "finish", (id(self), tl.request_id)))

    _active = mon
    return mon


def uninstall() -> LeakTraceMonitor | None:
    """Restore the original methods; already-created instances keep
    working (instance state was never touched)."""
    global _active
    for cls, method, original in reversed(_originals):
        setattr(cls, method, original)
    _originals.clear()
    mon, _active = _active, None
    return mon


def export_to(mon: LeakTraceMonitor, path: str) -> None:
    """Merge-write the monitor's observed pairs into ``path`` (several
    chaos tests append to one ``GOFR_LEAK_EXPORT`` file; the union is
    what the static coverage check consumes)."""
    data = mon.export()
    try:
        with open(path, encoding="utf-8") as fp:
            prior = json.load(fp)
    except (OSError, ValueError):
        prior = {}
    seen = {
        (e.get("kind"), e.get("op"), e.get("name"))
        for e in prior.get("events", ())
    }
    events = list(prior.get("events", ()))
    for e in data["events"]:
        if (e["kind"], e["op"], e["name"]) not in seen:
            events.append(e)
    payload = {
        "version": 1,
        "events": sorted(
            events, key=lambda e: (e["kind"], e["op"], e["name"])
        ),
        "unreclaimed": sorted(
            set(prior.get("unreclaimed", ())) | set(data["unreclaimed"])
        ),
    }
    with open(path, "w", encoding="utf-8") as fp:
        json.dump(payload, fp, indent=2)
        fp.write("\n")
